"""Thread-entrypoint index + lock-region tracking for lockcheck.

One :class:`ConcurrencyIndex` per module answers, purely syntactically,
the three questions every lockcheck rule needs:

  1. **Which execution context runs this function?** Entry points are
     classified from the idioms the serve stack actually uses —
     ``threading.Thread(target=f)`` / ``Thread`` subclass ``run``
     (context ``thread``), ``do_*`` methods of a
     ``BaseHTTPRequestHandler`` subclass (``handler``), ``async def``
     (``asyncio``), ``run_in_executor``/``Executor.submit`` targets
     (``executor``), ``threading.Timer`` callbacks (``timer``) — and
     propagated through the per-module call graph, so a helper called
     from a handler inherits ``handler``. Everything unreached is
     ``main``: the driving thread (bench loops, tests, module setup).
     Closures handed to a ``.call(...)`` marshal (the EngineLoop seam
     that runs ``fn(engine)`` ON the loop thread) classify as
     ``thread`` — the marshal is the blessed way to touch loop-owned
     state, and the index must not mistake it for the caller's context.

  2. **Which locks are held at each statement?** ``with self._lock:``
     regions tracked lexically, nested ``with`` accumulating in
     acquisition order. A lock is an attribute/name assigned
     ``threading.Lock/RLock/Condition/Semaphore`` in the module, or a
     ``with`` subject whose trailing name segment is lock-ish
     (``_lock``, ``_cond``, ``_mutex``); ids qualify by class
     (``EngineLoop._cond``) so the committed ordering file can name
     them.

  3. **Which attributes are declared guarded?** ``# guarded-by:
     <lock>`` trailing an attribute assignment declares its guarding
     lock; rules enforce every later access holds it.

Pure ast + tokenize: no jax, no imports of the analyzed code.
"""

from __future__ import annotations

import ast
import re
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from typing import Dict, List, Optional, Set, Tuple

# The execution contexts the serve stack actually has (ISSUE 18): the
# engine stepping thread, stdlib HTTP handler threads, the asyncio
# router event loop, its executor pools, timer callbacks, and the main
# driving thread (bench/step loops, tests).
CONTEXTS = ("thread", "handler", "asyncio", "executor", "timer", "main")

_GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_.]*)")

# `with self.X:` subjects whose trailing _-segment matches are treated
# as locks even without a visible threading.* assignment (a lock built
# by a base class or another module). "clock" does NOT match: the
# segment is "clock", not "lock".
_LOCKISH_SEGMENTS = {"lock", "rlock", "cond", "condition", "mutex",
                     "sem", "semaphore"}

_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore",
               "BoundedSemaphore"}

# Callables that run their function argument on another thread/loop.
# name -> (context, positional index of the callee argument or None
# for "target=" keyword).
_DISPATCHERS = {
    "run_in_executor": ("executor", 1),
    "submit": ("executor", 0),          # concurrent.futures Executor
    "call_soon": ("asyncio", 0),
    "call_soon_threadsafe": ("asyncio", 0),
    "call_later": ("asyncio", 1),
    "call": ("thread", 0),              # EngineLoop.call marshal seam
}


def _last_segment(name: str) -> str:
    return name.rsplit("_", 1)[-1].lower()


def _is_lockish_name(name: str) -> bool:
    return _last_segment(name) in _LOCKISH_SEGMENTS


def _dotted(node: ast.AST) -> Optional[str]:
    """'a.b.c' for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


@dataclass
class LockSite:
    """One lock acquisition (a ``with`` entry)."""
    lock: str                      # qualified id, e.g. "EngineLoop._cond"
    line: int
    held: Tuple[str, ...]          # locks already held, outermost first


@dataclass
class AttrWrite:
    attr: str
    line: int
    held: Tuple[str, ...]
    in_init: bool


@dataclass
class AttrAccess:
    """Any ``self.attr`` use (read, write, or method call on it)."""
    attr: str
    line: int
    held: Tuple[str, ...]
    is_write: bool
    in_init: bool


@dataclass
class CallSite:
    callee: str                    # simple name
    line: int
    held: Tuple[str, ...]
    via_self: bool                 # spelled self.callee(...)
    awaited: bool = False
    in_lambda: bool = False


@dataclass
class RawAcquire:
    """An explicit ``X.acquire()`` call (not a ``with``)."""
    lock: str
    line: int
    released_in_finally: bool


@dataclass
class FunctionInfo:
    name: str
    qualname: str                  # "Class.method" or "fn" or "fn.<inner>"
    cls: Optional[str]
    node: ast.AST
    is_async: bool
    lineno: int
    entry: Set[str] = field(default_factory=set)
    contexts: Set[str] = field(default_factory=set)
    calls: List[CallSite] = field(default_factory=list)
    acquires: List[LockSite] = field(default_factory=list)
    raw_acquires: List[RawAcquire] = field(default_factory=list)
    writes: List[AttrWrite] = field(default_factory=list)
    accesses: List[AttrAccess] = field(default_factory=list)


class ConcurrencyIndex:
    """Per-module concurrency model: functions, contexts, lock regions,
    guarded-by declarations, and the acquired-while-holding graph."""

    def __init__(self, tree: ast.Module, source: str = ""):
        self.tree = tree
        self.functions: Dict[str, FunctionInfo] = {}   # by qualname
        self.by_name: Dict[str, List[FunctionInfo]] = {}
        self.class_bases: Dict[str, List[str]] = {}
        # class name (or "" for module level) -> set of lock attr names
        self.lock_attrs: Dict[str, Set[str]] = {}
        # (class, attr) -> declared guarding lock name
        self.guarded_by: Dict[Tuple[str, str], str] = {}
        # (context, target name) dispatch marks seen while analyzing —
        # applied AFTER collection, because the target def (e.g. a
        # nested `run` handed to threading.Thread) may not be collected
        # yet when its dispatcher is analyzed.
        self._pending_marks: List[Tuple[str, str]] = []
        self._guard_comments = self._parse_guard_comments(source)
        self._collect(tree)
        for ctx, name in self._pending_marks:
            for fi in self.by_name.get(name, []):
                fi.entry.add(ctx)
        self._classify_entries()
        self._propagate_contexts()

    # ------------------------------------------------------- collection
    @staticmethod
    def _parse_guard_comments(source: str) -> Dict[int, str]:
        """line -> lock name for every ``# guarded-by: X`` comment."""
        out: Dict[int, str] = {}
        if not source:
            return out
        try:
            toks = tokenize.generate_tokens(StringIO(source).readline)
            for tok in toks:
                if tok.type != tokenize.COMMENT:
                    continue
                m = _GUARDED_BY_RE.search(tok.string)
                if m:
                    out[tok.start[0]] = m.group(1)
        except (tokenize.TokenError, IndentationError, SyntaxError):
            pass
        return out

    def _collect(self, tree: ast.Module) -> None:
        # First sweep: classes, bases, lock constructions, guarded-by.
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                bases = []
                for b in node.bases:
                    d = _dotted(b)
                    if d:
                        bases.append(d)
                self.class_bases[node.name] = bases
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                ctor = _dotted(node.value.func) or ""
                if ctor.split(".")[-1] in _LOCK_CTORS and (
                        "threading" in ctor or "." not in ctor):
                    for tgt in node.targets:
                        if (isinstance(tgt, ast.Attribute)
                                and isinstance(tgt.value, ast.Name)
                                and tgt.value.id == "self"):
                            cls = self._class_of(tree, node)
                            self.lock_attrs.setdefault(
                                cls or "", set()).add(tgt.attr)
                        elif isinstance(tgt, ast.Name):
                            self.lock_attrs.setdefault(
                                "", set()).add(tgt.id)
        # Guarded-by declarations: comment on a `self.attr = ...` line.
        for node in ast.walk(tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                lock = self._guard_comments.get(node.lineno)
                if lock is None:
                    continue
                targets = (node.targets
                           if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    if (isinstance(tgt, ast.Attribute)
                            and isinstance(tgt.value, ast.Name)
                            and tgt.value.id == "self"):
                        cls = self._class_of(tree, node) or ""
                        self.guarded_by[(cls, tgt.attr)] = lock
        # Second sweep: functions (module level, methods, nested).
        self._walk_defs(tree.body, cls=None, prefix="")

    def _class_of(self, tree: ast.Module,
                  node: ast.AST) -> Optional[str]:
        # Cheap enclosing-class lookup by line span.
        best = None
        for cd in ast.walk(tree):
            if isinstance(cd, ast.ClassDef):
                end = getattr(cd, "end_lineno", cd.lineno)
                if cd.lineno <= node.lineno <= end:
                    if best is None or cd.lineno > best.lineno:
                        best = cd
        return best.name if best else None

    def _walk_defs(self, body, cls: Optional[str], prefix: str) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = (f"{cls}." if cls else "") + prefix + stmt.name
                info = FunctionInfo(
                    name=stmt.name, qualname=qual, cls=cls, node=stmt,
                    is_async=isinstance(stmt, ast.AsyncFunctionDef),
                    lineno=stmt.lineno)
                self.functions[qual] = info
                self.by_name.setdefault(stmt.name, []).append(info)
                self._analyze_function(info)
                self._walk_defs(stmt.body, cls=cls,
                                prefix=f"{prefix}{stmt.name}.")
            elif isinstance(stmt, ast.ClassDef):
                self._walk_defs(stmt.body, cls=stmt.name, prefix="")
            elif isinstance(stmt, (ast.If, ast.Try, ast.With,
                                   ast.AsyncWith, ast.For, ast.AsyncFor,
                                   ast.While)):
                # defs declared under a conditional/with/loop still
                # belong to this scope.
                for sub_body in (getattr(stmt, "body", []),
                                 getattr(stmt, "orelse", []),
                                 getattr(stmt, "finalbody", [])):
                    self._walk_defs(sub_body, cls=cls, prefix=prefix)
                for h in getattr(stmt, "handlers", []):
                    self._walk_defs(h.body, cls=cls, prefix=prefix)

    # -------------------------------------------- per-function analysis
    def lock_id(self, expr: ast.AST, cls: Optional[str]) -> Optional[str]:
        """Qualified lock id for a ``with`` subject / acquire receiver,
        or None when the expression is not lock-like."""
        if isinstance(expr, ast.Attribute):
            attr = expr.attr
            base = expr.value
            known = False
            if isinstance(base, ast.Name) and base.id == "self":
                known = attr in self.lock_attrs.get(cls or "", set())
                owner = cls or "self"
            else:
                owner = _dotted(base) or "*"
                known = any(attr in s for s in self.lock_attrs.values())
            if known or _is_lockish_name(attr):
                return f"{owner}.{attr}"
            return None
        if isinstance(expr, ast.Name):
            if (expr.id in self.lock_attrs.get("", set())
                    or _is_lockish_name(expr.id)):
                return expr.id
        return None

    def _analyze_function(self, info: FunctionInfo) -> None:
        in_init = info.name == "__init__"

        def scan_stmt(stmt: ast.stmt, held: Tuple[str, ...],
                      finally_releases: Tuple[frozenset, ...]) -> None:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return                      # nested defs analyzed separately
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                new_held = held
                for item in stmt.items:
                    lid = self.lock_id(item.context_expr, info.cls)
                    if lid is not None:
                        info.acquires.append(LockSite(
                            lock=lid, line=item.context_expr.lineno,
                            held=new_held))
                        new_held = new_held + (lid,)
                    else:
                        scan_expr(item.context_expr, held,
                                  finally_releases)
                for s in stmt.body:
                    scan_stmt(s, new_held, finally_releases)
                return
            if isinstance(stmt, ast.Try):
                released = set()
                for f in stmt.finalbody:
                    for node in ast.walk(f):
                        if (isinstance(node, ast.Call)
                                and isinstance(node.func, ast.Attribute)
                                and node.func.attr == "release"):
                            lid = self.lock_id(node.func.value, info.cls)
                            if lid:
                                released.add(lid)
                inner = finally_releases + (frozenset(released),)
                for s in stmt.body:
                    scan_stmt(s, held, inner)
                for h in stmt.handlers:
                    for s in h.body:
                        scan_stmt(s, held, inner)
                for s in stmt.orelse:
                    scan_stmt(s, held, inner)
                for s in stmt.finalbody:
                    scan_stmt(s, held, finally_releases)
                return
            if isinstance(stmt, (ast.If, ast.For, ast.AsyncFor,
                                 ast.While)):
                scan_expr(getattr(stmt, "test", None)
                          or getattr(stmt, "iter", None),
                          held, finally_releases)
                for s in list(stmt.body) + list(stmt.orelse):
                    scan_stmt(s, held, finally_releases)
                return
            # Plain statement: scan every expression in it.
            scan_expr(stmt, held, finally_releases)

        def scan_expr(node, held: Tuple[str, ...],
                      finally_releases: Tuple[frozenset, ...],
                      in_lambda: bool = False) -> None:
            # Recursive (not ast.walk): Await/Lambda must PRUNE so the
            # wrapped call is recorded exactly once, with its flag.
            if node is None:
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                return
            if isinstance(node, ast.Lambda):
                # Lambda bodies run when (and where) the lambda is
                # called — mark their calls so context-sensitive rules
                # (asyncio-blocking-call) can skip executor thunks.
                scan_expr(node.body, held, finally_releases,
                          in_lambda=True)
                return
            if isinstance(node, ast.Await):
                if isinstance(node.value, ast.Call):
                    call = node.value
                    self._record_call(info, call, held, awaited=True,
                                      in_lambda=in_lambda,
                                      finally_releases=finally_releases)
                    for sub in (list(call.args)
                                + [kw.value for kw in call.keywords]):
                        scan_expr(sub, held, finally_releases, in_lambda)
                    scan_expr(call.func, held, finally_releases,
                              in_lambda)
                else:
                    scan_expr(node.value, held, finally_releases,
                              in_lambda)
                return
            if isinstance(node, ast.Call):
                self._record_call(info, node, held, in_lambda=in_lambda,
                                  finally_releases=finally_releases)
            if isinstance(node, (ast.Assign, ast.AugAssign,
                                 ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for tgt in targets:
                    attr = self._self_attr_target(tgt)
                    if attr is not None:
                        info.writes.append(AttrWrite(
                            attr=attr, line=node.lineno, held=held,
                            in_init=in_init))
            if isinstance(node, ast.Attribute) and isinstance(
                    node.value, ast.Name) and node.value.id == "self":
                is_store = isinstance(node.ctx, (ast.Store, ast.Del))
                info.accesses.append(AttrAccess(
                    attr=node.attr, line=node.lineno, held=held,
                    is_write=is_store, in_init=in_init))
            for child in ast.iter_child_nodes(node):
                scan_expr(child, held, finally_releases, in_lambda)

        for s in info.node.body:
            scan_stmt(s, (), ())

    @staticmethod
    def _self_attr_target(tgt: ast.AST) -> Optional[str]:
        """'attr' for self.attr / self.attr[k] assignment targets."""
        if isinstance(tgt, ast.Subscript):
            tgt = tgt.value
        if (isinstance(tgt, ast.Attribute)
                and isinstance(tgt.value, ast.Name)
                and tgt.value.id == "self"):
            return tgt.attr
        if isinstance(tgt, (ast.Tuple, ast.List)):
            for e in tgt.elts:
                a = ConcurrencyIndex._self_attr_target(e)
                if a is not None:
                    return a
        return None

    def _record_call(self, info: FunctionInfo, call: ast.Call,
                     held: Tuple[str, ...], *, in_lambda: bool = False,
                     awaited: bool = False,
                     finally_releases: Tuple[frozenset, ...] = ()
                     ) -> None:
        name = None
        via_self = False
        if isinstance(call.func, ast.Name):
            name = call.func.id
        elif isinstance(call.func, ast.Attribute):
            name = call.func.attr
            via_self = (isinstance(call.func.value, ast.Name)
                        and call.func.value.id == "self")
            if name == "acquire":
                lid = self.lock_id(call.func.value, info.cls)
                if lid is not None:
                    released = any(lid in s for s in finally_releases)
                    info.raw_acquires.append(RawAcquire(
                        lock=lid, line=call.lineno,
                        released_in_finally=released))
        if name is None:
            return
        info.calls.append(CallSite(callee=name, line=call.lineno,
                                   held=held, via_self=via_self,
                                   awaited=awaited, in_lambda=in_lambda))
        # Dispatcher idioms register their callee argument as an
        # entry point in another context.
        if name in _DISPATCHERS or name == "Thread" or name == "Timer":
            self._mark_dispatch(info, call, name)

    def _mark_dispatch(self, info: FunctionInfo, call: ast.Call,
                       name: str) -> None:
        def target_names(arg) -> List[str]:
            if isinstance(arg, ast.Name):
                return [arg.id]
            if isinstance(arg, ast.Attribute):
                return [arg.attr]
            if isinstance(arg, ast.Lambda):
                return []          # calls inside already marked in_lambda
            return []

        ctx = None
        cands: List[str] = []
        if name in ("Thread", "Timer"):
            ctx = "thread" if name == "Thread" else "timer"
            for kw in call.keywords:
                if kw.arg in ("target", "function"):
                    cands += target_names(kw.value)
            if name == "Timer" and len(call.args) >= 2:
                cands += target_names(call.args[1])
        else:
            ctx, pos = _DISPATCHERS[name]
            if len(call.args) > pos:
                cands += target_names(call.args[pos])
        for cand in cands:
            self._pending_marks.append((ctx, cand))

    # ---------------------------------------------------- classification
    def _bases_match(self, cls: str, needle: str) -> bool:
        for b in self.class_bases.get(cls, []):
            if needle in b:
                return True
        return False

    def _classify_entries(self) -> None:
        for info in self.functions.values():
            if info.is_async:
                info.entry.add("asyncio")
            if info.cls:
                if (info.name == "run"
                        and self._bases_match(info.cls, "Thread")):
                    info.entry.add("thread")
                if (info.name.startswith("do_")
                        and (self._bases_match(info.cls,
                                               "BaseHTTPRequestHandler")
                             or self._bases_match(info.cls,
                                                  "RequestHandler"))):
                    info.entry.add("handler")

    def _propagate_contexts(self) -> None:
        for info in self.functions.values():
            info.contexts = set(info.entry)
        changed = True
        while changed:
            changed = False
            for info in self.functions.values():
                src = info.contexts or {"main"}
                for call in info.calls:
                    for callee in self._resolve(info, call):
                        before = len(callee.contexts)
                        callee.contexts |= src
                        if len(callee.contexts) != before:
                            changed = True
        for info in self.functions.values():
            if not info.contexts:
                info.contexts = {"main"}

    def _resolve(self, caller: FunctionInfo,
                 call: CallSite) -> List[FunctionInfo]:
        cands = self.by_name.get(call.callee, [])
        if not cands:
            return []
        if call.via_self and caller.cls:
            same = [c for c in cands if c.cls == caller.cls]
            if same:
                return same
        return cands

    # ------------------------------------------------- derived relations
    def transitive_acquires(self) -> Dict[str, Set[str]]:
        """qualname -> every lock the function may acquire, including
        through same-module callees (fixpoint over the call graph)."""
        acq = {q: {a.lock for a in f.acquires}
               for q, f in self.functions.items()}
        changed = True
        while changed:
            changed = False
            for q, f in self.functions.items():
                for call in f.calls:
                    for callee in self._resolve(f, call):
                        extra = acq[callee.qualname] - acq[q]
                        if extra:
                            acq[q] |= extra
                            changed = True
        return acq

    def lock_edges(self) -> List[Tuple[str, str, str, int]]:
        """Acquired-while-holding edges: (held, acquired, file-qualname,
        line) — direct ``with`` nesting plus calls under a lock into
        functions that acquire."""
        edges: List[Tuple[str, str, str, int]] = []
        acq = self.transitive_acquires()
        for q, f in self.functions.items():
            for site in f.acquires:
                for h in site.held:
                    edges.append((h, site.lock, q, site.line))
            for call in f.calls:
                if not call.held:
                    continue
                for callee in self._resolve(f, call):
                    for lid in acq[callee.qualname]:
                        for h in call.held:
                            if h != lid:
                                edges.append((h, lid, q, call.line))
        return edges
