"""lockcheck framework: registry, suppressions, lock order, reports.

Same shape as the jaxlint framework (``analysis/core.py``) and reusing
its :class:`Finding`/:class:`Suppression` machinery, but a separate
tool: its own ``# lockcheck: disable=<rule> -- <why>`` comment tag, its
own rule registry, and one extra input — the committed lock-ordering
file (``budgets/lock_order.json``), the concurrency analogue of
shardcheck's committed collective budgets. Pure ast + stdlib; no jax.
"""

from __future__ import annotations

import json
import re
import ast
import tokenize
from dataclasses import dataclass, field
from io import StringIO
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from nanosandbox_tpu.analysis.core import (Finding, Suppression,
                                           _suppression_for,
                                           iter_python_files)
from nanosandbox_tpu.analysis.lockcheck.contexts import ConcurrencyIndex

JSON_SCHEMA_VERSION = 1

# Spelled without the leading hash so this comment is not itself a
# suppression: `lockcheck: disable=blocking-under-lock -- why`.
_SUPPRESS_RE = re.compile(
    r"#\s*lockcheck:\s*disable=([A-Za-z0-9_,\- ]+?)\s*(?:--\s*(.*?))?\s*$")

# Default location of the committed lock ordering, relative to repo
# root (the CLI also takes --lock-order=PATH).
DEFAULT_LOCK_ORDER = "budgets/lock_order.json"


@dataclass
class LockOrder:
    """The canonical acquisition order: tiers, earliest-first, and the
    qualified lock ids pinned to each tier. Acquiring a lock in an
    EARLIER tier while holding one from a LATER tier inverts the order;
    intra-tier nesting is allowed (it cannot deadlock against the
    committed order, and the inversion rule's cycle check still catches
    genuine intra-tier cycles)."""
    tiers: Tuple[str, ...] = ()
    locks: Dict[str, str] = field(default_factory=dict)  # lock id -> tier

    def tier_index(self, lock: str) -> Optional[int]:
        tier = self.locks.get(lock)
        if tier is None:
            return None
        try:
            return self.tiers.index(tier)
        except ValueError:
            return None


def load_lock_order(path: str) -> LockOrder:
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    tiers = tuple(data.get("order", ()))
    locks = dict(data.get("locks", {}))
    for lock, tier in locks.items():
        if tier not in tiers:
            raise ValueError(
                f"lock {lock!r} pinned to unknown tier {tier!r}; "
                f"order file declares {list(tiers)}")
    return LockOrder(tiers=tiers, locks=locks)


@dataclass
class ModuleContext:
    """Everything a lockcheck rule needs about one source file."""
    path: str
    source: str
    tree: ast.Module
    conc: ConcurrencyIndex
    lines: List[str] = field(default_factory=list)
    lock_order: Optional[LockOrder] = None


class Rule:
    """Base class: subclasses set ``id``/``doc`` and implement check()."""

    id: str = ""
    doc: str = ""

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        raise NotImplementedError


_REGISTRY: Dict[str, Rule] = {}


def register(rule_cls: Callable[[], Rule]):
    rule = rule_cls()
    if not rule.id:
        raise ValueError(f"rule {rule_cls.__name__} has no id")
    if rule.id in _REGISTRY:
        raise ValueError(f"duplicate rule id {rule.id!r}")
    _REGISTRY[rule.id] = rule
    return rule_cls


_BUILTINS_LOADED = False


def all_rules() -> Dict[str, Rule]:
    global _BUILTINS_LOADED
    if not _BUILTINS_LOADED:
        _BUILTINS_LOADED = True
        from nanosandbox_tpu.analysis.lockcheck import rules  # noqa: F401
    return dict(_REGISTRY)


def parse_suppressions(source: str) -> List[Suppression]:
    """Extract ``# lockcheck: disable=...`` comments via tokenize (a
    'lockcheck:' inside a string literal must not suppress)."""
    out: List[Suppression] = []
    try:
        tokens = list(tokenize.generate_tokens(StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = tuple(r.strip() for r in m.group(1).split(",") if r.strip())
        reason = (m.group(2) or "").strip()
        standalone = tok.line.strip().startswith("#")
        out.append(Suppression(line=tok.start[0], rules=rules,
                               reason=reason, standalone=standalone))
    return out


def analyze_source(source: str, path: str = "<string>",
                   select: Optional[Sequence[str]] = None,
                   strict_suppressions: bool = False,
                   lock_order: Optional[LockOrder] = None,
                   ) -> Tuple[List[Finding], int]:
    """Lint one source string. Returns (findings, suppressed_count).

    Suppression semantics match jaxlint exactly: reasons are mandatory
    (a bare disable is void AND a bad-suppression finding), a
    standalone comment covers the next statement if only comments and
    blanks sit between, and reasoned suppressions that no longer match
    are reported as unused (promoted to findings under
    ``strict_suppressions``).
    """
    rules = all_rules()
    if select:
        unknown = sorted(set(select) - set(rules))
        if unknown:
            raise ValueError(f"unknown rule id(s): {', '.join(unknown)}; "
                             f"known: {', '.join(sorted(rules))}")
        rules = {k: v for k, v in rules.items() if k in select}

    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        return [Finding(path, e.lineno or 1, e.offset or 0, "parse-error",
                        f"could not parse: {e.msg}")], 0

    ctx = ModuleContext(path=path, source=source, tree=tree,
                        conc=ConcurrencyIndex(tree, source),
                        lines=source.splitlines(), lock_order=lock_order)
    raw: List[Finding] = []
    for rule in rules.values():
        raw.extend(rule.check(ctx))

    suppressions = parse_suppressions(source)
    findings: List[Finding] = []
    suppressed = 0
    for f in sorted(set(raw), key=lambda f: f.key()):
        s = _suppression_for(suppressions, f, ctx.lines)
        if s is None:
            findings.append(f)
        elif not s.reason:
            s.used = True
            findings.append(f)
        else:
            s.used = True
            suppressed += 1
    known = set(all_rules()) | {"all", "parse-error", "bad-suppression",
                                "unused-suppression"}
    for s in suppressions:
        if not s.reason:
            findings.append(Finding(
                path, s.line, 0, "bad-suppression",
                "suppression without a reason — write "
                "'# lockcheck: disable=<rule> -- <why this is "
                "deliberate>'"))
        for r in s.rules:
            if r not in known:
                findings.append(Finding(
                    path, s.line, 0, "bad-suppression",
                    f"unknown rule id {r!r} in suppression — known: "
                    f"{', '.join(sorted(set(all_rules())))}"))
        if (s.reason and not s.used
                and (select is None
                     or ("all" not in s.rules
                         and all(r in select for r in s.rules)))):
            _UNUSED_LOG.append({
                "file": path, "line": s.line,
                "rules": list(s.rules), "reason": s.reason})
            if strict_suppressions:
                findings.append(Finding(
                    path, s.line, 0, "unused-suppression",
                    f"suppression for {', '.join(s.rules)} no longer "
                    "matches any finding — the audited violation is "
                    "gone; delete the comment (reason was: "
                    f"{s.reason!r})"))
    return sorted(set(findings), key=lambda f: f.key()), suppressed


_UNUSED_LOG: List[dict] = []


def drain_unused_suppressions() -> List[dict]:
    out, _UNUSED_LOG[:] = list(_UNUSED_LOG), []
    return out


def analyze_paths(paths: Sequence[str],
                  select: Optional[Sequence[str]] = None,
                  strict_suppressions: bool = False,
                  lock_order: Optional[LockOrder] = None) -> dict:
    """Lint files/directories; returns the report dict render_json dumps."""
    findings: List[Finding] = []
    suppressed = 0
    drain_unused_suppressions()
    files = iter_python_files(paths)
    for f in files:
        try:
            src = f.read_text(encoding="utf-8")
        except (OSError, UnicodeDecodeError) as e:
            findings.append(Finding(str(f), 1, 0, "parse-error",
                                    f"could not read: {e}"))
            continue
        fs, sup = analyze_source(src, str(f), select=select,
                                 strict_suppressions=strict_suppressions,
                                 lock_order=lock_order)
        findings.extend(fs)
        suppressed += sup
    by_rule: Dict[str, int] = {}
    for f in findings:
        by_rule[f.rule] = by_rule.get(f.rule, 0) + 1
    return {
        "version": JSON_SCHEMA_VERSION,
        "tool": "lockcheck",
        "findings": [vars(f) for f in findings],
        "unused_suppressions": drain_unused_suppressions(),
        "summary": {
            "files_scanned": len(files),
            "findings": len(findings),
            "suppressed": suppressed,
            "by_rule": dict(sorted(by_rule.items())),
        },
    }


def render_text(report: dict) -> str:
    lines = [f"{f['file']}:{f['line']}:{f['col']}: {f['rule']}: "
             f"{f['message']}" for f in report["findings"]]
    unused = report.get("unused_suppressions", [])
    lines.extend(
        f"{u['file']}:{u['line']}: note: unused suppression for "
        f"{', '.join(u['rules'])} (use --strict-suppressions to fail "
        "on these)" for u in unused)
    s = report["summary"]
    lines.append(f"lockcheck: {s['findings']} finding(s) in "
                 f"{s['files_scanned']} file(s), "
                 f"{s['suppressed']} suppressed"
                 + (f", {len(unused)} unused suppression(s)" if unused
                    else ""))
    return "\n".join(lines)


def render_json(report: dict) -> str:
    return json.dumps(report, indent=2, sort_keys=False)
