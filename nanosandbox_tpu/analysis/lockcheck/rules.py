"""lockcheck rules: the five concurrency contracts of the serve host.

Each rule reads the per-module :class:`ConcurrencyIndex` — execution
contexts per function, held-lock sets per statement, guarded-by
declarations — and emits findings. The contracts, in order:

- **unguarded-shared-write**: an attribute written from two or more
  execution contexts must have a common lock held at every write (or a
  ``# guarded-by:`` declaration it honors everywhere).
- **lock-order-inversion**: the acquired-while-holding graph must stay
  acyclic, and must respect the committed tier ordering in
  ``budgets/lock_order.json`` when one is loaded.
- **blocking-under-lock**: no host sync, device readback, network or
  file I/O, sleeps, or joins while holding a lock — a blocked holder
  stalls every contending thread (the PR 11 watchdog race was exactly
  this shape).
- **asyncio-blocking-call**: coroutines must route sync I/O through
  ``run_in_executor``; a direct call stalls the whole event loop.
- **leaked-acquire**: a bare ``.acquire()`` needs a try/finally that
  releases the same lock; otherwise an exception leaks the lock and
  every later contender deadlocks. ``with`` is always preferred.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set, Tuple

from nanosandbox_tpu.analysis.core import Finding
from nanosandbox_tpu.analysis.lockcheck.core import (ModuleContext, Rule,
                                                     register)

# Call names that block the calling thread: device readbacks / host
# syncs (the jaxlint vocabulary), network and file I/O, sleeps, joins.
# Matched on the trailing name of the call, so `time.sleep`, `urllib
# .request.urlopen`, and bare `sleep` all hit.
_BLOCKING_CALLS = {
    "sleep": "time.sleep",
    "host_sync": "host sync (device readback barrier)",
    "block_until_ready": "device readback",
    "device_get": "device readback",
    "urlopen": "network I/O",
    "getaddrinfo": "network I/O",
    "create_connection": "network I/O",
    "recv": "socket read",
    "sendall": "socket write",
    "connect": "socket connect",
    "check_call": "subprocess",
    "check_output": "subprocess",
    "run": None,          # subprocess.run only when spelled dotted — see below
    "join": "thread/queue join",
    "makedirs": "filesystem I/O",
    "mkdtemp": "filesystem I/O",
}

# For ambiguous trailing names, require the dotted prefix to confirm.
_REQUIRE_PREFIX = {
    "run": ("subprocess",),
    "join": ("thread", "_thread", "pool", "_pool", "proc", "_proc",
             "worker", "_worker", "queue", "_queue", "t", "th"),
    "connect": ("sock", "socket", "s", "conn"),
    "recv": ("sock", "socket", "s", "conn"),
    "sendall": ("sock", "socket", "s", "conn"),
}

# Sync file I/O that only counts inside async def (handlers and the
# loop thread legitimately write dumps; the event loop must not).
_ASYNC_ONLY_BLOCKING = {"open": "file I/O", "read_text": "file I/O",
                        "write_text": "file I/O"}


def _blocking_kind(callee: str, receiver: str) -> str:
    """Human label when (callee, receiver prefix) is a blocking call,
    else ''. receiver is the dotted expression before the final attr
    ('' for bare names)."""
    if callee not in _BLOCKING_CALLS:
        return ""
    need = _REQUIRE_PREFIX.get(callee)
    if need is not None:
        # Exact match on the receiver's trailing name only: `os.path
        # .join` must NOT satisfy the "join" blocking pattern.
        base = receiver.split(".")[-1].lower() if receiver else ""
        if base not in need:
            return ""
    label = _BLOCKING_CALLS[callee]
    if label is None:
        return f"{receiver}.{callee}" if receiver else callee
    return label


@register
class UnguardedSharedWrite(Rule):
    id = "unguarded-shared-write"
    doc = ("attribute written from two or more execution contexts with "
           "no common lock held at every write, or accessed without its "
           "declared guarded-by lock")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        conc = ctx.conc
        # (class, attr) -> list of (contexts, held, line, qualname)
        writes: Dict[Tuple[str, str], List] = {}
        for info in conc.functions.values():
            for w in info.writes:
                if w.in_init:
                    continue
                writes.setdefault((info.cls or "", w.attr), []).append(
                    (frozenset(info.contexts), frozenset(w.held),
                     w.line, info.qualname))
        for (cls, attr), sites in sorted(writes.items()):
            declared = conc.guarded_by.get((cls, attr))
            contexts = set()
            for ctxs, _held, _line, _q in sites:
                contexts |= ctxs
            if len(contexts) < 2:
                continue
            common = None
            for _ctxs, held, _line, _q in sites:
                common = held if common is None else common & held
            if common:
                continue          # every write shares a lock — guarded
            if declared:
                # Declared lock: flag only the writes not holding it
                # (the guarded-by enforcement below covers reads too).
                continue
            line = min(l for _c, _h, l, _q in sites)
            where = ", ".join(sorted(contexts))
            yield Finding(
                ctx.path, line, 0, self.id,
                f"'{('%s.' % cls) if cls else ''}{attr}' is written from "
                f"multiple execution contexts ({where}) with no common "
                "lock held at every write — guard the writes with one "
                "lock, marshal them onto one thread, or declare the "
                "single-writer design with '# guarded-by: <lock>' plus "
                "a reasoned suppression")
        # guarded-by enforcement: every non-__init__ access to a
        # declared attribute must hold the declared lock.
        for info in conc.functions.values():
            for a in info.accesses:
                declared = conc.guarded_by.get((info.cls or "", a.attr))
                if declared is None or a.in_init:
                    continue
                want = (f"{info.cls}.{declared}" if info.cls
                        else declared)
                if not any(h == want or h.endswith("." + declared)
                           or h == declared for h in a.held):
                    kind = "written" if a.is_write else "read"
                    yield Finding(
                        ctx.path, a.line, 0, self.id,
                        f"'{a.attr}' is declared '# guarded-by: "
                        f"{declared}' but {kind} here without holding "
                        f"it (in {info.qualname})")


@register
class LockOrderInversion(Rule):
    id = "lock-order-inversion"
    doc = ("cycle in the acquired-while-holding lock graph, or an "
           "acquisition that violates the committed tier ordering")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        edges = ctx.conc.lock_edges()
        # Committed-order check: acquiring a strictly EARLIER tier while
        # holding a LATER one inverts the canonical order.
        order = ctx.lock_order
        seen: Set[Tuple[str, str]] = set()
        if order is not None:
            for held, acquired, qual, line in edges:
                hi = order.tier_index(self._match(order, held))
                ai = order.tier_index(self._match(order, acquired))
                if hi is None or ai is None or ai >= hi:
                    continue
                key = (held, acquired)
                if key in seen:
                    continue
                seen.add(key)
                yield Finding(
                    ctx.path, line, 0, self.id,
                    f"acquires '{acquired}' (tier "
                    f"'{order.tiers[ai]}') while holding '{held}' "
                    f"(tier '{order.tiers[hi]}') — inverts the "
                    "committed lock order "
                    f"{' → '.join(order.tiers)}")
        # Cycle check over the module's own graph (works with no
        # ordering file at all — any A-while-B plus B-while-A pair).
        graph: Dict[str, Set[str]] = {}
        where: Dict[Tuple[str, str], int] = {}
        for held, acquired, _qual, line in edges:
            graph.setdefault(held, set()).add(acquired)
            where.setdefault((held, acquired), line)
        for a in sorted(graph):
            for b in sorted(graph[a]):
                if a in graph.get(b, ()) and a < b:
                    yield Finding(
                        ctx.path, where[(a, b)], 0, self.id,
                        f"lock cycle: '{a}' is acquired while holding "
                        f"'{b}' AND '{b}' while holding '{a}' — two "
                        "threads taking them in opposite orders "
                        "deadlock")

    @staticmethod
    def _match(order, lock_id: str) -> str:
        """Map a module-local lock id onto a committed id: exact match
        first, then by trailing '.attr' (the file pins 'Class.attr';
        call-site ids can be 'self.attr' spelled through a local)."""
        if lock_id in order.locks:
            return lock_id
        attr = lock_id.rsplit(".", 1)[-1]
        cands = [k for k in order.locks if k.rsplit(".", 1)[-1] == attr]
        if len(cands) == 1:
            return cands[0]
        return lock_id


@register
class BlockingUnderLock(Rule):
    id = "blocking-under-lock"
    doc = ("blocking call (host sync, device readback, network/file "
           "I/O, sleep, join) while holding a lock")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        conc = ctx.conc
        # Which functions block at all (transitively, same module)?
        blocks: Dict[str, str] = {}
        for q, info in conc.functions.items():
            for call in info.calls:
                kind = _blocking_kind(call.callee,
                                      self._receiver(ctx, call))
                if kind:
                    blocks[q] = kind
                    break
        changed = True
        while changed:
            changed = False
            for q, info in conc.functions.items():
                if q in blocks:
                    continue
                for call in info.calls:
                    for callee in conc._resolve(info, call):
                        if callee.qualname in blocks:
                            blocks[q] = (f"call into "
                                         f"{callee.qualname} "
                                         f"({blocks[callee.qualname]})")
                            changed = True
                            break
                    if q in blocks:
                        break
        for q, info in conc.functions.items():
            for call in info.calls:
                if not call.held:
                    continue
                kind = _blocking_kind(call.callee,
                                      self._receiver(ctx, call))
                if kind:
                    # cond.wait on the lock you hold is the condition-
                    # variable idiom, not a blocking bug.
                    yield Finding(
                        ctx.path, call.line, 0, self.id,
                        f"{kind} while holding "
                        f"{', '.join(call.held)} (in {q}) — a blocked "
                        "holder stalls every contending thread; move "
                        "the slow work outside the lock region")
                    continue
                for callee in conc._resolve(info, call):
                    if (callee.qualname in blocks
                            and callee.qualname != q):
                        yield Finding(
                            ctx.path, call.line, 0, self.id,
                            f"calls {callee.qualname} "
                            f"({blocks[callee.qualname]}) while "
                            f"holding {', '.join(call.held)} (in {q})"
                            " — move the slow work outside the lock "
                            "region")
                        break

    @staticmethod
    def _receiver(ctx: ModuleContext, call) -> str:
        # CallSite keeps only the trailing name; recover the dotted
        # receiver from the source line (cheap, line-local).
        if 0 < call.line <= len(ctx.lines):
            line = ctx.lines[call.line - 1]
            needle = f".{call.callee}("
            i = line.find(needle)
            if i > 0:
                j = i
                while j > 0 and (line[j - 1].isalnum()
                                 or line[j - 1] in "._"):
                    j -= 1
                return line[j:i]
        return ""


@register
class AsyncioBlockingCall(Rule):
    id = "asyncio-blocking-call"
    doc = ("synchronous blocking call inside an async def not routed "
           "through run_in_executor — stalls the whole event loop")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for q, info in ctx.conc.functions.items():
            if not info.is_async:
                continue
            for call in info.calls:
                if call.awaited or call.in_lambda:
                    # awaited → a coroutine; in a lambda → runs on the
                    # executor thread run_in_executor hands it to.
                    continue
                recv = BlockingUnderLock._receiver(ctx, call)
                kind = (_blocking_kind(call.callee, recv)
                        or _ASYNC_ONLY_BLOCKING.get(call.callee, ""))
                if not kind:
                    continue
                yield Finding(
                    ctx.path, call.line, 0, self.id,
                    f"{kind} called synchronously inside async "
                    f"{q} — blocks the event loop; wrap it in "
                    "loop.run_in_executor(None, ...)")


@register
class LeakedAcquire(Rule):
    id = "leaked-acquire"
    doc = ("lock.acquire() without a with-statement or try/finally "
           "release — an exception between acquire and release leaks "
           "the lock and deadlocks every later contender")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        for q, info in ctx.conc.functions.items():
            for raw in info.raw_acquires:
                if raw.released_in_finally:
                    continue
                yield Finding(
                    ctx.path, raw.line, 0, self.id,
                    f"'{raw.lock}.acquire()' in {q} has no enclosing "
                    "try/finally that releases it — use 'with "
                    f"{raw.lock}:' (or try/finally) so exceptions "
                    "cannot leak the lock")
