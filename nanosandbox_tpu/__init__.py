"""nanosandbox_tpu — a TPU-native distributed training framework.

Rebuilds, idiomatically for JAX/XLA on TPU, the full capability set of the
reference system (fxcawley/nanoSandbox, "DistTrain"): a nanoGPT-equivalent
training core (reference delegated this to karpathy/nanoGPT, cloned at
/root/reference/notebooks/colab_nanoGPT_companion.ipynb:39) plus the
Kubernetes/TPU orchestration shell (reference README.md:18-24).

Layout:
  config     — dataclass config + nanoGPT-style configurator (config file +
               --key=value CLI overrides; reference ipynb:71, 108)
  models/    — decoder-only GPT in flax.linen, bf16 MXU-friendly
  data/      — dataset preparation + memmapped per-host sharded batch loader
  ops/       — Pallas TPU kernels (flash attention) with pure-XLA fallbacks
  parallel/  — jax.sharding Mesh construction, DP/FSDP/TP sharding rules,
               multi-host jax.distributed initialization from pod env
  train      — iter-driven training loop (eval/log intervals, cosine LR,
               checkpoints, TensorBoard scalars)
  sample     — autoregressive generation from a checkpoint
  utils/     — metric writers, tree utilities
"""

__version__ = "0.1.0"
