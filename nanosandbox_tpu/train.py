"""Iter-driven training loop: the nanoGPT train.py contract, TPU-native.

CLI contract (reference ipynb:71-78, 108-115):

    python -m nanosandbox_tpu.train [config/foo.py] --key=value ...

Loop semantics reimplemented from the reference's exercised surface
(SURVEY.md §2.3 #26): iter-driven (max_iters), periodic eval
(eval_interval, eval_iters) and logging (log_interval), cosine LR decay
with warmup (lr_decay_iters, min_lr), AdamW with weight decay on >=2D
params only, global-norm grad clip, checkpoints to out_dir, resume via
--init_from=resume, TensorBoard scalars.

TPU-native structure: ONE jit-compiled train step over a
(data, fsdp, model) mesh — the gradient allreduce that DDP/NCCL did
per-step (SURVEY.md §3.1 hot loop) is an XLA collective inserted by the
SPMD partitioner, riding ICI. Gradient accumulation is a lax.scan inside
the same compiled step. Batches are built per-host and assembled into
global arrays with jax.make_array_from_process_local_data.
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial
from typing import Any

import numpy as np

from nanosandbox_tpu.config import GPTConfig, TrainConfig, load_config
from nanosandbox_tpu.obs import MetricRegistry, SpanTracer
from nanosandbox_tpu.utils import tracecheck

# Peak bf16 FLOP/s per chip for MFU reporting (public spec-sheet numbers).
_PEAK_FLOPS = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,  # v5e
    "TPU v5": 459e12,  # v5p
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,  # v6e / Trillium
    "cpu": 1e12,
}


def _select_platform(device: str) -> None:
    """Map the reference's --device={cpu,cuda} switch (ipynb:77) to JAX.

    Only --device=cpu needs forcing (an accelerator wins by default).
    jax.config wins over env vars even when a site hook pre-selected a
    platform, as long as the backend is not yet initialized.
    """
    if device != "cpu":
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    try:
        jax.config.update("jax_platforms", "cpu")
    except RuntimeError:
        pass  # backend already initialized; caller chose the platform


def make_lr_schedule(cfg: TrainConfig):
    import optax

    if not cfg.decay_lr:
        return cfg.learning_rate
    warmup = optax.linear_schedule(0.0, cfg.learning_rate,
                                   max(cfg.warmup_iters, 1))
    decay_steps = max(cfg.lr_decay_iters - cfg.warmup_iters, 1)
    cosine = optax.cosine_decay_schedule(
        cfg.learning_rate, decay_steps,
        alpha=cfg.min_lr / cfg.learning_rate)
    return optax.join_schedules([warmup, cosine], [cfg.warmup_iters])


def make_optimizer(cfg: TrainConfig):
    import jax
    import optax

    schedule = make_lr_schedule(cfg)
    decay_mask = lambda params: jax.tree.map(lambda p: p.ndim >= 2, params)
    tx = optax.chain(
        optax.clip_by_global_norm(cfg.grad_clip) if cfg.grad_clip > 0
        else optax.identity(),
        optax.adamw(schedule, b1=cfg.beta1, b2=cfg.beta2,
                    weight_decay=cfg.weight_decay, mask=decay_mask),
    )
    return tx, schedule


def restore_for_inference(out_dir: str, *, step: int | None = None,
                          device: str = "auto", **overrides):
    """(trainer, state, step): rebuild a Trainer from a checkpoint's SAVED
    config for single-host inference/conversion — the shared restore dance
    of sample.py and models/convert.py.

    Normalizations every inference consumer needs: training-time
    model/sequence parallelism is dropped (Orbax restores any checkpoint
    onto a pure-DP mesh, and short-sequence decode runs on whatever host
    invokes it), and batch_size is replaced by a mesh-divisible dummy
    (inference builds its own batches; the saved value may not divide
    this host's device count). Caller ``overrides`` are applied last.
    """
    # Force the platform BEFORE jax initializes below: len(jax.devices())
    # would otherwise be the call that grabs an accelerator a training job
    # may already hold (the device='cpu' conversion path).
    _select_platform(device)
    import jax
    import orbax.checkpoint as ocp

    from nanosandbox_tpu.checkpoint import Checkpointer
    from nanosandbox_tpu.config import TrainConfig

    ckpt = Checkpointer(out_dir)
    step = step if step is not None else ckpt.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoint under {out_dir}/ckpt")
    restored = ckpt.mgr.restore(
        step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
    cfg = TrainConfig(**{**restored["extra"]["config"], "device": device,
                         "init_from": "resume", "out_dir": out_dir})
    # Unconditional pure-DP normalization (idempotent for already-pure-DP
    # configs): a saved EXPLICIT mesh_dp (e.g. 8 from a v4-8 run) must not
    # survive onto a host with a different device count any more than
    # fsdp/sp/tp may.
    defaults = dict(
        attention_impl="auto" if cfg.attention_impl == "ring"
        else cfg.attention_impl,
        mesh_sp=1, mesh_fsdp=1, mesh_tp=1, mesh_dp=-1, mesh_slices=0,
        shard_params=False,
        batch_size=len(jax.devices()), gradient_accumulation_steps=1)
    cfg = cfg.replace(**{**defaults, **overrides})
    trainer = Trainer(cfg)
    state, _ = ckpt.restore(trainer.abstract_state, step)
    ckpt.close()
    return trainer, state, step


class Trainer:
    """Owns model/optimizer/state/mesh and the compiled step functions.

    mesh_devices: optional explicit device list for the mesh — the
    AOT-validation path (__graft_entry__.dryrun_multichip_full) passes
    abstract topology devices here to compile real-shape programs for a
    TPU target the host doesn't have. Normal training leaves it None
    (mesh over jax.devices()). Not a config field: device objects are
    process-local and must never serialize into checkpoints.
    """

    def __init__(self, cfg: TrainConfig, mesh_devices: list | None = None):
        _select_platform(cfg.device)
        import jax

        from nanosandbox_tpu.data.loader import BinDataset
        from nanosandbox_tpu.models.gpt import GPT
        from nanosandbox_tpu.parallel.distributed import (
            maybe_initialize_distributed)
        from nanosandbox_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                                   set_current_mesh)
        from nanosandbox_tpu.parallel.sharding import param_shardings

        self.cfg = cfg
        self.multi_host = maybe_initialize_distributed(
            cfg.coordinator_address, cfg.num_processes, cfg.process_id)
        self.process_index = jax.process_index()
        self.process_count = jax.process_count()
        self.is_main = self.process_index == 0

        self.dataset = BinDataset(cfg.data_dir, cfg.dataset)
        from nanosandbox_tpu.models.convert import HF_GPT2_NAMES
        meta_kind = self.dataset.meta.get("kind")
        if cfg.init_from in HF_GPT2_NAMES and meta_kind not in ("gpt2", None):
            # Real OpenAI GPT-2 weights expect the canonical tiktoken-gpt2
            # id space; a dataset prepared with the char/byte/local-BPE
            # tokenizers has the same SHAPE but different token ids, so
            # fine-tuning would silently train on garbage mappings
            # (round-4 VERDICT missing #1). kind=None (no meta.pkl) is the
            # nanoGPT OWT convention, which means gpt2 BPE — allowed.
            # Checked BEFORE the weight download so the mismatch fails
            # fast (and offline) rather than after pulling ~0.5-6 GB.
            raise ValueError(
                f"init_from={cfg.init_from!r} loads real GPT-2 weights, "
                f"but dataset {cfg.dataset!r} was tokenized with the "
                f"{meta_kind!r} tokenizer, not GPT-2 BPE. Re-prepare the "
                "dataset with the gpt2 tokenizer (python -m "
                "nanosandbox_tpu.data.prepare openwebtext ...) or drop "
                "init_from.")

        # Pretrained import (reference `--init_from=gpt2*`): the HF config
        # dictates the architecture, exactly as nanoGPT forces its model
        # args from the loaded checkpoint. block_size may be CROPPED
        # below the pretrained context (wpe rows sliced); growing it has
        # no trained positions to use and errors.
        from nanosandbox_tpu.models.convert import resolve_init_from
        hf_src = resolve_init_from(cfg.init_from)
        self._hf_params = None
        self._pretrained = bool(hf_src)  # 'hf:' (empty path) is not one
        if hf_src:
            from nanosandbox_tpu.models.convert import load_hf_gpt2
            hf_cfg, hf_params = load_hf_gpt2(hf_src)
            if cfg.block_size > hf_cfg.block_size:
                raise ValueError(
                    f"block_size {cfg.block_size} exceeds the pretrained "
                    f"context {hf_cfg.block_size} ({cfg.init_from})")
            if cfg.block_size < hf_cfg.block_size:
                hf_params["wpe"]["embedding"] = \
                    hf_params["wpe"]["embedding"][:cfg.block_size]
            self.cfg = cfg = cfg.replace(
                n_layer=hf_cfg.n_layer, n_head=hf_cfg.n_head,
                n_embd=hf_cfg.n_embd, vocab_size=hf_cfg.vocab_size,
                bias=True)
            self._hf_params = hf_params
            if self.is_main:
                print(f"initializing from pretrained {cfg.init_from}: "
                      f"{hf_cfg.n_layer}L/{hf_cfg.n_head}H/"
                      f"{hf_cfg.n_embd}d, vocab {hf_cfg.vocab_size}")

        vocab = cfg.vocab_size or self.dataset.vocab_size
        self.model_cfg = GPTConfig.from_train_config(cfg, vocab)

        if cfg.mesh_slices:
            from nanosandbox_tpu.parallel.mesh import make_hybrid_mesh
            self.mesh = make_hybrid_mesh(cfg.mesh_dp, cfg.mesh_fsdp,
                                         cfg.mesh_tp, cfg.mesh_sp,
                                         num_slices=cfg.mesh_slices,
                                         devices=mesh_devices)
        else:
            self.mesh = make_mesh(cfg.mesh_dp, cfg.mesh_fsdp, cfg.mesh_tp,
                                  cfg.mesh_sp, devices=mesh_devices)
        set_current_mesh(self.mesh)
        # The mesh is bound to the model explicitly (ring attention needs
        # it); the global above is only a fallback for standalone model use.
        self.model = GPT(self.model_cfg, mesh=self.mesh)
        self.batch_sharding = batch_sharding(self.mesh)
        # Fail fast on batch/mesh mismatches instead of surfacing them later
        # as opaque pjit sharding errors (docs/playbook.md pitfalls).
        dp_shards = self.mesh.shape["data"] * self.mesh.shape["fsdp"]
        if cfg.batch_size % dp_shards:
            raise ValueError(
                f"batch_size {cfg.batch_size} must be divisible by "
                f"data*fsdp mesh shards ({dp_shards})")
        from nanosandbox_tpu.config import resolve_loss_chunk_size

        self.loss_chunk_size = resolve_loss_chunk_size(
            cfg.loss_chunk_size, cfg.batch_size // dp_shards,
            cfg.block_size, self.model_cfg.vocab_size,
            seq_shards=self.mesh.shape["seq"])
        if cfg.sequences_per_iter % self.process_count:
            raise ValueError(
                f"batch_size*accum {cfg.sequences_per_iter} must be "
                f"divisible by num_processes ({self.process_count})")
        if cfg.batch_size % self.process_count:
            # estimate_loss builds per-process eval batches of
            # batch_size // process_count rows; accumulation does NOT
            # carry the divisibility there, so a config like batch 2 /
            # accum 4 / 8 processes would crash mid-run at the first
            # eval with a 0-row batch. Fail at construction instead.
            raise ValueError(
                f"batch_size {cfg.batch_size} must be divisible by "
                f"num_processes ({self.process_count}) for evaluation")
        if cfg.block_size % self.mesh.shape["seq"]:
            raise ValueError(
                f"block_size {cfg.block_size} must be divisible by the "
                f"seq mesh axis ({self.mesh.shape['seq']})")
        if cfg.mesh_sp > 1 and cfg.attention_impl != "ring":
            raise ValueError(
                "mesh_sp > 1 requires attention_impl='ring' (other impls "
                "compute attention over the local sequence shard only)")
        if (cfg.attention_impl == "ring" and cfg.mesh_tp > 1
                and cfg.n_head % cfg.mesh_tp):
            raise ValueError(
                f"attention_impl='ring' shards heads over model: n_head "
                f"{cfg.n_head} must be divisible by mesh_tp {cfg.mesh_tp}")
        self.tx, self.lr_schedule = make_optimizer(cfg)

        # Abstract state -> shardings -> sharded init.
        abstract = jax.eval_shape(self._init_state, jax.random.key(cfg.seed))
        self.state_shardings = {
            "params": param_shardings(
                self.mesh, abstract["params"],
                shard_params=cfg.shard_params, tp=cfg.mesh_tp > 1),
            "opt_state": param_shardings(
                self.mesh, abstract["opt_state"],
                shard_params=cfg.shard_params, tp=cfg.mesh_tp > 1),
            "step": jax.sharding.NamedSharding(
                self.mesh, jax.sharding.PartitionSpec()),
        }
        self.abstract_state = jax.tree.map(
            lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s),
            abstract, self.state_shardings,
            is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))

        self._train_step = None
        self._eval_step = None
        # Retrace budgets for the compiled steps (utils.tracecheck):
        # each is ONE program — batch/sequence shapes are fixed by the
        # config — so a second trace means something specialized the
        # step (the failure mode jaxlint's nonstatic-shape rule hunts
        # statically) and raises instead of silently recompiling.
        self.tracecheck = tracecheck.TraceBudgetRegistry()
        # Telemetry spine (nanosandbox_tpu/obs): the loss/MFU/tok-s
        # scalars land on the same registry kind the serve engine
        # publishes (MetricsWriter keeps owning the JSONL/TB artifact
        # contract — the registry is the live snapshot view), and the
        # tracer records eval windows / checkpoint saves / profiler
        # windows as spans. Only updated at log/eval points, never
        # inside the compiled step.
        self.metrics = MetricRegistry()
        self.tracer = SpanTracer(capacity=2048)
        m = self.metrics
        self._m_loss = m.gauge("train_loss",
                               "Training loss at the last log step.")
        self._m_grad_norm = m.gauge("train_grad_norm",
                                    "Global grad norm at the last log step.")
        self._m_lr = m.gauge("train_lr", "Learning rate at the last "
                             "log step.")
        self._m_toks = m.gauge("train_tokens_per_sec",
                               "Window-averaged training tokens/sec.")
        self._m_mfu = m.gauge("train_mfu",
                              "Model FLOPs utilization (0..1).")
        self._m_iters = m.counter("train_iters_total",
                                  "Optimizer steps completed.")
        self._m_eval = m.gauge("eval_loss", "Last estimate_loss value, "
                               "by split.", labelnames=("split",))
        self._m_ckpt = m.counter("checkpoint_saves_total",
                                 "Checkpoints written.")

    # -- state ---------------------------------------------------------------

    def _init_state(self, rng) -> dict[str, Any]:
        import jax.numpy as jnp

        # The dummy init batch must satisfy the same sharding divisibility
        # as real batches (ring attention's shard_map validates shapes at
        # trace time): B divisible by data*fsdp, T by the seq axis.
        dp_shards = self.mesh.shape["data"] * self.mesh.shape["fsdp"]
        sp = self.mesh.shape["seq"]
        B = max(2, dp_shards)
        T = min(self.cfg.block_size, max(8, sp))
        T = max(sp, (T // sp) * sp)
        dummy = jnp.zeros((B, T), jnp.int32)
        variables = self.model.init(rng, dummy, deterministic=True)
        params = variables["params"]
        opt_state = self.tx.init(params)
        return {"params": params, "opt_state": opt_state,
                "step": jnp.zeros((), jnp.int32)}

    def init_state(self) -> dict[str, Any]:
        import jax

        init = jax.jit(self._init_state,
                       out_shardings=self.state_shardings)
        return init(jax.random.key(self.cfg.seed))

    def pretrained_state(self) -> dict[str, Any]:
        """Training state from the imported HF weights: each converted
        leaf is placed with its mesh sharding (so FSDP fine-tuning of a
        pretrained model shards on arrival), fresh optimizer state.

        Single-shot: the host-side float32 copy is released once placed
        (gpt2-xl is ~6 GB of numpy that must not stay pinned for the whole
        run), so a second call raises instead of silently re-initializing.
        """
        import jax
        import jax.numpy as jnp

        if self._hf_params is None:
            raise RuntimeError(
                "pretrained weights already consumed (pretrained_state is "
                "single-shot) or init_from is not a pretrained source")
        dtype = jnp.dtype(self.cfg.param_dtype)
        # Cast on host, then ONE placement directly onto the sharding:
        # jnp.asarray would first copy to the default device and the
        # device_put would then reshard device-to-device — double
        # transfer plus a transient full replica for a gpt2-xl import.
        params = jax.tree.map(
            lambda x, s: jax.device_put(np.asarray(x, dtype), s),
            self._hf_params, self.state_shardings["params"])
        opt_state = jax.jit(
            self.tx.init,
            out_shardings=self.state_shardings["opt_state"])(params)
        step = jax.device_put(jnp.zeros((), jnp.int32),
                              self.state_shardings["step"])
        self._hf_params = None
        return {"params": params, "opt_state": opt_state, "step": step}

    # -- compiled steps ------------------------------------------------------

    def _loss_fn(self, params, x, y, rng):
        from nanosandbox_tpu.models.gpt import (
            chunked_cross_entropy_loss, cross_entropy_loss,
            sharded_chunked_cross_entropy_loss)

        deterministic = self.cfg.dropout == 0.0 or rng is None
        kwargs = {} if deterministic else {"rngs": {"dropout": rng}}
        # Chunked head+loss keeps (B, T, vocab) logits out of HBM. Under
        # sequence parallelism the scan runs per-shard inside shard_map
        # (a scan over the T-sharded dim would otherwise force gathers,
        # and full logits at long context defeat the ring's memory story).
        if self.loss_chunk_size > 0:
            hidden = self.model.apply({"params": params}, x,
                                      deterministic=deterministic,
                                      return_hidden=True, **kwargs)
            if self.mesh.shape["seq"] == 1:
                return chunked_cross_entropy_loss(
                    hidden, params["wte"]["embedding"], y,
                    chunk_size=self.loss_chunk_size,
                    compute_dtype=self.cfg.compute_dtype)
            return sharded_chunked_cross_entropy_loss(
                hidden, params["wte"]["embedding"], y, mesh=self.mesh,
                chunk_size=self.loss_chunk_size,
                compute_dtype=self.cfg.compute_dtype)
        logits = self.model.apply({"params": params}, x,
                                  deterministic=deterministic, **kwargs)
        return cross_entropy_loss(logits, y)

    def train_rng(self, seed: int):
        """Root key of the TRAINING rng stream (dropout masks), honoring
        cfg.rng_impl. Init/eval keys stay on the default impl — they are
        not per-step costs and their determinism contract predates the
        knob."""
        import jax

        return jax.random.key(seed, impl=self.cfg.rng_impl)

    def _train_step_fn(self, state, x, y, rng):
        import jax
        import jax.numpy as jnp
        from jax import lax

        accum = self.cfg.gradient_accumulation_steps
        params = state["params"]

        if accum == 1:
            loss, grads = jax.value_and_grad(self._loss_fn)(params, x, y, rng)
        else:
            # x is (accum * batch_size, T): nanoGPT semantics — accumulation
            # multiplies the data per optimizer step, micro-batch stays
            # batch_size.
            micro = x.shape[0] // accum
            xs = x.reshape(accum, micro, -1)
            ys = y.reshape(accum, micro, -1)

            def body(carry, xy):
                loss_acc, grad_acc = carry
                xm, ym, i = xy
                r = None if rng is None else jax.random.fold_in(rng, i)
                l, g = jax.value_and_grad(self._loss_fn)(params, xm, ym, r)
                return (loss_acc + l,
                        jax.tree.map(jnp.add, grad_acc, g)), None

            zero = jax.tree.map(jnp.zeros_like, params)
            (loss, grads), _ = lax.scan(
                body, (jnp.zeros(()), zero),
                (xs, ys, jnp.arange(accum)))
            loss = loss / accum
            grads = jax.tree.map(lambda g: g / accum, grads)

        updates, opt_state = self.tx.update(grads, state["opt_state"], params)
        import optax
        params = optax.apply_updates(params, updates)
        new_state = {"params": params, "opt_state": opt_state,
                     "step": state["step"] + 1}
        grad_norm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": grad_norm}

    def _eval_step_fn(self, state, x, y):
        return self._loss_fn(state["params"], x, y, None)

    def compiled_steps(self):
        import jax

        if self._train_step is None:
            step = partial(self._train_step_fn)
            if self.cfg.compile:
                # CPU jit ignores donation (and warns every compile);
                # donate the train state only on accelerators, the same
                # gate the serve engine applies to its pool/state.
                on_accel = jax.default_backend() != "cpu"
                # Budget 2 under --memory_report: its AOT .lower() on
                # abstract operands traces once on top of the live step.
                train_budget = 2 if self.cfg.memory_report else 1
                step = self.tracecheck.guard("train_step",
                                             train_budget)(step)
                eval_fn = self.tracecheck.guard("eval_step",
                                                1)(self._eval_step_fn)
                self._train_step = jax.jit(
                    step,
                    in_shardings=(self.state_shardings, self.batch_sharding,
                                  self.batch_sharding, None),
                    out_shardings=(self.state_shardings, None),
                    donate_argnums=(0,) if on_accel else ())
                # jaxlint: disable=unconstrained-output -- scalar loss output: nothing mesh-sized to constrain
                self._eval_step = jax.jit(
                    eval_fn,
                    in_shardings=(self.state_shardings, self.batch_sharding,
                                  self.batch_sharding))
            else:
                # Uncompiled steps run the body EVERY call — a call
                # counter would not be a trace counter, so no guard.
                self._train_step = step
                self._eval_step = self._eval_step_fn
        return self._train_step, self._eval_step

    def memory_report(self) -> dict:
        """XLA's compile-time memory analysis of the train step — the
        'will this config fit HBM?' answer without burning a step (the
        760M/1.5B configs live or die by this, BASELINE.md scaling notes).

        AOT-lowers on abstract inputs; costs one extra compile, which is
        why it sits behind --memory_report instead of running always.
        Keys are bytes, per device."""
        import jax
        import jax.numpy as jnp

        if not self.cfg.compile:
            raise ValueError("memory_report requires compile=True")
        train_step, _ = self.compiled_steps()
        rows = self.cfg.sequences_per_iter
        batch_sds = jax.ShapeDtypeStruct((rows, self.cfg.block_size),
                                         jnp.int32,
                                         sharding=self.batch_sharding)
        ma = train_step.lower(self.abstract_state, batch_sds, batch_sds,
                              self.train_rng(0)).compile().memory_analysis()
        if ma is None:  # backend without memory analysis
            return {}
        self.flops_per_iter()  # populates self._n_params
        itemsize = jnp.dtype(self.cfg.param_dtype).itemsize
        return {
            "params_bytes": itemsize * self._n_params,
            "state_bytes": ma.argument_size_in_bytes,   # params+opt+batch
            "temp_bytes": ma.temp_size_in_bytes,        # activations/workspace
            "output_bytes": ma.output_size_in_bytes,
            "code_bytes": ma.generated_code_size_in_bytes,
            # alias_size: the donated train state appears in BOTH argument
            # and output sizes (donate_argnums=(0,)); the aliased bytes
            # occupy HBM once, so subtract them or the preflight would
            # overstate by the whole params+opt footprint.
            "total_bytes": (ma.argument_size_in_bytes
                            + ma.temp_size_in_bytes
                            + ma.output_size_in_bytes
                            + ma.generated_code_size_in_bytes
                            - ma.alias_size_in_bytes),
        }

    # -- sharding analysis (shardcheck program enumeration) ------------------

    def shardcheck_programs(self) -> list:
        """ProgramSpecs for the comms analyzer (analysis/shardcheck):
        the train and eval steps AOT-lowered under this trainer's mesh
        with the REAL in/out shardings. Fresh ``jax.jit`` objects, not
        the guarded ``compiled_steps`` ones — an analysis lower must not
        consume the tracecheck retrace budgets the live loop enforces.

        Expectations encode the mesh contract: full param gathers are
        the point of ZeRO-3 (fsdp) and ring attention's transposes
        (seq), TP activations gather over model — but the data axis
        carries gradient all-reduces ONLY, and nothing may materialize
        a sharded tensor on any other axis."""
        import jax
        import jax.numpy as jnp

        from nanosandbox_tpu.analysis.shardcheck import (Expectations,
                                                         ProgramSpec)

        rows = self.cfg.sequences_per_iter
        batch = jax.ShapeDtypeStruct((rows, self.cfg.block_size), jnp.int32,
                                     sharding=self.batch_sharding)
        key = self.train_rng(0)
        expect = Expectations(gather_ok_axes=("fsdp", "seq", "model"),
                              allreduce_only_axes=("data",))

        def lower_train():
            return jax.jit(
                self._train_step_fn,
                in_shardings=(self.state_shardings, self.batch_sharding,
                              self.batch_sharding, None),
                out_shardings=(self.state_shardings, None),
            ).lower(self.abstract_state, batch, batch, key)

        def lower_eval():
            # jaxlint: disable=unconstrained-output -- scalar loss output: nothing mesh-sized to constrain
            return jax.jit(
                self._eval_step_fn,
                in_shardings=(self.state_shardings, self.batch_sharding,
                              self.batch_sharding),
            ).lower(self.abstract_state, batch, batch)

        return [
            ProgramSpec(name="train_step", lower=lower_train,
                        abstract_args=(self.abstract_state, batch, batch),
                        expect=expect, tags=("train",)),
            ProgramSpec(name="eval_step", lower=lower_eval,
                        abstract_args=(self.abstract_state, batch, batch),
                        expect=expect, tags=("train",)),
        ]

    # -- data ----------------------------------------------------------------

    def make_loader(self, split: str, start_step: int = 0, prefetch=True):
        from nanosandbox_tpu.data.loader import BatchLoader

        return BatchLoader(
            self.dataset, split, self.cfg.sequences_per_iter,
            self.cfg.block_size,
            seed=self.cfg.seed, process_index=self.process_index,
            num_processes=self.process_count, start_step=start_step,
            prefetch=prefetch)

    def to_global(self, local: np.ndarray):
        import jax

        global_batch = local.shape[0] * self.process_count
        global_shape = (global_batch,) + local.shape[1:]
        return jax.make_array_from_process_local_data(
            self.batch_sharding, local, global_shape)

    # -- evaluation (nanoGPT estimate_loss) ----------------------------------

    def estimate_loss(self, state, eval_iters: int | None = None) -> dict:
        import jax.numpy as jnp

        eval_iters = eval_iters or self.cfg.eval_iters
        _, eval_step = self.compiled_steps()
        sid = self.tracer.begin("eval", cat="train",
                                args={"eval_iters": eval_iters})
        out = {}
        for split in ("train", "val"):
            # Build ALL host batches up front, THEN enqueue every eval
            # step, THEN read ONE scalar. The host-side gather (memmap
            # window copies, ~ms each) used to sit inside the enqueue
            # loop, serializing with eval dispatch; hoisted, the device
            # chews through back-to-back steps while the host is already
            # done gathering. And under async dispatch each float() is a
            # host<->device round trip (~100ms+ on a tunneled PJRT
            # transport), so a per-step readback would cost eval_iters
            # RTTs per split — the char-convergence run spent ~40% of its
            # wall clock there before the single-readback change.
            batches = [
                self.dataset.sample_batch(
                    split, 1_000_000 + i,
                    self.cfg.batch_size // self.process_count,
                    self.cfg.block_size, seed=self.cfg.seed + 1,
                    process_index=self.process_index)
                for i in range(eval_iters)
            ]
            losses = [eval_step(state, self.to_global(xb), self.to_global(yb))
                      for xb, yb in batches]
            # tracecheck.host_sync is THE deliberate readback: the one
            # scalar sync per split the comment above promises, logged
            # so profiler windows can report their sync count.
            out[split] = tracecheck.host_sync("eval-readback",
                                              jnp.stack(losses).mean())
            self._m_eval.labels(split=split).set(out[split])
        self.tracer.end(sid, {f"{k}_loss": round(v, 6)
                              for k, v in out.items()})
        return out

    # -- MFU -----------------------------------------------------------------

    def flops_per_iter(self) -> float:
        cfg, m = self.cfg, self.model_cfg
        from nanosandbox_tpu.models.gpt import count_params
        import jax

        if not hasattr(self, "_n_params"):
            abstract = jax.eval_shape(self._init_state,
                                      jax.random.key(0))
            self._n_params = count_params(abstract["params"])
        N = self._n_params - m.block_size * m.n_embd  # exclude wpe (nanoGPT)
        L, H, Q, T = m.n_layer, m.n_head, m.n_embd // m.n_head, cfg.block_size
        flops_per_token = 6 * N + 12 * L * H * Q * T
        return flops_per_token * cfg.tokens_per_iter

    def peak_flops(self) -> float:
        import jax

        kind = jax.devices()[0].device_kind
        for k, v in _PEAK_FLOPS.items():
            if kind.lower().startswith(k.lower()):
                return v * len(jax.devices())
        return 100e12 * len(jax.devices())

    # -- main loop -----------------------------------------------------------

    def run(self) -> dict:
        import jax

        from nanosandbox_tpu.checkpoint import Checkpointer
        from nanosandbox_tpu.utils.metrics import MetricsWriter

        cfg = self.cfg
        ckpt = Checkpointer(cfg.out_dir, keep=cfg.keep_checkpoints)

        iter_num = 0
        best_val_loss = 1e9
        # 'auto' = resume when a checkpoint exists, else scratch — the mode
        # k8s restarts use: a crashed pod comes back with the same identity
        # (SURVEY.md §5 restart-with-stable-identity) and must continue, but
        # the very first boot has nothing to restore.
        init_from = cfg.init_from
        if init_from == "auto":
            init_from = ("resume" if ckpt.latest_step() is not None
                         else "scratch")
        if init_from == "resume":
            state, extra = ckpt.restore(self.abstract_state)
            # jaxlint: disable=host-sync -- one-time resume readback
            iter_num = int(extra.get("iter_num", int(state["step"])))
            best_val_loss = float(extra.get("best_val_loss", 1e9))
            if self.is_main:
                print(f"resumed from iter {iter_num} "
                      f"(best val loss {best_val_loss:.4f})")
        elif self._pretrained:
            state = self.pretrained_state()  # raises if already consumed
        else:
            state = self.init_state()

        train_step, _ = self.compiled_steps()
        writer = MetricsWriter(cfg.resolved_log_dir, cfg.run_name,
                               enabled=self.is_main,
                               tensorboard=cfg.tensorboard)
        if cfg.memory_report and not cfg.compile and self.is_main:
            print("memory_report skipped: requires compile=True")
        if cfg.memory_report and cfg.compile:
            mem = self.memory_report()
            if mem and self.is_main:
                gb = 1 << 30
                print(f"memory report (per device): params "
                      f"{mem['params_bytes'] / gb:.2f} GB, state+batch "
                      f"{mem['state_bytes'] / gb:.2f} GB, activations/temp "
                      f"{mem['temp_bytes'] / gb:.2f} GB, total "
                      f"{mem['total_bytes'] / gb:.2f} GB")
            if mem:
                writer.log(0, {f"mem/{k}": float(v)
                               for k, v in mem.items()})
        loader = self.make_loader("train", start_step=iter_num)
        rng = self.train_rng(cfg.seed + 7)
        writer.write_header({
            # estimate_loss draws the SAME batches every eval (step index
            # 1_000_000+i, seed seed+1): deliberate low-variance gating,
            # but "best val loss" is therefore ranked on one frozen
            # eval_iters-batch sample.
            "eval_batch_policy": "fixed", "eval_seed": cfg.seed + 1,
            "eval_iters": cfg.eval_iters,
            # Which offset sampler the loader actually resolved — the
            # native (csrc) xorshift128+ path and the numpy Philox
            # fallback draw DIFFERENT batch streams from the same seed,
            # so cross-machine reproduction needs this recorded.
            "offset_sampler": ("native-xorshift128+" if loader.native
                               else "numpy-philox"),
            "rng_impl": cfg.rng_impl,
        })

        tokens_per_iter = cfg.tokens_per_iter
        flops_per_iter = self.flops_per_iter()
        peak = self.peak_flops()
        last_loss = float("nan")
        last_eval: tuple[int, dict] | None = None
        # --profile_steps=a:b — jax.profiler trace of iters [a, b), written
        # next to the TB events (the README runbook's profiling workflow;
        # SURVEY.md §5 tracing hook point). Validated in TrainConfig.
        self._profiling = False
        prof_range = cfg.profile_range() if self.is_main else None
        if prof_range:
            self.profile_dir = os.path.join(cfg.resolved_log_dir, "profile")
        t0 = time.time()
        window_start_iter = iter_num - 1  # sync precedes step iter_num
        try:
            while iter_num < cfg.max_iters:
                # iter 0 included: every curve gets a scratch-loss anchor
                # (round-4 VERDICT weak #6 — the "resumes at 2.22 vs
                # scratch 11.0" style argument needs the scratch point in
                # the metrics stream). Checkpoint saving below still
                # requires iter_num > 0.
                if (cfg.eval_interval > 0
                        and iter_num % cfg.eval_interval == 0):
                    losses = self.estimate_loss(state)
                    last_eval = (iter_num, losses)
                    if self.is_main:
                        print(f"step {iter_num}: train loss "
                              f"{losses['train']:.4f}, val loss "
                              f"{losses['val']:.4f}")
                    writer.log(iter_num, {"eval/train_loss": losses["train"],
                                          "eval/val_loss": losses["val"]})
                    # The iter-0 anchor is metrics-only: it must not seed
                    # best_val_loss, or a run that never beats its
                    # random-init val loss (too-high LR, tiny corpus)
                    # would end with ZERO checkpoints — the save below is
                    # gated on iter_num > 0 but the bar would already be
                    # set at the scratch loss.
                    if iter_num > 0 and (losses["val"] < best_val_loss
                                         or cfg.always_save_checkpoint):
                        best_val_loss = min(best_val_loss, losses["val"])
                        sid = self.tracer.begin("checkpoint_save",
                                                cat="train",
                                                args={"iter": iter_num})
                        ckpt.save(iter_num, state,
                                  {"iter_num": iter_num,
                                   "best_val_loss": best_val_loss,
                                   "config": cfg.to_dict()})
                        self.tracer.end(sid)
                        self._m_ckpt.inc()
                    if cfg.eval_only:
                        break
                    # Eval + checkpoint time is reported on its own lines;
                    # restart the throughput window so the next logged
                    # tok/s reflects training steps only. iter_num - 1,
                    # not iter_num: this sync point is BEFORE step
                    # iter_num runs, while the log-step sync is after its
                    # step completes — the next window spans steps
                    # [iter_num, next_log] inclusive.
                    t0, window_start_iter = time.time(), iter_num - 1

                if prof_range and iter_num == prof_range[0]:
                    jax.profiler.start_trace(self.profile_dir)
                    self._profiling = True
                    self._profile_span = self.tracer.begin(
                        "profiler_window", cat="train",
                        args={"start": prof_range[0],
                              "stop": prof_range[1]})
                    # Snapshot the sync ledger so the window report
                    # below describes the TRACED REGION's syncs, not the
                    # process-lifetime totals.
                    self._profile_sync_mark = tracecheck.sync_counts()

                xb, yb = next(loader)
                step_rng = jax.random.fold_in(rng, iter_num)
                state, metrics = train_step(state, self.to_global(xb),
                                            self.to_global(yb), step_rng)

                if self._profiling and iter_num == prof_range[1] - 1:
                    # Drain the async queue so the traced window contains
                    # the device work, then stop. Scalar readback, not
                    # block_until_ready: some PJRT transports make the
                    # latter a no-op (see utils/benchmarking.py), which
                    # would stop the trace before the device work lands.
                    # host_sync (not a bare float()) so the drain lands
                    # in the sync ledger with the rest of the window.
                    tracecheck.host_sync("profile-window-drain",
                                         metrics["loss"])
                    jax.profiler.stop_trace()
                    self._profiling = False
                    self.tracer.end(self._profile_span)
                    if self.is_main:
                        by_kind = tracecheck.sync_delta(
                            self._profile_sync_mark)
                        print(f"profiler trace for iters "
                              f"[{prof_range[0]}:{prof_range[1]}) -> "
                              f"{self.profile_dir} "
                              f"({sum(by_kind.values())} logged host "
                              f"sync(s) in the window; by kind: "
                              f"{by_kind})")

                if cfg.log_interval > 0 and iter_num % cfg.log_interval == 0:
                    # The log-step sync point, through the audited
                    # readback wrapper (profiler windows count it).
                    loss = tracecheck.host_sync("train-log-readback",
                                                metrics["loss"])
                    last_loss = loss
                    # Window-averaged timing: under async dispatch the
                    # host enqueues steps far faster than the device runs
                    # them, and the scalar readback above drains the whole
                    # backlog — so per-iteration wall time is meaningless
                    # at the log step (it would charge ~log_interval
                    # steps of device work to one iteration and understate
                    # tok/s by that factor). Average over the iterations
                    # since the last sync point instead.
                    now = time.time()
                    n_iters = iter_num - window_start_iter
                    dt = (now - t0) / max(n_iters, 1)
                    t0, window_start_iter = now, iter_num
                    toks = tokens_per_iter / max(dt, 1e-9)
                    mfu = flops_per_iter / max(dt, 1e-9) / peak
                    if self.is_main:
                        print(f"iter {iter_num}: loss {loss:.4f}, "
                              f"time {dt * 1000:.2f}ms, "
                              f"tok/s {toks:,.0f}, mfu {mfu * 100:.2f}%")
                    # jaxlint: disable=host-sync -- free after loss sync
                    grad_norm = float(metrics["grad_norm"])
                    lr = (float(self.lr_schedule(iter_num))
                          if callable(self.lr_schedule)
                          else self.lr_schedule)
                    writer.log(iter_num, {
                        "train/loss": loss,
                        "train/grad_norm": grad_norm,
                        "train/lr": lr,
                        "perf/tokens_per_sec": toks,
                        "perf/mfu": mfu,
                    })
                    # The live-snapshot view of the same scalars: the
                    # registry answers "what is this trainer doing NOW"
                    # (tests, notebooks, a future scrape) without
                    # tailing the JSONL artifact.
                    self._m_loss.set(loss)
                    self._m_grad_norm.set(grad_norm)
                    self._m_lr.set(lr)
                    self._m_toks.set(toks)
                    self._m_mfu.set(mfu)
                    self._m_iters._set_total(iter_num + 1)
                iter_num += 1
        finally:
            if self._profiling:
                jax.profiler.stop_trace()
                self._profiling = False
                self.tracer.end(self._profile_span)
            loader.close()
            writer.close()

        if last_eval is not None and last_eval[0] == iter_num:
            losses = last_eval[1]  # already evaluated at this exact step
        else:
            losses = self.estimate_loss(state) if cfg.max_iters > 0 else {}
        if cfg.max_iters > 0 and not cfg.eval_only:
            sid = self.tracer.begin("checkpoint_save", cat="train",
                                    args={"iter": iter_num, "final": True})
            ckpt.save(iter_num, state,
                      {"iter_num": iter_num,
                       "best_val_loss": min(best_val_loss,
                                            losses.get("val", 1e9)),
                       "config": cfg.to_dict()}, wait=True)
            self.tracer.end(sid)
            self._m_ckpt.inc()
        ckpt.close()
        return {"iter_num": iter_num, "final_loss": last_loss, **{
            f"final_{k}_loss": v for k, v in losses.items()}}


def main(argv: list[str] | None = None) -> dict:
    cfg = load_config(argv if argv is not None else sys.argv[1:])
    _select_platform(cfg.device)
    trainer = Trainer(cfg)
    if trainer.is_main:
        print(f"tokens per iteration: {cfg.tokens_per_iter:,}")
        print(f"mesh: {trainer.mesh}")
    return trainer.run()


if __name__ == "__main__":
    main()
