"""Unified telemetry spine: metric registry + span tracer.

Three halves (ISSUE 5), all pure host-side stdlib — no jax import, no
device readback, nothing for jaxlint to flag:

  registry.py — process/instance MetricRegistry: named Counter / Gauge /
                Histogram families with labels, JSON ``snapshot()``, and
                Prometheus text exposition. Engines, the Trainer, the
                tracecheck ledgers and warn_once all publish here.
  tracer.py   — SpanTracer: begin/end spans recorded from already-
                host-resident dispatch-time state, bounded ring,
                request-id correlation, Chrome trace-event JSON export
                (Perfetto-loadable) per request or per time window.

The serving surface (serve/http.py) exposes both: ``GET /metrics``
(Prometheus scrape), ``GET /trace?rid=N`` (one request's timeline),
``POST /profile`` (an on-demand jax.profiler window over the live
serve loop).
"""

from nanosandbox_tpu.obs.registry import (DEFAULT_BUCKETS, MetricFamily,
                                          MetricRegistry, global_registry,
                                          render_prometheus)
from nanosandbox_tpu.obs.tracer import ENGINE_TRACK, Span, SpanTracer

__all__ = ["MetricRegistry", "MetricFamily", "SpanTracer", "Span",
           "global_registry", "render_prometheus", "DEFAULT_BUCKETS",
           "ENGINE_TRACK"]
