"""Unified telemetry spine: metric registry + span tracer.

Three halves (ISSUE 5), all pure host-side stdlib — no jax import, no
device readback, nothing for jaxlint to flag:

  registry.py — process/instance MetricRegistry: named Counter / Gauge /
                Histogram families with labels, JSON ``snapshot()``, and
                Prometheus text exposition. Engines, the Trainer, the
                tracecheck ledgers and warn_once all publish here.
  tracer.py   — SpanTracer: begin/end spans recorded from already-
                host-resident dispatch-time state, bounded ring,
                request-id correlation, Chrome trace-event JSON export
                (Perfetto-loadable) per request or per time window.

Two more halves (ISSUE 10), same contract:

  flight.py   — FlightRecorder: bounded per-request lifecycle ledger
                (submit -> queue -> block-reserve -> admit ->
                prefill[hit|miss] -> retire* -> finish|reject|shed)
                with JSONL export, plus WatchdogPanel: anomaly
                detectors (TTFT spike, admission stall, pool thrash,
                post-freeze retrace, stuck slot) that snapshot the
                ledger + span ring on a trip.
  slo.py      — SLOLedger: per-request deadline_s / slo_class
                accounting — attainment, goodput tokens, deadline
                margins — published through the registry.
  vitals.py   — register_process_vitals: RSS / open fds / uptime /
                jax live-buffer gauges, sampled per scrape.

The serving surface (serve/http.py) exposes all of it: ``GET
/metrics`` (Prometheus scrape), ``GET /trace?rid=N`` (one request's
timeline), ``GET /debug/requests|slots|kvpool|scheduler`` (flight
ledger + live introspection), ``POST /profile`` (an on-demand
jax.profiler window over the live serve loop).
"""

from nanosandbox_tpu.obs.flight import (TERMINAL_EVENTS, FlightRecorder,
                                        WatchdogPanel)
from nanosandbox_tpu.obs.registry import (DEFAULT_BUCKETS, MetricFamily,
                                          MetricRegistry, global_registry,
                                          render_prometheus)
from nanosandbox_tpu.obs.slo import SLOLedger, validate_slo_class
from nanosandbox_tpu.obs.tracer import ENGINE_TRACK, Span, SpanTracer
from nanosandbox_tpu.obs.vitals import register_process_vitals

__all__ = ["MetricRegistry", "MetricFamily", "SpanTracer", "Span",
           "global_registry", "render_prometheus", "DEFAULT_BUCKETS",
           "ENGINE_TRACK", "FlightRecorder", "WatchdogPanel",
           "TERMINAL_EVENTS", "SLOLedger", "validate_slo_class",
           "register_process_vitals"]
