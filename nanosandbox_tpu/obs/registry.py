"""Process-wide metric registry: named Counter/Gauge/Histogram families.

Before this module the repo had one signal per subsystem: RingStat
percentiles inside ``Engine.stats()``, a JSON ``/stats`` dict, the
tracecheck sync/compile ledgers, and ``train.py``'s stdout scalars —
four shapes, zero shared names, nothing a Prometheus scrape could read.
This registry is the one spine they all hang off:

  * a **family** is a named metric (``serve_ttft_seconds``) of one kind
    (counter | gauge | histogram) with a fixed tuple of label names;
    ``family.labels(slot="3")`` returns the child series for one label
    combination, created on first touch;
  * ``snapshot()`` is the JSON view (the ``/stats`` superset);
  * ``prometheus_text()`` is the text exposition format a k8s
    Prometheus scrape consumes (``GET /metrics`` in serve/http.py).

Hot-loop cost is ZERO by design: counters that mirror engine state are
not incremented per token — **collectors** (callbacks run at
collection time, i.e. per scrape) copy the engine's plain-int counters
into the families. Only histograms observe per event, and an observe is
a deque append + one bisect. Nothing here imports jax; recorded values
are already-host-resident ints/floats (the jaxlint contract).

Histograms are two views of the same stream: the bounded ``RingStat``
window (recent percentiles — what a dashboard wants for "how slow is
it NOW") plus fixed-bucket cumulative counts + sum + count (what
Prometheus wants for rate()/histogram_quantile over all time). The
exposition renders both: the histogram proper, and a ``<name>_window``
summary with ``quantile`` labels from the ring.
"""

from __future__ import annotations

import math
import re
import threading
from bisect import bisect_left
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from nanosandbox_tpu.utils.metrics import RingStat

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")

# Latency-shaped default buckets (seconds): spans ~1ms..10s, the serving
# TTFT/TPOT range on everything from a CPU tiny model to a tunneled TPU.
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1,
                   0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt(v: float) -> str:
    """Prometheus sample value: integral floats render as ints so the
    golden-format test (and a human) reads `3`, not `3.0`."""
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_help(s: str) -> str:
    return s.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label(s: str) -> str:
    return (s.replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _labelstr(names: Tuple[str, ...], values: Tuple[str, ...],
              extra: str = "") -> str:
    parts = [f'{n}="{_escape_label(v)}"' for n, v in zip(names, values)]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


class _Child:
    """One labeled series of a family. Created by ``family.labels()``;
    the label-less family delegates to its own ``()`` child."""

    __slots__ = ("_family", "_values", "_value", "_lock",
                 "_ring", "_bucket_counts", "_sum", "_count")

    def __init__(self, family: "MetricFamily", values: Tuple[str, ...]):
        self._family = family
        self._values = values
        self._lock = threading.Lock()
        self._value: Optional[float] = 0.0 if family.kind == "counter" \
            else None
        if family.kind == "histogram":
            self._ring = RingStat(family.window)
            self._bucket_counts = [0] * len(family.buckets)
            self._sum = 0.0
            self._count = 0

    # -- counter ----------------------------------------------------------
    def inc(self, amount: float = 1.0) -> None:
        if self._family.kind != "counter":
            raise TypeError(f"{self._family.name} is {self._family.kind}, "
                            "not counter")
        if amount < 0:
            raise ValueError(f"counter increment must be >= 0, got {amount}")
        with self._lock:
            self._value += amount

    def _set_total(self, value: float) -> None:
        """Collector backdoor: mirror an externally-owned monotonic
        counter (engine plain ints, the tracecheck ledgers) into this
        series at collection time. Not part of the recording API."""
        with self._lock:
            self._value = float(value)

    # -- gauge ------------------------------------------------------------
    def set(self, value: float) -> None:
        if self._family.kind != "gauge":
            raise TypeError(f"{self._family.name} is {self._family.kind}, "
                            "not gauge")
        with self._lock:
            self._value = float(value)

    # -- histogram --------------------------------------------------------
    def observe(self, value: float) -> None:
        if self._family.kind != "histogram":
            raise TypeError(f"{self._family.name} is {self._family.kind}, "
                            "not histogram")
        v = float(value)
        with self._lock:
            self._ring.record(v)
            i = bisect_left(self._family.buckets, v)
            if i < len(self._bucket_counts):
                self._bucket_counts[i] += 1
            self._sum += v
            self._count += 1

    def hist_state(self) -> Tuple[List[int], float, int]:
        """Coherent (bucket_counts, sum, count) copy under the same lock
        observe() writes under — a render interleaving with an observe
        must never emit a finite bucket greater than +Inf/_count (a
        non-monotonic histogram poisons histogram_quantile())."""
        with self._lock:
            return list(self._bucket_counts), self._sum, self._count

    # RingStat-compatible window reads — Engine.stats()'s legacy dict
    # shapes are built from these, so the /stats contract survives the
    # migration unchanged.
    def mean(self) -> Optional[float]:
        return self._ring.mean()

    def percentiles(self, ps: tuple = (50, 90, 99)) -> Optional[dict]:
        return self._ring.percentiles(ps)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def value(self) -> Optional[float]:
        return self._value

    def reset(self) -> None:
        """Clear this series (benchmarks reset between warmup and the
        timed window; production scrapes never call this)."""
        with self._lock:
            if self._family.kind == "histogram":
                self._ring.clear()
                self._bucket_counts = [0] * len(self._family.buckets)
                self._sum = 0.0
                self._count = 0
            elif self._family.kind == "counter":
                self._value = 0.0
            else:
                self._value = None


class MetricFamily:
    """A named metric with a fixed label-name tuple; children per label
    value combination. Label-less use (``family.inc()``) routes to the
    ``()`` child so callers never see the two-level structure unless
    they label."""

    def __init__(self, name: str, kind: str, help: str = "",
                 unit: str = "", labelnames: Tuple[str, ...] = (),
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                 window: int = 1024):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln):
                raise ValueError(f"invalid label name {ln!r} on {name}")
        if kind not in ("counter", "gauge", "histogram"):
            raise ValueError(f"invalid metric kind {kind!r}")
        self.name = name
        self.kind = kind
        self.help = help
        self.unit = unit
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        self.window = window
        self._children: Dict[Tuple[str, ...], _Child] = {}
        self._lock = threading.Lock()

    def _key(self, labels: dict) -> Tuple[str, ...]:
        if tuple(sorted(labels)) != tuple(sorted(self.labelnames)):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, "
                f"got {tuple(sorted(labels))}")
        return tuple(str(labels[n]) for n in self.labelnames)

    def labels(self, **labels: object) -> _Child:
        key = self._key(labels)
        with self._lock:
            child = self._children.get(key)
            if child is None:
                child = self._children[key] = _Child(self, key)
            return child

    def peek(self, **labels: object) -> Optional[_Child]:
        """The child for this label combination IF it exists — never
        creates one. The read-side twin of labels(): Engine.stats()
        reads series this way so a feature that never recorded (prefix
        cache off, spec off) never mints an empty series that the
        exposition would then render as a placeholder (the /metrics
        label-hygiene rule, pinned by test)."""
        key = self._key(labels)
        with self._lock:
            return self._children.get(key)

    def _default(self) -> _Child:
        if self.labelnames:
            raise ValueError(
                f"{self.name} is labeled {self.labelnames}; use .labels()")
        return self.labels()

    # label-less conveniences: WRITES create the () child; READS peek
    # (a family nothing ever recorded to must stay series-less so the
    # exposition skips it — reading stats() is not recording).
    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def _set_total(self, value: float) -> None:
        self._default()._set_total(value)

    def set(self, value: float) -> None:
        self._default().set(value)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def mean(self):
        child = self.peek()
        return None if child is None else child.mean()

    def percentiles(self, ps: tuple = (50, 90, 99)):
        child = self.peek()
        return None if child is None else child.percentiles(ps)

    @property
    def value(self):
        child = self.peek()
        return None if child is None else child.value

    def series(self) -> List[Tuple[Tuple[str, ...], _Child]]:
        with self._lock:
            return sorted(self._children.items())

    def reset(self) -> None:
        for _, child in self.series():
            child.reset()


class MetricRegistry:
    """A namespace of families plus collection-time callbacks.

    Re-registering a name returns the existing family (process-wide
    semantics: any module may say ``registry.counter("x", ...)`` and get
    the shared series) — but a kind or label mismatch is a programming
    error and raises rather than silently forking the metric.
    """

    def __init__(self):
        self._families: Dict[str, MetricFamily] = {}
        self._collectors: List[Callable[[], None]] = []
        self._lock = threading.Lock()

    # -- family constructors ---------------------------------------------
    def _family(self, name: str, kind: str, help: str, unit: str,
                labelnames: Tuple[str, ...], **kw) -> MetricFamily:
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.labelnames != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.labelnames}, not "
                        f"{kind}{tuple(labelnames)}")
                return fam
            fam = MetricFamily(name, kind, help=help, unit=unit,
                               labelnames=tuple(labelnames), **kw)
            self._families[name] = fam
            return fam

    def counter(self, name: str, help: str = "", unit: str = "",
                labelnames: Iterable[str] = ()) -> MetricFamily:
        return self._family(name, "counter", help, unit, tuple(labelnames))

    def gauge(self, name: str, help: str = "", unit: str = "",
              labelnames: Iterable[str] = ()) -> MetricFamily:
        return self._family(name, "gauge", help, unit, tuple(labelnames))

    def histogram(self, name: str, help: str = "", unit: str = "",
                  labelnames: Iterable[str] = (),
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS,
                  window: int = 1024) -> MetricFamily:
        return self._family(name, "histogram", help, unit,
                            tuple(labelnames), buckets=buckets,
                            window=window)

    def add_collector(self, fn: Callable[[], None]) -> None:
        """Register a collection-time callback (runs per snapshot/scrape,
        NEVER in a hot loop) that copies externally-owned state — engine
        plain-int counters, tracecheck ledgers — into families."""
        with self._lock:
            self._collectors.append(fn)

    def collect(self) -> None:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            fn()

    def families(self) -> List[MetricFamily]:
        with self._lock:
            return [self._families[n] for n in sorted(self._families)]

    # -- views ------------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-ready view of every family after running collectors."""
        self.collect()
        out: dict = {}
        for fam in self.families():
            series = []
            for values, child in fam.series():
                labels = dict(zip(fam.labelnames, values))
                if fam.kind == "histogram":
                    _, hsum, hcount = child.hist_state()
                    series.append({
                        "labels": labels,
                        "count": hcount,
                        "sum": hsum,
                        "mean": child.mean(),
                        "percentiles": child.percentiles((50, 90, 99)),
                    })
                else:
                    if child.value is None:
                        continue  # unset gauge: no sample
                    series.append({"labels": labels, "value": child.value})
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "unit": fam.unit, "series": series}
        return out

    def prometheus_text(self) -> str:
        self.collect()
        return render_prometheus_families(self.families())


def render_prometheus_families(families: Iterable[MetricFamily]) -> str:
    """Text exposition format (version 0.0.4) over already-collected
    families — the shared renderer behind ``registry.prometheus_text()``
    and serve/http.py's multi-registry ``GET /metrics``."""
    lines: List[str] = []
    for fam in families:
        series = fam.series()
        if not series:
            continue
        if all(fam.kind != "histogram" and c.value is None
               for _, c in series):
            continue
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        if fam.kind == "histogram":
            hist_states = {}
            for values, child in series:
                buckets, hsum, hcount = child.hist_state()
                hist_states[values] = (hsum, hcount)
                cum = 0
                for le, n in zip(fam.buckets, buckets):
                    cum += n
                    lab = _labelstr(fam.labelnames, values,
                                    f'le="{_fmt(le)}"')
                    lines.append(f"{fam.name}_bucket{lab} {cum}")
                lab = _labelstr(fam.labelnames, values, 'le="+Inf"')
                lines.append(f"{fam.name}_bucket{lab} {hcount}")
                lab = _labelstr(fam.labelnames, values)
                lines.append(f"{fam.name}_sum{lab} {_fmt(hsum)}")
                lines.append(f"{fam.name}_count{lab} {hcount}")
            # The recent-window percentile view, as its own summary
            # family: histogram_quantile() needs rate() over scrapes,
            # but an operator mid-incident (or the CI smoke) wants the
            # current p50/p90/p99 directly.
            wname = f"{fam.name}_window"
            lines.append(f"# TYPE {wname} summary")
            for values, child in series:
                pct = child.percentiles((50, 90, 99)) or {}
                for p, q in (("p50", "0.5"), ("p90", "0.9"),
                             ("p99", "0.99")):
                    if p in pct:
                        lab = _labelstr(fam.labelnames, values,
                                        f'quantile="{q}"')
                        lines.append(f"{wname}{lab} {_fmt(pct[p])}")
                hsum, hcount = hist_states[values]
                lab = _labelstr(fam.labelnames, values)
                lines.append(f"{wname}_sum{lab} {_fmt(hsum)}")
                lines.append(f"{wname}_count{lab} {hcount}")
        else:
            for values, child in series:
                if child.value is None:
                    continue
                lab = _labelstr(fam.labelnames, values)
                lines.append(f"{fam.name}{lab} {_fmt(child.value)}")
    return "\n".join(lines) + ("\n" if lines else "")


def render_prometheus(*registries: MetricRegistry) -> str:
    """One exposition over several registries (engine + process-global +
    loop in serve/http.py). Duplicate family names across registries
    would emit conflicting TYPE lines, so they raise loudly here instead
    of producing a page Prometheus rejects at scrape time."""
    fams: List[MetricFamily] = []
    seen: Dict[str, MetricFamily] = {}
    for reg in registries:
        reg.collect()
        for fam in reg.families():
            if fam.name in seen:
                raise ValueError(
                    f"metric {fam.name!r} exported by two registries")
            seen[fam.name] = fam
            fams.append(fam)
    return render_prometheus_families(fams)


# Process-global registry: the home of metrics with no natural owner
# object — the tracecheck host-sync/compile ledgers, warn_once firings.
# Engines and Trainers own per-instance registries (tests spin up many)
# and serve/http.py renders both on /metrics.
_GLOBAL = MetricRegistry()


def global_registry() -> MetricRegistry:
    return _GLOBAL
