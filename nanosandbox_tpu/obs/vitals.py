"""Process vitals for /metrics: host health next to engine counters.

A scrape that shows TTFT p99 climbing but nothing about WHY is half a
dashboard — RSS creep, fd exhaustion and device-buffer growth are the
classic serving slow-deaths, and none of them live in any engine
counter.  ``register_process_vitals`` adds collection-time gauges to a
registry (the process-global one by default):

  process_resident_memory_bytes   current RSS (/proc/self/statm;
                                  ru_maxrss high-water fallback)
  process_open_fds                /proc/self/fd count
  process_start_time_seconds      unix time this module first registered
  process_uptime_seconds          seconds since then
  jax_live_buffer_bytes           sum of nbytes over jax.live_arrays()
  jax_live_buffer_count           len(jax.live_arrays())

Everything is sampled AT COLLECTION TIME (per scrape) — zero hot-loop
cost, the PR 5 collector contract.  This module imports no jax: the
buffer gauges read ``jax.live_arrays()`` only when jax is ALREADY in
sys.modules (a process that never touched jax must not initialize a
backend because Prometheus scraped it), and ``nbytes`` is shape
metadata — no device sync, so the no-new-host-syncs ledger assertion
holds with vitals registered.
"""

from __future__ import annotations

import os
import resource
import sys
import time
from typing import Optional

from nanosandbox_tpu.obs.registry import MetricRegistry, global_registry

_START_WALL = time.time()


def _rss_bytes() -> Optional[int]:
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * (resource.getpagesize())
    except (OSError, ValueError, IndexError):
        pass
    # Portable fallback: the high-water mark (KB on Linux, bytes on
    # macOS — normalize Linux's KB).
    rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    if rss <= 0:
        return None
    return rss * 1024 if sys.platform.startswith("linux") else rss


def _open_fds() -> Optional[int]:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return None


def register_process_vitals(registry: Optional[MetricRegistry] = None,
                            ) -> MetricRegistry:
    """Idempotently add the vitals gauges + collector to ``registry``
    (default: the process-global registry). Returns the registry."""
    reg = registry if registry is not None else global_registry()
    # Once per registry OBJECT (a flag on the instance, not an id() set:
    # a recycled address must not silently skip a fresh registry) —
    # re-registering would double-add the collector; the families
    # themselves are idempotent by registry semantics.
    if getattr(reg, "_vitals_registered", False):
        return reg
    reg._vitals_registered = True
    g_rss = reg.gauge("process_resident_memory_bytes",
                      "Resident set size of this process.", unit="bytes")
    g_fds = reg.gauge("process_open_fds",
                      "Open file descriptors of this process.")
    g_start = reg.gauge("process_start_time_seconds",
                        "Unix time vitals were first registered.",
                        unit="seconds")
    g_uptime = reg.gauge("process_uptime_seconds",
                         "Seconds since vitals were first registered.",
                         unit="seconds")
    g_jax_bytes = reg.gauge(
        "jax_live_buffer_bytes",
        "Total bytes of live jax arrays at collection time.",
        unit="bytes")
    g_jax_count = reg.gauge("jax_live_buffer_count",
                            "Live jax arrays at collection time.")

    def collect() -> None:
        rss = _rss_bytes()
        if rss is not None:
            g_rss.set(rss)
        fds = _open_fds()
        if fds is not None:
            g_fds.set(fds)
        g_start.set(_START_WALL)
        g_uptime.set(time.time() - _START_WALL)
        jax = sys.modules.get("jax")
        if jax is not None:
            try:
                arrs = jax.live_arrays()
                g_jax_count.set(len(arrs))
                # nbytes is ShapeDtype metadata — reading it syncs
                # nothing (the no-new-host-syncs pin covers this).
                g_jax_bytes.set(float(sum(a.nbytes for a in arrs)))
            except Exception:
                pass            # deleted-buffer races mid-iteration
    reg.add_collector(collect)
    return reg
