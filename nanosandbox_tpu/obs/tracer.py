"""Span tracer: host-side begin/end spans with Chrome trace-event export.

Answers "why was THIS request slow" — the causality question the metric
registry's aggregates cannot. The engine records spans from
dispatch-time state it already holds on the host (admission wave
composition, decode step tick, spec verify round), so tracing adds NO
device readback and no host sync: every recorded value is an
already-host-resident int/float/str (the jaxlint contract), and a
record is one dict build + one deque append under a lock.

Semantics that matter for the pipelined engine: a ``decode_step`` span
is OPENED at dispatch and CLOSED at its retire — which, with one step
in flight, happens AFTER the next step's dispatch. The exported
timeline therefore shows step k overlapping step k+1, which is the
truth of the pipeline, not a prettified synchronous story. Request
spans (``queued`` -> ``generate``) carry the request id; eviction +
backfill reuse a slot but never a span, so an exported request track is
exactly one request's life.

Export is Chrome trace-event JSON (the ``{"traceEvents": [...]}``
variant), loadable in Perfetto / chrome://tracing: complete events
(``ph: "X"``) on one track per request (tid = rid + 1, named) plus an
engine track (tid 0) for waves/steps/verify rounds.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional

ENGINE_TRACK = 0  # tid for engine-wide spans; request rid r rides tid r+1


@dataclass
class Span:
    sid: int
    name: str
    cat: str
    t0: float                       # time.monotonic() at begin
    dur: Optional[float] = None     # seconds; None while open
    rid: Optional[int] = None
    args: dict = field(default_factory=dict)

    @property
    def t1(self) -> Optional[float]:
        return None if self.dur is None else self.t0 + self.dur


class SpanTracer:
    """Bounded in-memory ring of completed spans + the open-span table.

    ``enabled=False`` turns every call into a constant-time no-op (the
    overhead-pin test measures the enabled path; the escape hatch exists
    for experiments, not because the enabled path is hot)."""

    def __init__(self, capacity: int = 8192, enabled: bool = True):
        self.enabled = enabled
        self._t0 = time.monotonic()   # export epoch: ts are relative
        self._ring: deque = deque(maxlen=capacity)
        self._open: Dict[int, Span] = {}
        self._sid = itertools.count(1)
        self._lock = threading.Lock()

    # ------------------------------------------------------------- record
    def begin(self, name: str, cat: str = "engine", *,
              rid: Optional[int] = None, args: Optional[dict] = None,
              ) -> int:
        """Open a span; returns its id (0 when disabled). The caller
        holds only the sid — ending by id keeps the hot path free of
        span-object bookkeeping."""
        if not self.enabled:
            return 0
        sp = Span(sid=next(self._sid), name=name, cat=cat,
                  t0=time.monotonic(), rid=rid, args=dict(args or {}))
        with self._lock:
            self._open[sp.sid] = sp
        return sp.sid

    def end(self, sid: int, args: Optional[dict] = None) -> None:
        """Close a span by id. Unknown/zero sids are ignored so a
        disabled tracer's 0 handles (and double-ends on teardown paths)
        never raise in the serving loop."""
        if not self.enabled or sid == 0:
            return
        now = time.monotonic()
        with self._lock:
            sp = self._open.pop(sid, None)
            if sp is None:
                return
            sp.dur = now - sp.t0
            if args:
                sp.args.update(args)
            self._ring.append(sp)

    def instant(self, name: str, cat: str = "engine", *,
                rid: Optional[int] = None,
                args: Optional[dict] = None) -> None:
        """A zero-duration marker (renders as a thin slice)."""
        if not self.enabled:
            return
        sp = Span(sid=next(self._sid), name=name, cat=cat,
                  t0=time.monotonic(), dur=0.0, rid=rid,
                  args=dict(args or {}))
        with self._lock:
            self._ring.append(sp)

    # ------------------------------------------------------------ queries
    def spans(self, rid: Optional[int] = None,
              last_s: Optional[float] = None) -> List[Span]:
        """Completed spans, optionally filtered to one request id and/or
        the trailing ``last_s`` seconds, oldest first."""
        with self._lock:
            out = list(self._ring)
        if rid is not None:
            out = [s for s in out if s.rid == rid]
        if last_s is not None:
            horizon = time.monotonic() - last_s
            out = [s for s in out if s.t1 is not None and s.t1 >= horizon]
        return out

    def _open_snapshot(self, rid: int) -> List[Span]:
        """Point-in-time copies of one request's still-open spans, with
        duration-so-far and an ``incomplete`` marker. /trace?rid=N must
        show a request SITTING IN THE QUEUE — that is the admission-
        pressure diagnosis the endpoint exists for — not 404 until the
        request is done."""
        now = time.monotonic()
        with self._lock:
            return [Span(sid=sp.sid, name=sp.name, cat=sp.cat, t0=sp.t0,
                         dur=now - sp.t0, rid=sp.rid,
                         args={**sp.args, "incomplete": True})
                    for sp in self._open.values() if sp.rid == rid]

    def open_count(self) -> int:
        """Spans begun but not ended — the orphan detector: after a
        drain this must be zero (a leak means some finish path forgot
        its end, exactly the eviction/backfill bug class)."""
        with self._lock:
            return len(self._open)

    def clear(self) -> None:
        """Drop completed spans (benchmarks clear between warmup and the
        timed window, like reset_latency_stats). Open spans survive —
        they belong to in-flight work."""
        with self._lock:
            self._ring.clear()

    # ------------------------------------------------------------- export
    def export_chrome(self, rid: Optional[int] = None,
                      last_s: Optional[float] = None) -> dict:
        """Chrome trace-event JSON for Perfetto / chrome://tracing.

        With ``rid``: that request's spans PLUS the engine-track spans
        overlapping its lifetime (the decode steps / waves / verify
        rounds that explain its latency). Without: everything in the
        ring (optionally time-bounded)."""
        spans = self.spans(last_s=last_s)
        if rid is not None:
            mine = ([s for s in spans if s.rid == rid]
                    + self._open_snapshot(rid))
            if mine:
                lo = min(s.t0 for s in mine)
                hi = max(s.t1 for s in mine)
                engine_ctx = [s for s in spans
                              if s.rid is None and s.t1 is not None
                              and s.t1 >= lo and s.t0 <= hi]
                spans = sorted(mine + engine_ctx, key=lambda s: s.t0)
            else:
                spans = []
        events: List[dict] = []
        tracks: Dict[int, str] = {}
        for s in spans:
            tid = ENGINE_TRACK if s.rid is None else s.rid + 1
            tracks.setdefault(
                tid, "engine" if s.rid is None else f"request {s.rid}")
            ev = {
                "name": s.name,
                "cat": s.cat,
                "ph": "X",
                "ts": round((s.t0 - self._t0) * 1e6, 3),
                "dur": round((s.dur or 0.0) * 1e6, 3),
                "pid": 0,
                "tid": tid,
                "args": dict(s.args),
            }
            if s.rid is not None:
                ev["args"]["rid"] = s.rid
            events.append(ev)
        meta = [{"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
                 "args": {"name": name}}
                for tid, name in sorted(tracks.items())]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}
