"""Request-lifecycle flight recorder + anomaly watchdogs.

The metric registry answers "how slow is it" in aggregate and the span
tracer answers "when did things run" — neither answers **"what happened
to request 4812"** after the fact, nor notices that the engine has
quietly started thrashing its KV pool.  Two host-side pieces close
that gap (ISSUE 10):

  * ``FlightRecorder`` — a bounded, lock-light ledger of per-request
    lifecycle events.  Every request leaves a track::

        submit -> queue -> [block_stall*] -> block_reserve -> admit ->
        prefill[hit|miss] -> retire* -> evict -> finish
                 (or terminal: reject at submit / shed from the queue /
                 failed on permanent-failure drain)

    Under fault recovery (ISSUE 11) a track may additionally carry
    fault / poison / quarantine / requeue / recover events — the
    request re-enters at ``queue`` and STILL reaches exactly one
    terminal (the fuzz pin covers interrupted-and-resumed requests).

    Under disaggregated serving (ISSUE 16) a migrated track spans TWO
    engines' ledgers (rids are tier-namespaced: ``prefill:7`` /
    ``decode:3``) and carries the handoff events:

        export   — prefill side: first token sampled, request parked
                   in migration limbo (slot freed, blocks pinned)
        migrate  — the chain moved (``blocks``/chain length,
                   ``bytes``, ``src``/``dst`` engine)
        adopt    — decode side: chain re-admitted as a prefix hit
                   through the rung-1 admit program (zero prefill)
        requeue  — the handoff failed (dst death, backpressure
                   timeout); the request re-enters colocated on the
                   source, same rid, same first token

    The exactly-once fuzz extends across the handoff: merged over
    both tiers (``DisaggPair.merged_flight_events``), each namespaced
    rid still reaches exactly one terminal, including when
    ``replica_down`` fires mid-migration.

    Each event is one small dict recorded from ALREADY-HOST-RESIDENT
    dispatch-time state (ints/floats the engine holds anyway), so the
    pipelined loop gains no host sync and jaxlint stays clean.  A
    record is a dict build + deque append under a lock — single-digit
    microseconds, pinned by test at < 50 us/event.  Export is JSONL
    (one event per line) or the ``GET /debug/requests`` JSON view.

  * ``WatchdogPanel`` — cheap per-step anomaly detectors over the
    engine's plain-int state (TTFT spike vs a rolling baseline,
    admission stalled on KV blocks, prefix-cache eviction thrash,
    post-warmup retrace, stuck slot).  A trip increments
    ``watchdog_trips_total{kind=}`` and snapshots the flight ledger +
    span ring + engine stats to a dump directory — the black box an
    operator opens AFTER the incident, when /metrics only says "it was
    slow for a while".

Nothing here imports jax; everything is stdlib + plain Python state
(the obs/ contract).
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
from collections import deque
from typing import Dict, List, Optional

# Terminal lifecycle events: every submitted request must reach EXACTLY
# one of these (the no-orphan contract tests fuzz against, including
# across engine recoveries — a fault-interrupted, re-admitted request
# still terminates exactly once). 'failed' is the permanent-failure
# drain (recovery exhausted; partial tokens salvaged).
TERMINAL_EVENTS = ("finish", "reject", "shed", "failed")


class FlightRecorder:
    """Bounded ring of per-request lifecycle events.

    ``record()`` is the hot-path entry: one dict build + one deque
    append under a lock.  Queries (``events``, ``to_jsonl``, ``dump``)
    copy the ring under the same lock and filter on the copy, so an
    HTTP debug handler never races the engine thread's appends.
    """

    def __init__(self, capacity: int = 65536, enabled: bool = True,
                 namespace: Optional[str] = None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.enabled = enabled
        self.capacity = capacity
        # Fleet namespacing (ISSUE 15): with a namespace (the replica
        # id), every recorded rid becomes "<namespace>:<rid>", so N
        # replicas' ledgers merge into ONE JSONL trace that stays
        # exactly-once analyzable — replica 0's request 7 and replica
        # 1's request 7 are different tracks, not a double terminal.
        # Engine-internal int-rid lookups keep working: queries
        # normalize through the same mapping.
        self.namespace = namespace
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        # Epoch pair: events carry monotonic "t" (orderable, immune to
        # clock steps); exports add a wall-clock view computed from the
        # pairing so JSONL lines correlate with external logs.
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()
        self.recorded = 0            # total ever (ring rotation visible)
        self._cleared = 0            # events removed by clear(), not rotation

    def _rid(self, rid):
        """Apply the namespace to an engine-local int rid; strings (an
        already-namespaced id, or a caller's own scheme) pass through."""
        if rid is None or self.namespace is None or isinstance(rid, str):
            return rid
        return f"{self.namespace}:{rid}"

    # ------------------------------------------------------------ record
    def record(self, ev: str, rid: Optional[int] = None,
               step: Optional[int] = None, **fields) -> None:
        """Append one event. ``rid`` None is legal for events with no
        request id (a reject happens before one is assigned)."""
        if not self.enabled:
            return
        e: dict = {"t": time.monotonic(), "ev": ev, "rid": self._rid(rid)}
        if step is not None:
            e["step"] = step
        if fields:
            e.update(fields)
        with self._lock:
            self._ring.append(e)
            self.recorded += 1

    # ----------------------------------------------------------- queries
    def _snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def events(self, rid: Optional[int] = None,
               last_s: Optional[float] = None) -> List[dict]:
        """Event copies (oldest first) with the wall-clock view added:
        ``t`` becomes seconds since recorder start, ``wall`` the unix
        timestamp. Optionally filtered to one rid / trailing window."""
        out = self._snapshot()
        if rid is not None:
            rid = self._rid(rid)
            out = [e for e in out if e.get("rid") == rid]
        if last_s is not None:
            horizon = time.monotonic() - last_s
            out = [e for e in out if e["t"] >= horizon]
        return [{**e, "t": round(e["t"] - self._t0_mono, 6),
                 "wall": round(e["t"] - self._t0_mono + self._t0_wall, 6)}
                for e in out]

    def to_jsonl(self, rid: Optional[int] = None,
                 last_s: Optional[float] = None) -> str:
        """One JSON object per line — the dump format obs_smoke.py
        schema-validates and the watchdogs write on a trip."""
        lines = [json.dumps(e, sort_keys=True)
                 for e in self.events(rid=rid, last_s=last_s)]
        return "\n".join(lines) + ("\n" if lines else "")

    def dump(self, path: str) -> int:
        """Write the ledger as JSONL; returns the event count."""
        text = self.to_jsonl()
        with open(path, "w") as f:
            f.write(text)
        return 0 if not text else text.count("\n")

    def terminals(self, rid: int) -> List[str]:
        """Terminal event names recorded for one rid — the no-orphan
        test asserts len == 1 for every request the engine ever saw."""
        rid = self._rid(rid)
        return [e["ev"] for e in self._snapshot()
                if e.get("rid") == rid and e["ev"] in TERMINAL_EVENTS]

    def counts(self) -> Dict[str, int]:
        """Event counts by kind over the current ring (debug view)."""
        out: Dict[str, int] = {}
        for e in self._snapshot():
            out[e["ev"]] = out.get(e["ev"], 0) + 1
        return out

    def clear(self) -> None:
        """Drop recorded events (benchmarks clear between warmup and
        the timed window, like reset_latency_stats)."""
        with self._lock:
            self._cleared += len(self._ring)
            self._ring.clear()

    def stats(self) -> dict:
        with self._lock:
            # dropped = lost to RING ROTATION only; deliberately cleared
            # events (warmup hygiene) are not capacity pressure.
            dropped = self.recorded - self._cleared - len(self._ring)
            return {"enabled": self.enabled, "capacity": self.capacity,
                    "namespace": self.namespace,
                    "events": len(self._ring), "recorded": self.recorded,
                    "dropped": max(0, dropped)}


class WatchdogPanel:
    """Anomaly detectors over the engine's host-side state.

    The panel is event-fed (``on_ttft`` at each admission) plus polled
    (``check`` from the engine step, every ``check_interval_steps``) —
    each poll is a handful of int compares and an O(num_slots) scan, so
    leaving it on costs nothing measurable.  Detectors:

      ttft_spike          TTFT > spike_factor x rolling-median baseline
                          (and above ttft_min_s — tiny absolute TTFTs
                          never page) once >= min_samples exist.
      admission_stall     the FIFO head deferred on KV-block
                          availability for stall_trip_steps consecutive
                          polls — pool pressure is now user-visible
                          queueing, not a transient.
      pool_thrash         prefix-cache evictions exceeding the whole
                          pool within one poll window: allocations are
                          fighting the cache block-for-block, so hits
                          are being destroyed as fast as they form.
      post_freeze_retrace a compile trace AFTER mark_steady() — the
                          shape-leak class the tracecheck freeze turns
                          into a crash; deployments that keep lazy
                          compiles (--warmup=buckets) get the page
                          instead.
      stuck_slot          an active slot with no retired token for
                          stuck_slot_s — a wedged device or a dead
                          pipeline, caught before the client timeout.
      stalled_step        ONE engine step whose wall time exceeded
                          stalled_step_s (fed by Engine.step's own
                          clock) — a wedged dispatch/readback that DID
                          eventually return; the recovery supervisor
                          treats it (with stuck_slot) as recoverable.

    A trip increments ``watchdog_trips_total{kind=}`` on the engine's
    registry and (rate-limited per kind by ``cooldown_s``) snapshots
    the flight ledger, the span ring, and ``engine.stats()`` into
    ``dump_dir/<kind>-<n>-<unixtime>/`` — flight-<kind>.jsonl,
    trace-<kind>.json, meta-<kind>.json.  Dumps are SERIALIZED by a
    lock and every file carries the trip kind: two trips of different
    kinds racing (an HTTP-thread feed against the engine thread's
    poll) can no longer interleave writes into one snapshot.  Dump
    failures are recorded, never raised: the serving loop outlives its
    black box."""

    KINDS = ("ttft_spike", "admission_stall", "pool_thrash",
             "post_freeze_retrace", "stuck_slot", "stalled_step")

    def __init__(self, engine, *, dump_dir: Optional[str] = None,
                 enabled: bool = True,
                 cooldown_s: float = 60.0,
                 check_interval_steps: int = 16,
                 ttft_spike_factor: float = 8.0,
                 ttft_min_samples: int = 32,
                 ttft_min_s: float = 0.25,
                 ttft_baseline_window: int = 128,
                 stall_trip_steps: int = 64,
                 thrash_factor: float = 1.0,
                 stuck_slot_s: float = 120.0,
                 stalled_step_s: float = 30.0):
        self.engine = engine
        self.enabled = enabled
        self.dump_dir = dump_dir
        self.cooldown_s = cooldown_s
        self.check_interval_steps = max(1, int(check_interval_steps))
        self.ttft_spike_factor = ttft_spike_factor
        self.ttft_min_samples = ttft_min_samples
        self.ttft_min_s = ttft_min_s
        self.stall_trip_steps = stall_trip_steps
        self.thrash_factor = thrash_factor
        self.stuck_slot_s = stuck_slot_s
        self.stalled_step_s = stalled_step_s
        self.trips: Dict[str, int] = {}
        self.last_trip: Optional[dict] = None
        self.dump_errors = 0
        self._ttft_ring: deque = deque(maxlen=ttft_baseline_window)
        self._last_dump: Dict[str, float] = {}
        # Dumps serialize: concurrent trips of different kinds must not
        # interleave their writes (regression-pinned).
        self._dump_lock = threading.Lock()
        self._last_check_step = -1
        self._stall_mark = 0         # block_pool.stall_steps at last poll
        self._stall_polls = 0        # consecutive polls with stall growth
        self._evict_mark = 0
        self._steady_traces: Optional[int] = None
        # The trip counter family lives on the engine registry so a
        # scrape sees trips next to the latency they explain; children
        # appear only when a kind actually trips (label hygiene).
        self._c_trips = engine.metrics.counter(
            "watchdog_trips_total",
            "Anomaly watchdog trips, by detector kind.",
            labelnames=("kind",))

    # ------------------------------------------------------------- feeds
    def on_ttft(self, ttft_s: float) -> None:
        """Called at each admission with the just-observed TTFT (an
        already-host-resident float). Baseline = rolling median."""
        if not self.enabled:
            return
        ring = self._ttft_ring
        if (len(ring) >= self.ttft_min_samples
                and ttft_s >= self.ttft_min_s):
            baseline = sorted(ring)[len(ring) // 2]
            if baseline > 0 and ttft_s > self.ttft_spike_factor * baseline:
                self._trip("ttft_spike",
                           {"ttft_s": ttft_s, "baseline_s": baseline,
                            "factor": ttft_s / baseline})
        ring.append(ttft_s)

    def on_step_time(self, dt_s: float) -> None:
        """Called by the engine with each step's wall time: one step
        past ``stalled_step_s`` is a wedged dispatch (a hung device, a
        runaway host stall), not load — load shows up as MANY normal
        steps. One float compare when healthy."""
        if not self.enabled:
            return
        if dt_s > self.stalled_step_s:
            self._trip("stalled_step",
                       {"step_s": dt_s, "limit_s": self.stalled_step_s})

    def mark_steady(self) -> None:
        """Declare the compile set complete (serve __main__ calls this
        after warmup): any trace observed past this point trips
        post_freeze_retrace."""
        self._steady_traces = sum(self.engine.tracecheck.counts().values())

    def check(self, now: Optional[float] = None) -> None:
        """Poll the cheap detectors; called once per engine step and
        self-throttled to every check_interval_steps."""
        if not self.enabled:
            return
        step = self.engine.steps
        if step - self._last_check_step < self.check_interval_steps:
            return
        self._last_check_step = step
        now = time.monotonic() if now is None else now
        # stuck slot: an active row whose last retired token is old.
        for slot, st in list(self.engine._active.items()):
            if now - st.last_t > self.stuck_slot_s:
                self._trip("stuck_slot",
                           {"slot": slot, "rid": st.req.rid,
                            "idle_s": now - st.last_t,
                            "tokens": len(st.tokens)})
                break                     # one page per poll is plenty
        pool = self.engine.block_pool
        if pool is not None:
            # admission stall: the head deferred on blocks in EVERY
            # recent poll window — a transient resets the streak. A
            # counter moving BACKWARDS means the pool ledger was reset
            # (reset_latency_stats between bench points / post-warmup):
            # resync the mark instead of comparing against a stale high
            # value that would blind the detector.
            stalls = pool.stall_steps
            if stalls < self._stall_mark:
                self._stall_mark = stalls
                self._stall_polls = 0
            if self._evict_mark > pool.evicted_blocks:
                self._evict_mark = pool.evicted_blocks
            if stalls > self._stall_mark:
                self._stall_polls += 1
                if (self._stall_polls * self.check_interval_steps
                        >= self.stall_trip_steps):
                    self._trip("admission_stall",
                               {"stall_steps": stalls,
                                "free_blocks": pool.free_blocks,
                                "queued": self.engine.sched.queued})
                    self._stall_polls = 0
            else:
                self._stall_polls = 0
            self._stall_mark = stalls
            # pool thrash: evictions within one window exceeding the
            # whole pool (x thrash_factor).
            ev = pool.evicted_blocks
            if (ev - self._evict_mark
                    > self.thrash_factor * pool.num_blocks):
                self._trip("pool_thrash",
                           {"evicted_in_window": ev - self._evict_mark,
                            "num_blocks": pool.num_blocks})
            self._evict_mark = ev
        if self._steady_traces is not None:
            total = sum(self.engine.tracecheck.counts().values())
            if total > self._steady_traces:
                self._trip("post_freeze_retrace",
                           {"traces": total,
                            "steady_traces": self._steady_traces})
                self._steady_traces = total     # page once per new trace

    # -------------------------------------------------------------- trip
    def _trip(self, kind: str, info: dict) -> None:
        self.trips[kind] = self.trips.get(kind, 0) + 1
        self._c_trips.labels(kind=kind).inc()
        now = time.monotonic()
        entry = {"kind": kind, "n": self.trips[kind], "wall": time.time(),
                 **info}
        last = self._last_dump.get(kind)
        if last is None or now - last >= self.cooldown_s:
            self._last_dump[kind] = now
            entry["dump"] = self._dump(kind, entry)
        self.last_trip = entry

    def _dump(self, kind: str, info: dict) -> Optional[str]:
        """Snapshot flight + spans + stats to the dump dir; returns the
        dump path, or None when writing failed (recorded, not raised —
        a full disk must not kill the serving loop).

        Serialized under ``_dump_lock`` and every file is suffixed with
        the trip kind: two near-simultaneous trips of DIFFERENT kinds
        (e.g. an on_ttft feed racing the per-step poll from another
        thread in tests/benches) used to be able to interleave their
        writes into one snapshot directory; now each write completes
        whole, into unambiguously-named files (regression-pinned)."""
        with self._dump_lock:
            try:
                if self.dump_dir is None:
                    # lockcheck: disable=blocking-under-lock -- the
                    # dump I/O under _dump_lock IS the feature: this
                    # lock exists solely to serialize whole snapshot
                    # writes against each other (docstring above,
                    # regression-pinned), nothing latency-sensitive
                    # ever contends on it, and trips are cooldown-
                    # limited cold events.
                    self.dump_dir = tempfile.mkdtemp(
                        prefix="serve-watchdog-")
                d = os.path.join(
                    self.dump_dir,
                    f"{kind}-{self.trips[kind]}-{int(time.time())}")
                # lockcheck: disable=blocking-under-lock -- same
                # deliberate serialization as the mkdtemp above.
                os.makedirs(d, exist_ok=True)
                self.engine.flight.dump(
                    os.path.join(d, f"flight-{kind}.jsonl"))
                with open(os.path.join(d, f"trace-{kind}.json"), "w") as f:
                    json.dump(self.engine.tracer.export_chrome(), f)
                with open(os.path.join(d, f"meta-{kind}.json"), "w") as f:
                    json.dump({"trip": info, "trips": dict(self.trips),
                               "stats": self.engine.stats()}, f,
                              default=str)
                return d
            except OSError:
                self.dump_errors += 1
                return None

    # ------------------------------------------------------------- views
    def reset(self) -> None:
        """Clear the rolling TTFT baseline (warmup samples must not
        anchor it) without forgetting trips already counted."""
        self._ttft_ring.clear()
        self._stall_polls = 0

    def stats(self) -> dict:
        return {"enabled": self.enabled,
                "trips": dict(self.trips),
                "last_trip": self.last_trip,
                "dump_dir": self.dump_dir,
                "dump_errors": self.dump_errors}
