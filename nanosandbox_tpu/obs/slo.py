"""Per-request SLO accounting: deadlines, attainment, goodput.

ROADMAP item 3's scheduler work will be judged on "goodput under
overload" — which needs a ledger BEFORE it needs a policy.  This module
is that ledger (ISSUE 10): each request may carry a ``deadline_s``
(submit-to-finish budget) and an ``slo_class`` label; at its terminal
event the engine records the outcome here, and the ledger publishes:

  * ``serve_slo_requests_total{slo_class=,outcome=}`` — outcome is
    ``met`` (finished within deadline), ``missed`` (finished late) or
    ``shed`` (dropped from the queue after its deadline expired);
  * ``serve_goodput_tokens_total{slo_class=}`` — tokens of requests
    that FINISHED WITHIN DEADLINE; the overload sweep's goodput is
    rate() over this, and `bench.py --mode=serve` pins it;
  * ``serve_slo_attainment{slo_class=}`` — met / (met+missed+shed),
    mirrored at collection time;
  * ``serve_deadline_margin_seconds{slo_class=,prefix=}`` — histogram
    of (deadline - end-to-end latency) at finish, split by prefix-cache
    outcome: negative margin IS the miss, and the hit/miss split shows
    how much of the attainment budget the prefix cache is buying.

Hot-loop cost follows the PR 5 contract: terminal events update plain
ints (+ one histogram observe); counters and the attainment gauge are
mirrored by a collector per scrape.  Requests WITHOUT a deadline are
not SLO-tracked at all — their label children are never created, so a
deployment that never sets deadlines scrapes no placeholder SLO series
(the label-hygiene rule).  No jax import (the obs/ contract).
"""

from __future__ import annotations

import re
from typing import Dict, Optional

# Margin buckets (seconds): symmetric around 0 — the miss boundary —
# so histogram_quantile and a burn-rate query both resolve "how late".
MARGIN_BUCKETS = (-60.0, -10.0, -5.0, -1.0, -0.5, -0.1, 0.0,
                  0.1, 0.5, 1.0, 5.0, 10.0, 60.0)

# Class names become Prometheus label values; a bounded charset keeps
# an open HTTP surface from minting unbounded series cardinality.
_CLASS_RE = re.compile(r"^[a-zA-Z0-9_.\-]{1,32}$")
DEFAULT_CLASS = "default"


def validate_slo_class(slo_class: str) -> str:
    if not _CLASS_RE.match(slo_class):
        raise ValueError(
            f"slo_class {slo_class!r} must match {_CLASS_RE.pattern}")
    return slo_class


class _ClassLedger:
    __slots__ = ("met", "missed", "shed", "goodput_tokens", "late_tokens")

    def __init__(self):
        self.met = 0
        self.missed = 0
        self.shed = 0
        self.goodput_tokens = 0
        self.late_tokens = 0


class SLOLedger:
    """Plain-int per-class deadline accounting, mirrored into an
    ``obs.MetricRegistry`` at collection time. Owned by the Engine
    (one per engine, on the engine's registry)."""

    def __init__(self, registry):
        self._classes: Dict[str, _ClassLedger] = {}
        self._c_req = registry.counter(
            "serve_slo_requests_total",
            "Deadline-carrying requests by terminal outcome.",
            labelnames=("slo_class", "outcome"))
        self._c_goodput = registry.counter(
            "serve_goodput_tokens_total",
            "Tokens of requests that finished within their deadline.",
            labelnames=("slo_class",))
        self._g_attain = registry.gauge(
            "serve_slo_attainment",
            "met / (met + missed + shed) per SLO class.",
            labelnames=("slo_class",))
        self._h_margin = registry.histogram(
            "serve_deadline_margin_seconds",
            "deadline_s minus end-to-end latency at finish (negative = "
            "missed), by class and prefix-cache outcome.",
            unit="seconds", labelnames=("slo_class", "prefix"),
            buckets=MARGIN_BUCKETS)
        registry.add_collector(self._collect)

    def _cls(self, slo_class: str) -> _ClassLedger:
        led = self._classes.get(slo_class)
        if led is None:
            led = self._classes[slo_class] = _ClassLedger()
        return led

    # ------------------------------------------------------------ record
    def record_finish(self, slo_class: str, *, tokens: int,
                      elapsed_s: float, deadline_s: Optional[float],
                      prefix: str = "miss") -> Optional[bool]:
        """Terminal accounting for a finished request. Returns whether
        the deadline was met (None when the request carried none — such
        requests are not SLO-tracked)."""
        if deadline_s is None:
            return None
        led = self._cls(slo_class)
        met = elapsed_s <= deadline_s
        if met:
            led.met += 1
            led.goodput_tokens += tokens
        else:
            led.missed += 1
            led.late_tokens += tokens
        self._h_margin.labels(slo_class=slo_class,
                              prefix=prefix).observe(deadline_s - elapsed_s)
        return met

    def record_shed(self, slo_class: str) -> None:
        """A queued request dropped after its deadline expired — counts
        against attainment; it produced zero (good) tokens."""
        self._cls(slo_class).shed += 1

    # ------------------------------------------------------------- views
    def _collect(self) -> None:
        for name, led in list(self._classes.items()):
            self._c_req.labels(slo_class=name,
                               outcome="met")._set_total(led.met)
            self._c_req.labels(slo_class=name,
                               outcome="missed")._set_total(led.missed)
            self._c_req.labels(slo_class=name,
                               outcome="shed")._set_total(led.shed)
            self._c_goodput.labels(slo_class=name)._set_total(
                led.goodput_tokens)
            total = led.met + led.missed + led.shed
            self._g_attain.labels(slo_class=name).set(
                led.met / total if total else 0.0)

    def totals(self) -> tuple:
        """(met, missed, shed) across every class — the brownout
        controller differences this between check windows, so it must
        stay a few int adds (no dict building per step)."""
        met = missed = shed = 0
        for led in self._classes.values():
            met += led.met
            missed += led.missed
            shed += led.shed
        return met, missed, shed

    def stats(self) -> dict:
        """The Engine.stats()["slo"] view: per-class dicts plus the
        cross-class rollup bench.py's overload sweep reads."""
        classes = {}
        met = missed = shed = goodput = late = 0
        for name, led in sorted(self._classes.items()):
            total = led.met + led.missed + led.shed
            classes[name] = {
                "met": led.met, "missed": led.missed, "shed": led.shed,
                "goodput_tokens": led.goodput_tokens,
                "late_tokens": led.late_tokens,
                "attainment": (led.met / total) if total else None,
            }
            met += led.met
            missed += led.missed
            shed += led.shed
            goodput += led.goodput_tokens
            late += led.late_tokens
        total = met + missed + shed
        return {"classes": classes,
                "overall": {"met": met, "missed": missed, "shed": shed,
                            "goodput_tokens": goodput,
                            "late_tokens": late,
                            "attainment": (met / total) if total else None}}

    def reset(self) -> None:
        """Zero the ledger (benchmarks reset between warmup and the
        timed window). Existing label children reset too — a cleared
        class would otherwise freeze its last mirrored totals on the
        scrape forever."""
        self._classes.clear()
        for fam in (self._c_req, self._c_goodput, self._g_attain,
                    self._h_margin):
            fam.reset()
