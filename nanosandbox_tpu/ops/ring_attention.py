"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context support beyond the reference's capability envelope (the
reference caps at block_size=1024 and has no sequence parallelism,
SURVEY.md §5 "Long-context"): the sequence dimension is sharded over the
mesh's ``seq`` axis, each device holds a T/cp chunk of Q/K/V, and K/V
chunks rotate around the ring via ``lax.ppermute`` while an online-softmax
accumulator builds the exact attention output — full attention over the
global sequence without ever materializing global K/V (or the (T, T)
score matrix) on any chip.

TPU-first shape: the per-step block matmuls are MXU-sized, the rotation is
a neighbor exchange that XLA schedules on ICI and overlaps with the block
compute, and the whole loop is unrolled at trace time (cp is a static mesh
property) so autodiff works straight through — the backward pass rotates
in the opposite direction automatically via the transpose of ppermute.

Composition: designed to run inside jit via jax.shard_map; everything
outside attention (MLP, layernorm, embeddings) is position-wise, so the
GSPMD partitioner handles the sharded T dimension there with no
collectives at all.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

__all__ = ["ring_attention", "ring_attention_sharded"]


# ---------------------------------------------------------------------------
# Per-block math: XLA einsum or the Pallas flash kernel
# ---------------------------------------------------------------------------
#
# Both ring bodies are expressed over ONE block primitive returning a
# normalized partial result + its logsumexp:
#
#   (out_j, lse_j) = attention(q_blk, k_chunk, v_chunk)   [diag or full]
#
# merged exactly across chunks via
#
#   lse   = logaddexp(lse_a, lse_b)
#   out   = out_a * exp(lse_a - lse) + out_b * exp(lse_b - lse)
#
# The 'xla' impl materializes one (B, H, Tq, Tk) f32 score block per call
# (fine at test scale); 'pallas' runs the Mosaic flash kernel per call —
# scores never leave VMEM, residuals stay O(T) per chunk — making the
# long-context configs this feature exists for actually fit in HBM
# (round-2 VERDICT weak #1). Autodiff flows through flash_attention_lse's
# custom_vjp (the lse cotangent folds into its backward row stat).


def _xla_block(q, k, v, mask, sm_scale):
    """(out f32, lse f32) for one block; mask True = attend."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm_scale,
                        k.astype(jnp.float32))
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m = scores.max(axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = p.sum(axis=-1)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out / jnp.maximum(l, 1e-30)[..., None], m + jnp.log(l)


def _make_block_fn(block_impl: str, sm_scale: float):
    """Returns block(q, k, v, diag) -> (out f32, lse (B, H, Tq) f32).

    diag=True applies the in-chunk causal mask (q and k share a position
    base); diag=False attends fully (the chunk is entirely in the past).
    """
    if block_impl == "xla":
        def block(q, k, v, diag):
            mask = None
            if diag:
                Tq, Tk = q.shape[2], k.shape[2]
                mask = (lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
                        >= lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1))
            return _xla_block(q, k, v, mask, sm_scale)
        return block
    if block_impl in ("pallas", "pallas_interpret"):
        from nanosandbox_tpu.ops.attention import flash_attention_lse

        interpret = block_impl == "pallas_interpret"

        def block(q, k, v, diag):
            out, lse = flash_attention_lse(q, k, v, diag, sm_scale,
                                           interpret)
            return out.astype(jnp.float32), lse
        return block
    raise ValueError(f"unknown ring block impl: {block_impl!r}")


def _merge(carry, blk):
    out_a, lse_a = carry
    out_b, lse_b = blk
    lse = jnp.logaddexp(lse_a, lse_b)
    out = (out_a * jnp.exp(lse_a - lse)[..., None]
           + out_b * jnp.exp(lse_b - lse)[..., None])
    return out, lse


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, axis_size: int, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   block_impl: str = "xla") -> jax.Array:
    """Per-shard ring attention body (call under shard_map).

    q, k, v: (B, H, Tc, D) local sequence chunks; global T = Tc * axis_size,
    chunked contiguously (device i holds positions [i*Tc, (i+1)*Tc)).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    my = lax.axis_index(axis_name)
    block = _make_block_fn(block_impl, sm_scale)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Step 0: the local chunk — diagonal (in-chunk causal) when causal.
    carry = block(q, k, v, causal)
    for s in range(1, axis_size):
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        # After s rotations device `my` holds the chunk originating at
        # ring position (my - s) mod cp.
        if causal:
            # Chunks strictly in this query's future are fully masked:
            # skip their matmuls entirely (they'd contribute exactly 0).
            # With contiguous chunking that's blocks where src > my, i.e.
            # s > my — devices still step the ring together, but a skipping
            # device does no attention FLOPs this step. (The zigzag layout
            # below equalizes per-device work; contiguous-but-skipping is
            # exact already.)
            carry = lax.cond(s <= my,
                             lambda c, kk, vv: _merge(c, block(q, kk, vv,
                                                               False)),
                             lambda c, kk, vv: c,
                             carry, k, v)
        else:
            carry = _merge(carry, block(q, k, v, False))
    out, _ = carry
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Zigzag (load-balanced) layout
# ---------------------------------------------------------------------------
#
# Contiguous chunking skips future blocks exactly, but unevenly: device 0
# computes 1 block while device cp-1 computes cp, so the ring's wall-clock
# is the worst device and causal skipping saves nothing. Zigzag ownership
# fixes the balance: split T into 2*cp half-chunks c_0..c_{2cp-1} and give
# device i the PAIR (c_i, c_{2cp-1-i}) — one early, one late. Then at
# every ring step each device computes exactly 2 half-blocks:
#
#   step 0 (local):  diag(q_early, k_early) + full(q_late, k_early)
#                    + diag(q_late, k_late)              [2 blocks total]
#   step s>0, src j: full(q_late, k_early_j) always, plus EITHER
#                    full(q_early, k_early_j)  when j < i
#                    OR full(q_late, k_late_j) when j > i [2 blocks total]
#
# (q_early never attends any late chunk: its global index i < cp <= every
# late index. q_late attends every early chunk: 2cp-1-i >= cp > j.)
# Same math, same comms (one k/v pair rotation per step), equal work —
# wall-clock drops from cp blocks to (cp+1) half-blocks ~= a 2x win at
# large cp.


def zigzag_ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          axis_name: str, axis_size: int,
                          sm_scale: Optional[float] = None,
                          block_impl: str = "xla") -> jax.Array:
    """Per-shard zigzag ring body (call under shard_map; causal only).

    q, k, v: (B, H, 2h, D) where rows [:h] are this device's EARLY
    half-chunk c_i and rows [h:] its LATE half-chunk c_{2cp-1-i}
    (the layout zigzag_permutation() produces).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, T2, D = q.shape
    h = T2 // 2
    cp = axis_size
    my = lax.axis_index(axis_name)
    block = _make_block_fn(block_impl, sm_scale)

    qe, ql = q[:, :, :h, :], q[:, :, h:, :]
    ke, kl = k[:, :, :h, :], k[:, :, h:, :]
    ve, vl = v[:, :, :h, :], v[:, :, h:, :]
    carry_e = block(qe, ke, ve, True)
    carry_l = _merge(block(ql, ke, ve, False), block(ql, kl, vl, True))

    perm = [(i, (i + 1) % cp) for i in range(cp)]
    for s in range(1, cp):
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        src = (my - s) % cp
        ke, kl = k[:, :, :h, :], k[:, :, h:, :]
        ve, vl = v[:, :, :h, :], v[:, :, h:, :]
        carry_l = _merge(carry_l, block(ql, ke, ve, False))
        carry_e, carry_l = lax.cond(
            src < my,
            lambda ce, cl, ke=ke, ve=ve: (_merge(ce, block(qe, ke, ve,
                                                           False)), cl),
            lambda ce, cl, kl=kl, vl=vl: (ce, _merge(cl, block(ql, kl, vl,
                                                               False))),
            carry_e, carry_l)

    out = jnp.concatenate([carry_e[0], carry_l[0]], axis=2)
    return out.astype(q.dtype)


def zigzag_permutation(T: int, cp: int):
    """(idx, inv): x.take(idx, axis) puts global rows into zigzag order
    (device i's contiguous shard = [c_i, c_{2cp-1-i}]); take(inv) undoes
    it. Requires T % (2*cp) == 0."""
    import numpy as np

    h = T // (2 * cp)
    idx = np.concatenate([
        np.concatenate([np.arange(i * h, (i + 1) * h),
                        np.arange((2 * cp - 1 - i) * h, (2 * cp - i) * h)])
        for i in range(cp)])
    inv = np.argsort(idx)
    return idx, inv


# Cache the shard_map closure per (mesh, params), bounded at 8 entries.
# Note a weakref cache would buy nothing here: jax interns Mesh objects
# with strong references (jax._src.mesh._mesh_object_dict), so a mesh
# key never dies. Instead the cache is small and explicitly clearable —
# parallel.mesh.set_current_mesh() calls clear_sharded_cache() whenever
# the active mesh actually changes, releasing retired closures
# deterministically in long-lived processes (Trainer re-creation, tests).


@functools.lru_cache(maxsize=8)
def _sharded_fn(mesh, causal: bool, sm_scale: float, seq_axis: str,
                zigzag: bool = False, block_impl: str = "xla"):
    spec = P(("data", "fsdp"), "model", seq_axis, None)
    if zigzag:
        body = functools.partial(
            zigzag_ring_attention, axis_name=seq_axis,
            axis_size=mesh.shape[seq_axis], sm_scale=sm_scale,
            block_impl=block_impl)
    else:
        body = functools.partial(
            ring_attention, axis_name=seq_axis,
            axis_size=mesh.shape[seq_axis], causal=causal, sm_scale=sm_scale,
            block_impl=block_impl)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)


def clear_sharded_cache() -> None:
    """Drop cached shard_map closures (call when the active mesh changes)."""
    _sharded_fn.cache_clear()


def _resolve_block_impl(block_impl: str, chunk_len: int) -> str:
    """'auto' -> 'pallas' when the Mosaic kernel compiles on this backend
    AND the per-call chunk is 128-lane aligned (the flash path's full
    [non-causal] blocks forbid T padding); 'xla' otherwise. A PINNED
    pallas impl with an unaligned chunk fails here with a ring-level
    error — previously it surfaced as a block-divisibility ValueError
    deep inside _pad_qkv that never mentioned ring_block_impl (ADVICE r3)."""
    if block_impl in ("pallas", "pallas_interpret") and chunk_len % 128:
        raise ValueError(
            f"ring_block_impl={block_impl!r} requires the per-device "
            f"sequence chunk to be a multiple of 128 (got {chunk_len}): "
            "non-causal ring blocks cannot pad T. Use a block_size "
            "divisible by 128*mesh_sp, or ring_block_impl='xla'/'auto'")
    if block_impl != "auto":
        return block_impl
    if chunk_len % 128:
        return "xla"
    from nanosandbox_tpu.ops.attention import pallas_compile_probe

    return "pallas" if pallas_compile_probe() else "xla"


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, *,
                           mesh, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           seq_axis: str = "seq",
                           layout: str = "zigzag",
                           block_impl: str = "auto") -> jax.Array:
    """Ring attention over (B, H, T, D) global arrays on ``mesh``.

    Batch is sharded over (data, fsdp), heads over model, sequence over
    ``seq_axis``. With a size-1 seq axis this degenerates to one local
    flash/XLA-equivalent block — still correct, so callers don't need a
    special case.

    layout='zigzag' (default) redistributes rows so each device owns one
    early + one late half-chunk, equalizing per-device causal work (see
    zigzag_ring_attention); the redistribution is a static take() the
    partitioner lowers to an all-to-all once on entry and once on exit.
    Falls back to the contiguous layout when zigzag does not apply
    (non-causal, cp == 1, or T not divisible by 2*cp).

    block_impl selects the per-chunk math: 'auto' runs the Pallas flash
    kernel inside the ring when available (scores stay in VMEM — the
    long-context configs need this), degrading to the XLA einsum block.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    T = q.shape[2]
    cp = mesh.shape[seq_axis]
    if T % cp:
        raise ValueError(f"sequence length {T} not divisible by seq axis {cp}")
    if layout not in ("zigzag", "contiguous"):
        raise ValueError(f"unknown ring layout: {layout!r}")
    use_zigzag = (layout == "zigzag" and causal and cp > 1
                  and T % (2 * cp) == 0)
    chunk = T // (2 * cp) if use_zigzag else T // cp
    impl = _resolve_block_impl(block_impl, chunk)
    if not use_zigzag:
        return _sharded_fn(mesh, causal, float(sm_scale), seq_axis,
                           block_impl=impl)(q, k, v)
    idx, inv = zigzag_permutation(T, cp)
    qz, kz, vz = (jnp.take(x, idx, axis=2) for x in (q, k, v))
    out = _sharded_fn(mesh, causal, float(sm_scale), seq_axis,
                      zigzag=True, block_impl=impl)(qz, kz, vz)
    return jnp.take(out, inv, axis=2)
