"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context support beyond the reference's capability envelope (the
reference caps at block_size=1024 and has no sequence parallelism,
SURVEY.md §5 "Long-context"): the sequence dimension is sharded over the
mesh's ``seq`` axis, each device holds a T/cp chunk of Q/K/V, and K/V
chunks rotate around the ring via ``lax.ppermute`` while an online-softmax
accumulator builds the exact attention output — full attention over the
global sequence without ever materializing global K/V (or the (T, T)
score matrix) on any chip.

TPU-first shape: the per-step block matmuls are MXU-sized, the rotation is
a neighbor exchange that XLA schedules on ICI and overlaps with the block
compute, and the whole loop is unrolled at trace time (cp is a static mesh
property) so autodiff works straight through — the backward pass rotates
in the opposite direction automatically via the transpose of ppermute.

Dropout (round-5): attention-probability dropout composes with the ring
because the keep-mask is a counter-based hash of GLOBAL (q_pos, k_pos)
coordinates (ops/attention.py) — every ring step reconstructs the same
mask for the same global score element no matter which device computes
it, and the per-shard offsets ride in the (5,) seed vector. The xla and
pallas block impls derive bit-identical masks (hash_dropout_keep_mask is
the same function the kernels inline).

Composition: designed to run inside jit via jax.shard_map; everything
outside attention (MLP, layernorm, embeddings) is position-wise, so the
GSPMD partitioner handles the sharded T dimension there with no
collectives at all.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

__all__ = ["ring_attention", "ring_attention_sharded"]


# ---------------------------------------------------------------------------
# Per-block math: XLA einsum or the Pallas flash kernel
# ---------------------------------------------------------------------------
#
# Both ring bodies are expressed over ONE block primitive returning a
# normalized partial result + its logsumexp:
#
#   (out_j, lse_j) = attention(q_blk, k_chunk, v_chunk)   [diag or full]
#
# merged exactly across chunks via
#
#   lse   = logaddexp(lse_a, lse_b)
#   out   = out_a * exp(lse_a - lse) + out_b * exp(lse_b - lse)
#
# The 'xla' impl materializes one (B, H, Tq, Tk) f32 score block per call
# (fine at test scale); 'pallas' runs the Mosaic flash kernel per call —
# scores never leave VMEM, residuals stay O(T) per chunk — making the
# long-context configs this feature exists for actually fit in HBM
# (round-2 VERDICT weak #1). Autodiff flows through the flash custom_vjps
# (the lse cotangent folds into their backward row stat).
#
# Dropout merging note: each block's lse is the UNMASKED normalizer, and
# each block's out is (masked p) @ v / l_block. The merge rescales by
# exp(lse_j - lse_total), which telescopes to (masked p) @ v / l_total —
# exactly dropout(softmax(s_global)) @ v, because masking commutes with
# the global normalization.


def _xla_block(q, k, v, mask, sm_scale, keep=None, rate: float = 0.0):
    """(out f32, lse f32) for one block; mask True = attend; keep is an
    optional (B, H, Tq, Tk) dropout keep-mask applied to the normalized
    probabilities (with the 1/(1-rate) inverted-dropout rescale)."""
    scores = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm_scale,
                        k.astype(jnp.float32))
    if mask is not None:
        scores = jnp.where(mask, scores, NEG_INF)
    m = scores.max(axis=-1)
    p = jnp.exp(scores - m[..., None])
    l = p.sum(axis=-1)
    if keep is not None:
        p = jnp.where(keep, p * (1.0 / (1.0 - rate)), 0.0)
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return out / jnp.maximum(l, 1e-30)[..., None], m + jnp.log(l)


def _make_block_fn(block_impl: str, sm_scale: float,
                   stat_layout: str = "replicated",
                   dropout_rate: float = 0.0,
                   hash_heads: int | None = None,
                   hash_seq_len: int | None = None):
    """Returns block(q, k, v, diag, seed) -> (out f32, lse (B, H, Tq) f32).

    diag=True applies the in-chunk causal mask (q and k share a position
    base); diag=False attends fully (the chunk is entirely in the past).
    seed: (SEED_WORDS,) uint32 with global offsets (ignored when
    dropout_rate == 0).
    """
    if block_impl == "xla":
        from nanosandbox_tpu.ops.attention import hash_dropout_keep_mask

        def block(q, k, v, diag, seed):
            mask = None
            Tq, Tk = q.shape[2], k.shape[2]
            if diag:
                mask = (lax.broadcasted_iota(jnp.int32, (Tq, Tk), 0)
                        >= lax.broadcasted_iota(jnp.int32, (Tq, Tk), 1))
            keep = None
            if dropout_rate > 0.0:
                keep = hash_dropout_keep_mask(
                    seed, q.shape[0], q.shape[1], Tq, Tk,
                    hash_heads=hash_heads, hash_seq_len=hash_seq_len,
                    rate=dropout_rate)
            return _xla_block(q, k, v, mask, sm_scale, keep, dropout_rate)
        return block
    if block_impl in ("pallas", "pallas_interpret"):
        from nanosandbox_tpu.ops.attention import (flash_attention_lse,
                                                   flash_attention_lse_dropout)

        interpret = block_impl == "pallas_interpret"

        def block(q, k, v, diag, seed):
            if dropout_rate > 0.0:
                out, lse = flash_attention_lse_dropout(
                    q, k, v, seed, diag, sm_scale, dropout_rate,
                    interpret, stat_layout, hash_heads, hash_seq_len)
            else:
                out, lse = flash_attention_lse(q, k, v, diag, sm_scale,
                                               interpret, stat_layout)
            return out.astype(jnp.float32), lse
        return block
    raise ValueError(f"unknown ring block impl: {block_impl!r}")


def _merge(carry, blk):
    out_a, lse_a = carry
    out_b, lse_b = blk
    lse = jnp.logaddexp(lse_a, lse_b)
    out = (out_a * jnp.exp(lse_a - lse)[..., None]
           + out_b * jnp.exp(lse_b - lse)[..., None])
    return out, lse


def _shard_offsets(q, dropout_rate: float, data_size: int, fsdp_size: int,
                   model_size: int = 1):
    """(b_off, h_off) — global index of this shard's first batch row and
    head, from the mesh axis indices. Only consulted when dropout is
    active (the axis names only exist under the full training mesh;
    direct shard_map harnesses without them keep working dropout-free)."""
    if dropout_rate <= 0.0:
        return jnp.uint32(0), jnp.uint32(0)
    B_loc, H_loc = q.shape[0], q.shape[1]
    b_idx = 0
    if data_size > 1 or fsdp_size > 1:
        b_idx = (lax.axis_index("data") * fsdp_size
                 + lax.axis_index("fsdp"))
    h_idx = lax.axis_index("model") if model_size > 1 else 0
    return (jnp.uint32(b_idx) * jnp.uint32(B_loc),
            jnp.uint32(h_idx) * jnp.uint32(H_loc))


def _block_seed(seed, b_off, h_off, q_off, k_off):
    """Assemble the (5,) seed vector for one block call."""
    s0 = (jnp.zeros((), jnp.uint32) if seed is None
          else jnp.asarray(seed, jnp.uint32).reshape(-1)[0])
    return jnp.stack([s0, b_off, h_off,
                      jnp.uint32(q_off), jnp.uint32(k_off)])


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   seed: Optional[jax.Array] = None, *,
                   axis_name: str, axis_size: int, causal: bool = True,
                   sm_scale: Optional[float] = None,
                   block_impl: str = "xla",
                   stat_layout: str = "replicated",
                   dropout_rate: float = 0.0,
                   hash_heads: int | None = None,
                   hash_seq_len: int | None = None,
                   data_size: int = 1, fsdp_size: int = 1,
                   model_size: int = 1) -> jax.Array:
    """Per-shard ring attention body (call under shard_map).

    q, k, v: (B, H, Tc, D) local sequence chunks; global T = Tc * axis_size,
    chunked contiguously (device i holds positions [i*Tc, (i+1)*Tc)).
    seed: (1,) uint32 per-step dropout seed (replicated; required when
    dropout_rate > 0).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    my = lax.axis_index(axis_name)
    Tc = q.shape[2]
    block = _make_block_fn(block_impl, sm_scale, stat_layout,
                           dropout_rate, hash_heads, hash_seq_len)
    b_off, h_off = _shard_offsets(q, dropout_rate, data_size, fsdp_size,
                                  model_size)
    q_off = my.astype(jnp.uint32) * jnp.uint32(Tc)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    # Step 0: the local chunk — diagonal (in-chunk causal) when causal.
    carry = block(q, k, v, causal,
                  _block_seed(seed, b_off, h_off, q_off, q_off))
    for s in range(1, axis_size):
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        # After s rotations device `my` holds the chunk originating at
        # ring position (my - s) mod cp.
        src = (my - s) % axis_size
        k_off = src.astype(jnp.uint32) * jnp.uint32(Tc)
        blk_seed = _block_seed(seed, b_off, h_off, q_off, k_off)
        if causal:
            # Chunks strictly in this query's future are fully masked:
            # skip their matmuls entirely (they'd contribute exactly 0).
            # With contiguous chunking that's blocks where src > my, i.e.
            # s > my — devices still step the ring together, but a skipping
            # device does no attention FLOPs this step. (The zigzag layout
            # below equalizes per-device work; contiguous-but-skipping is
            # exact already.)
            carry = lax.cond(s <= my,
                             lambda c, kk, vv, sd: _merge(
                                 c, block(q, kk, vv, False, sd)),
                             lambda c, kk, vv, sd: c,
                             carry, k, v, blk_seed)
        else:
            carry = _merge(carry, block(q, k, v, False, blk_seed))
    out, _ = carry
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Zigzag (load-balanced) layout
# ---------------------------------------------------------------------------
#
# Contiguous chunking skips future blocks exactly, but unevenly: device 0
# computes 1 block while device cp-1 computes cp, so the ring's wall-clock
# is the worst device and causal skipping saves nothing. Zigzag ownership
# fixes the balance: split T into 2*cp half-chunks c_0..c_{2cp-1} and give
# device i the PAIR (c_i, c_{2cp-1-i}) — one early, one late. Then at
# every ring step each device computes exactly 2 half-blocks:
#
#   step 0 (local):  diag(q_early, k_early) + full(q_late, k_early)
#                    + diag(q_late, k_late)              [2 blocks total]
#   step s>0, src j: full(q_late, k_early_j) always, plus EITHER
#                    full(q_early, k_early_j)  when j < i
#                    OR full(q_late, k_late_j) when j > i [2 blocks total]
#
# (q_early never attends any late chunk: its global index i < cp <= every
# late index. q_late attends every early chunk: 2cp-1-i >= cp > j.)
# Same math, same comms (one k/v pair rotation per step), equal work —
# wall-clock drops from cp blocks to (cp+1) half-blocks ~= a 2x win at
# large cp.
#
# Dropout positions under zigzag are the ORIGINAL global row/col indices
# (the take() permutation is undone in the hash by per-half offsets), so
# zigzag, contiguous, and the non-ring path all agree on which global
# score elements drop for a given seed.


def zigzag_ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                          seed: Optional[jax.Array] = None, *,
                          axis_name: str, axis_size: int,
                          sm_scale: Optional[float] = None,
                          block_impl: str = "xla",
                          stat_layout: str = "replicated",
                          dropout_rate: float = 0.0,
                          hash_heads: int | None = None,
                          hash_seq_len: int | None = None,
                          data_size: int = 1, fsdp_size: int = 1,
                          model_size: int = 1) -> jax.Array:
    """Per-shard zigzag ring body (call under shard_map; causal only).

    q, k, v: (B, H, 2h, D) where rows [:h] are this device's EARLY
    half-chunk c_i and rows [h:] its LATE half-chunk c_{2cp-1-i}
    (the layout zigzag_permutation() produces).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, T2, D = q.shape
    h = T2 // 2
    cp = axis_size
    my = lax.axis_index(axis_name)
    block = _make_block_fn(block_impl, sm_scale, stat_layout,
                           dropout_rate, hash_heads, hash_seq_len)
    b_off, h_off = _shard_offsets(q, dropout_rate, data_size, fsdp_size,
                                  model_size)
    hh = jnp.uint32(h)
    qe_off = my.astype(jnp.uint32) * hh                      # c_my
    ql_off = (jnp.uint32(2 * cp - 1) - my.astype(jnp.uint32)) * hh

    def sd(q_off, k_off):
        return _block_seed(seed, b_off, h_off, q_off, k_off)

    qe, ql = q[:, :, :h, :], q[:, :, h:, :]
    ke, kl = k[:, :, :h, :], k[:, :, h:, :]
    ve, vl = v[:, :, :h, :], v[:, :, h:, :]
    carry_e = block(qe, ke, ve, True, sd(qe_off, qe_off))
    carry_l = _merge(block(ql, ke, ve, False, sd(ql_off, qe_off)),
                     block(ql, kl, vl, True, sd(ql_off, ql_off)))

    perm = [(i, (i + 1) % cp) for i in range(cp)]
    for s in range(1, cp):
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        src = (my - s) % cp
        ke_off = src.astype(jnp.uint32) * hh
        kl_off = (jnp.uint32(2 * cp - 1) - src.astype(jnp.uint32)) * hh
        ke, kl = k[:, :, :h, :], k[:, :, h:, :]
        ve, vl = v[:, :, :h, :], v[:, :, h:, :]
        carry_l = _merge(carry_l, block(ql, ke, ve, False,
                                        sd(ql_off, ke_off)))
        carry_e, carry_l = lax.cond(
            src < my,
            lambda ce, cl, ke=ke, ve=ve, ke_off=ke_off: (
                _merge(ce, block(qe, ke, ve, False, sd(qe_off, ke_off))),
                cl),
            lambda ce, cl, kl=kl, vl=vl, kl_off=kl_off: (
                ce,
                _merge(cl, block(ql, kl, vl, False, sd(ql_off, kl_off)))),
            carry_e, carry_l)

    out = jnp.concatenate([carry_e[0], carry_l[0]], axis=2)
    return out.astype(q.dtype)


def zigzag_permutation(T: int, cp: int):
    """(idx, inv): x.take(idx, axis) puts global rows into zigzag order
    (device i's contiguous shard = [c_i, c_{2cp-1-i}]); take(inv) undoes
    it. Requires T % (2*cp) == 0."""
    import numpy as np

    h = T // (2 * cp)
    idx = np.concatenate([
        np.concatenate([np.arange(i * h, (i + 1) * h),
                        np.arange((2 * cp - 1 - i) * h, (2 * cp - i) * h)])
        for i in range(cp)])
    inv = np.argsort(idx)
    return idx, inv


# Cache the shard_map closure per (mesh, params), bounded at 8 entries.
# Note a weakref cache would buy nothing here: jax interns Mesh objects
# with strong references (jax._src.mesh._mesh_object_dict), so a mesh
# key never dies. Instead the cache is small and explicitly clearable —
# parallel.mesh.set_current_mesh() calls clear_sharded_cache() whenever
# the active mesh actually changes, releasing retired closures
# deterministically in long-lived processes (Trainer re-creation, tests).


@functools.lru_cache(maxsize=8)
def _sharded_fn(mesh, causal: bool, sm_scale: float, seq_axis: str,
                zigzag: bool = False, block_impl: str = "xla",
                stat_layout: str = "replicated", dropout_rate: float = 0.0,
                hash_heads: int | None = None,
                hash_seq_len: int | None = None):
    spec = P(("data", "fsdp"), "model", seq_axis, None)
    common = dict(axis_name=seq_axis, axis_size=mesh.shape[seq_axis],
                  sm_scale=sm_scale, block_impl=block_impl,
                  stat_layout=stat_layout, dropout_rate=dropout_rate,
                  hash_heads=hash_heads, hash_seq_len=hash_seq_len,
                  data_size=mesh.shape["data"],
                  fsdp_size=mesh.shape["fsdp"],
                  model_size=mesh.shape["model"])
    if zigzag:
        body = functools.partial(zigzag_ring_attention, **common)
    else:
        body = functools.partial(ring_attention, causal=causal, **common)
    from nanosandbox_tpu.parallel.mesh import shard_map

    return shard_map(body, mesh=mesh,
                     in_specs=(spec, spec, spec, P(None)),
                     out_specs=spec, check_vma=False)


def clear_sharded_cache() -> None:
    """Drop cached shard_map closures (call when the active mesh changes)."""
    _sharded_fn.cache_clear()


def _resolve_block_impl(block_impl: str, chunk_len: int,
                        has_full_blocks: bool = True) -> str:
    """'auto' -> 'pallas' when the Mosaic kernel compiles on this backend
    AND the per-call chunk is 128-lane aligned (the flash path's full
    [non-causal] blocks forbid T padding); 'xla' otherwise. A PINNED
    pallas impl with an unaligned chunk fails here with a ring-level
    error — previously it surfaced as a block-divisibility ValueError
    deep inside _pad_qkv that never mentioned ring_block_impl (ADVICE r3).

    has_full_blocks=False (cp == 1, the degenerate ring that wraps plain
    flash attention in its SPMD shell): the only block is the CAUSAL
    local one, which pads T freely — alignment is not required."""
    unaligned = chunk_len % 128 and has_full_blocks
    if block_impl in ("pallas", "pallas_interpret") and unaligned:
        raise ValueError(
            f"ring_block_impl={block_impl!r} requires the per-device "
            f"sequence chunk to be a multiple of 128 (got {chunk_len}): "
            "non-causal ring blocks cannot pad T. Use a block_size "
            "divisible by 128*mesh_sp, or ring_block_impl='xla'/'auto'")
    if block_impl != "auto":
        return block_impl
    if unaligned:
        return "xla"
    from nanosandbox_tpu.ops.attention import pallas_compile_probe

    return "pallas" if pallas_compile_probe() else "xla"


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, *,
                           mesh, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           seq_axis: str = "seq",
                           layout: str = "zigzag",
                           block_impl: str = "auto",
                           stat_layout: str = "replicated",
                           dropout_rate: float = 0.0,
                           dropout_seed: Optional[jax.Array] = None
                           ) -> jax.Array:
    """Ring attention over (B, H, T, D) global arrays on ``mesh``.

    Batch is sharded over (data, fsdp), heads over model, sequence over
    ``seq_axis``. With a size-1 seq axis this degenerates to one local
    flash/XLA-equivalent block — still correct, so callers don't need a
    special case.

    layout='zigzag' (default) redistributes rows so each device owns one
    early + one late half-chunk, equalizing per-device causal work (see
    zigzag_ring_attention); the redistribution is a static take() the
    partitioner lowers to an all-to-all once on entry and once on exit.
    Falls back to the contiguous layout when zigzag does not apply
    (non-causal, cp == 1, or T not divisible by 2*cp).

    block_impl selects the per-chunk math: 'auto' runs the Pallas flash
    kernel inside the ring when available (scores stay in VMEM — the
    long-context configs need this), degrading to the XLA einsum block.
    stat_layout is forwarded to the flash backward (round-4 ADVICE #3).

    dropout_rate/dropout_seed: attention-probability dropout via the
    global-position hash mask; seed is a (1,) uint32 per-step value
    (required when dropout_rate > 0). The mask is keyed on global
    coordinates, so all layouts and block impls — and the sp=1 non-ring
    kernel at the same padded length — drop the same elements.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    T = q.shape[2]
    cp = mesh.shape[seq_axis]
    if T % cp:
        raise ValueError(f"sequence length {T} not divisible by seq axis {cp}")
    if layout not in ("zigzag", "contiguous"):
        raise ValueError(f"unknown ring layout: {layout!r}")
    if dropout_rate > 0.0 and dropout_seed is None:
        raise ValueError("ring attention dropout needs a per-step "
                         "dropout_seed ((1,) uint32) when dropout_rate > 0")
    use_zigzag = (layout == "zigzag" and causal and cp > 1
                  and T % (2 * cp) == 0)
    chunk = T // (2 * cp) if use_zigzag else T // cp
    impl = _resolve_block_impl(block_impl, chunk,
                               has_full_blocks=cp > 1 or not causal)
    seed = (jnp.zeros((1,), jnp.uint32) if dropout_seed is None
            else jnp.asarray(dropout_seed, jnp.uint32).reshape((1,)))
    hash_heads = q.shape[1]  # global head count (sharded over 'model')
    fn_args = dict(stat_layout=stat_layout, dropout_rate=float(dropout_rate),
                   hash_heads=hash_heads, hash_seq_len=T)
    if not use_zigzag:
        return _sharded_fn(mesh, causal, float(sm_scale), seq_axis,
                           block_impl=impl, **fn_args)(q, k, v, seed)
    idx, inv = zigzag_permutation(T, cp)
    qz, kz, vz = (jnp.take(x, idx, axis=2) for x in (q, k, v))
    out = _sharded_fn(mesh, causal, float(sm_scale), seq_axis,
                      zigzag=True, block_impl=impl, **fn_args)(qz, kz, vz,
                                                               seed)
    return jnp.take(out, inv, axis=2)
