"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context support beyond the reference's capability envelope (the
reference caps at block_size=1024 and has no sequence parallelism,
SURVEY.md §5 "Long-context"): the sequence dimension is sharded over the
mesh's ``seq`` axis, each device holds a T/cp chunk of Q/K/V, and K/V
chunks rotate around the ring via ``lax.ppermute`` while an online-softmax
accumulator builds the exact attention output — full attention over the
global sequence without ever materializing global K/V (or the (T, T)
score matrix) on any chip.

TPU-first shape: the per-step block matmuls are MXU-sized, the rotation is
a neighbor exchange that XLA schedules on ICI and overlaps with the block
compute, and the whole loop is unrolled at trace time (cp is a static mesh
property) so autodiff works straight through — the backward pass rotates
in the opposite direction automatically via the transpose of ppermute.

Composition: designed to run inside jit via jax.shard_map; everything
outside attention (MLP, layernorm, embeddings) is position-wise, so the
GSPMD partitioner handles the sharded T dimension there with no
collectives at all.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

__all__ = ["ring_attention", "ring_attention_sharded"]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, axis_size: int, causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Per-shard ring attention body (call under shard_map).

    q, k, v: (B, H, Tc, D) local sequence chunks; global T = Tc * axis_size,
    chunked contiguously (device i holds positions [i*Tc, (i+1)*Tc)).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, Tc, D = q.shape
    my = lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32) * sm_scale
    q_pos = my * Tc + lax.broadcasted_iota(jnp.int32, (Tc, Tc), 0)

    acc = jnp.zeros((B, H, Tc, D), jnp.float32)
    m = jnp.full((B, H, Tc, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tc, 1), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def block_update(carry, k, v, src):
        acc, m, l = carry
        k_pos = src * Tc + lax.broadcasted_iota(jnp.int32, (Tc, Tc), 1)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k.astype(jnp.float32))
        if causal:
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       v.astype(jnp.float32))
        return acc, m_new, l

    carry = (acc, m, l)
    for s in range(axis_size):
        # After s rotations device `my` holds the chunk originating at
        # ring position (my - s) mod cp.
        src = (my - s) % axis_size
        if causal and s > 0:
            # Chunks strictly in this query's future are fully masked:
            # skip their matmuls entirely (they'd contribute exactly 0).
            # With contiguous chunking that's blocks where src > my, i.e.
            # s > my — devices still step the ring together, but a skipping
            # device does no attention FLOPs this step. (A zigzag chunk
            # layout that equalizes per-device work is the follow-on
            # optimization; contiguous-but-skipping is exact already.)
            carry = lax.cond(s <= my,
                             lambda c, kk, vv: block_update(c, kk, vv, src),
                             lambda c, kk, vv: c,
                             carry, k, v)
        else:
            carry = block_update(carry, k, v, src)
        if s != axis_size - 1:  # last chunk needs no forwarding
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    acc, m, l = carry

    # Fully-masked rows (none exist for causal self-attention, but guard
    # the division for robustness) normalize to zero.
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


# ---------------------------------------------------------------------------
# Zigzag (load-balanced) layout
# ---------------------------------------------------------------------------
#
# Contiguous chunking skips future blocks exactly, but unevenly: device 0
# computes 1 block while device cp-1 computes cp, so the ring's wall-clock
# is the worst device and causal skipping saves nothing. Zigzag ownership
# fixes the balance: split T into 2*cp half-chunks c_0..c_{2cp-1} and give
# device i the PAIR (c_i, c_{2cp-1-i}) — one early, one late. Then at
# every ring step each device computes exactly 2 half-blocks:
#
#   step 0 (local):  diag(q_early, k_early) + full(q_late, k_early)
#                    + diag(q_late, k_late)              [2 blocks total]
#   step s>0, src j: full(q_late, k_early_j) always, plus EITHER
#                    full(q_early, k_early_j)  when j < i
#                    OR full(q_late, k_late_j) when j > i [2 blocks total]
#
# (q_early never attends any late chunk: its global index i < cp <= every
# late index. q_late attends every early chunk: 2cp-1-i >= cp > j.)
# Same math, same comms (one k/v pair rotation per step), equal work —
# wall-clock drops from cp blocks to (cp+1) half-blocks ~= a 2x win at
# large cp.


def zigzag_ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          axis_name: str, axis_size: int,
                          sm_scale: Optional[float] = None) -> jax.Array:
    """Per-shard zigzag ring body (call under shard_map; causal only).

    q, k, v: (B, H, 2h, D) where rows [:h] are this device's EARLY
    half-chunk c_i and rows [h:] its LATE half-chunk c_{2cp-1-i}
    (the layout zigzag_permutation() produces).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, T2, D = q.shape
    h = T2 // 2
    cp = axis_size
    my = lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32) * sm_scale
    q32e, q32l = q32[:, :, :h, :], q32[:, :, h:, :]

    # In-chunk causal mask (both diagonals share it: q_pos = base + row,
    # k_pos = base + col with the same base).
    row = lax.broadcasted_iota(jnp.int32, (h, h), 0)
    diag_mask = row >= lax.broadcasted_iota(jnp.int32, (h, h), 1)

    def block(carry, q32b, kb, vb, mask):
        acc, m, l = carry
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32b, kb.astype(jnp.float32))
        if mask is not None:
            scores = jnp.where(mask, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       vb.astype(jnp.float32))
        return acc, m_new, l

    def init():
        return (jnp.zeros((B, H, h, D), jnp.float32),
                jnp.full((B, H, h, 1), NEG_INF, jnp.float32),
                jnp.zeros((B, H, h, 1), jnp.float32))

    ke, kl = k[:, :, :h, :], k[:, :, h:, :]
    ve, vl = v[:, :, :h, :], v[:, :, h:, :]
    carry_e = block(init(), q32e, ke, ve, diag_mask)
    carry_l = block(init(), q32l, ke, ve, None)
    carry_l = block(carry_l, q32l, kl, vl, diag_mask)

    perm = [(i, (i + 1) % cp) for i in range(cp)]
    for s in range(1, cp):
        k = lax.ppermute(k, axis_name, perm)
        v = lax.ppermute(v, axis_name, perm)
        src = (my - s) % cp
        ke, kl = k[:, :, :h, :], k[:, :, h:, :]
        ve, vl = v[:, :, :h, :], v[:, :, h:, :]
        carry_l = block(carry_l, q32l, ke, ve, None)
        carry_e, carry_l = lax.cond(
            src < my,
            lambda ce, cl, ke=ke, ve=ve: (block(ce, q32e, ke, ve, None), cl),
            lambda ce, cl, kl=kl, vl=vl: (ce, block(cl, q32l, kl, vl, None)),
            carry_e, carry_l)

    def finalize(carry):
        acc, _, l = carry
        return acc / jnp.maximum(l, 1e-30)

    out = jnp.concatenate([finalize(carry_e), finalize(carry_l)], axis=2)
    return out.astype(q.dtype)


def zigzag_permutation(T: int, cp: int):
    """(idx, inv): x.take(idx, axis) puts global rows into zigzag order
    (device i's contiguous shard = [c_i, c_{2cp-1-i}]); take(inv) undoes
    it. Requires T % (2*cp) == 0."""
    import numpy as np

    h = T // (2 * cp)
    idx = np.concatenate([
        np.concatenate([np.arange(i * h, (i + 1) * h),
                        np.arange((2 * cp - 1 - i) * h, (2 * cp - i) * h)])
        for i in range(cp)])
    inv = np.argsort(idx)
    return idx, inv


# Cache the shard_map closure per (mesh, params), bounded at 8 entries.
# Note a weakref cache would buy nothing here: jax interns Mesh objects
# with strong references (jax._src.mesh._mesh_object_dict), so a mesh
# key never dies. Instead the cache is small and explicitly clearable —
# parallel.mesh.set_current_mesh() calls clear_sharded_cache() whenever
# the active mesh actually changes, releasing retired closures
# deterministically in long-lived processes (Trainer re-creation, tests).


@functools.lru_cache(maxsize=8)
def _sharded_fn(mesh, causal: bool, sm_scale: float, seq_axis: str,
                zigzag: bool = False):
    spec = P(("data", "fsdp"), "model", seq_axis, None)
    if zigzag:
        body = functools.partial(
            zigzag_ring_attention, axis_name=seq_axis,
            axis_size=mesh.shape[seq_axis], sm_scale=sm_scale)
    else:
        body = functools.partial(
            ring_attention, axis_name=seq_axis,
            axis_size=mesh.shape[seq_axis], causal=causal, sm_scale=sm_scale)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)


def clear_sharded_cache() -> None:
    """Drop cached shard_map closures (call when the active mesh changes)."""
    _sharded_fn.cache_clear()


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, *,
                           mesh, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           seq_axis: str = "seq",
                           layout: str = "zigzag") -> jax.Array:
    """Ring attention over (B, H, T, D) global arrays on ``mesh``.

    Batch is sharded over (data, fsdp), heads over model, sequence over
    ``seq_axis``. With a size-1 seq axis this degenerates to one local
    flash/XLA-equivalent block — still correct, so callers don't need a
    special case.

    layout='zigzag' (default) redistributes rows so each device owns one
    early + one late half-chunk, equalizing per-device causal work (see
    zigzag_ring_attention); the redistribution is a static take() the
    partitioner lowers to an all-to-all once on entry and once on exit.
    Falls back to the contiguous layout when zigzag does not apply
    (non-causal, cp == 1, or T not divisible by 2*cp).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    T = q.shape[2]
    cp = mesh.shape[seq_axis]
    if T % cp:
        raise ValueError(f"sequence length {T} not divisible by seq axis {cp}")
    if layout not in ("zigzag", "contiguous"):
        raise ValueError(f"unknown ring layout: {layout!r}")
    use_zigzag = (layout == "zigzag" and causal and cp > 1
                  and T % (2 * cp) == 0)
    if not use_zigzag:
        return _sharded_fn(mesh, causal, float(sm_scale), seq_axis)(q, k, v)
    idx, inv = zigzag_permutation(T, cp)
    qz, kz, vz = (jnp.take(x, idx, axis=2) for x in (q, k, v))
    out = _sharded_fn(mesh, causal, float(sm_scale), seq_axis,
                      zigzag=True)(qz, kz, vz)
    return jnp.take(out, inv, axis=2)
