"""Ring attention: causal attention over a sequence-parallel mesh axis.

Long-context support beyond the reference's capability envelope (the
reference caps at block_size=1024 and has no sequence parallelism,
SURVEY.md §5 "Long-context"): the sequence dimension is sharded over the
mesh's ``seq`` axis, each device holds a T/cp chunk of Q/K/V, and K/V
chunks rotate around the ring via ``lax.ppermute`` while an online-softmax
accumulator builds the exact attention output — full attention over the
global sequence without ever materializing global K/V (or the (T, T)
score matrix) on any chip.

TPU-first shape: the per-step block matmuls are MXU-sized, the rotation is
a neighbor exchange that XLA schedules on ICI and overlaps with the block
compute, and the whole loop is unrolled at trace time (cp is a static mesh
property) so autodiff works straight through — the backward pass rotates
in the opposite direction automatically via the transpose of ppermute.

Composition: designed to run inside jit via jax.shard_map; everything
outside attention (MLP, layernorm, embeddings) is position-wise, so the
GSPMD partitioner handles the sharded T dimension there with no
collectives at all.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

NEG_INF = -1e30

__all__ = ["ring_attention", "ring_attention_sharded"]


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str, axis_size: int, causal: bool = True,
                   sm_scale: Optional[float] = None) -> jax.Array:
    """Per-shard ring attention body (call under shard_map).

    q, k, v: (B, H, Tc, D) local sequence chunks; global T = Tc * axis_size,
    chunked contiguously (device i holds positions [i*Tc, (i+1)*Tc)).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    B, H, Tc, D = q.shape
    my = lax.axis_index(axis_name)

    q32 = q.astype(jnp.float32) * sm_scale
    q_pos = my * Tc + lax.broadcasted_iota(jnp.int32, (Tc, Tc), 0)

    acc = jnp.zeros((B, H, Tc, D), jnp.float32)
    m = jnp.full((B, H, Tc, 1), NEG_INF, jnp.float32)
    l = jnp.zeros((B, H, Tc, 1), jnp.float32)

    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def block_update(carry, k, v, src):
        acc, m, l = carry
        k_pos = src * Tc + lax.broadcasted_iota(jnp.int32, (Tc, Tc), 1)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q32,
                            k.astype(jnp.float32))
        if causal:
            scores = jnp.where(q_pos >= k_pos, scores, NEG_INF)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        p = jnp.exp(scores - m_new)
        alpha = jnp.exp(m - m_new)
        l = alpha * l + p.sum(axis=-1, keepdims=True)
        acc = acc * alpha + jnp.einsum("bhqk,bhkd->bhqd", p,
                                       v.astype(jnp.float32))
        return acc, m_new, l

    carry = (acc, m, l)
    for s in range(axis_size):
        # After s rotations device `my` holds the chunk originating at
        # ring position (my - s) mod cp.
        src = (my - s) % axis_size
        if causal and s > 0:
            # Chunks strictly in this query's future are fully masked:
            # skip their matmuls entirely (they'd contribute exactly 0).
            # With contiguous chunking that's blocks where src > my, i.e.
            # s > my — devices still step the ring together, but a skipping
            # device does no attention FLOPs this step. (A zigzag chunk
            # layout that equalizes per-device work is the follow-on
            # optimization; contiguous-but-skipping is exact already.)
            carry = lax.cond(s <= my,
                             lambda c, kk, vv: block_update(c, kk, vv, src),
                             lambda c, kk, vv: c,
                             carry, k, v)
        else:
            carry = block_update(carry, k, v, src)
        if s != axis_size - 1:  # last chunk needs no forwarding
            k = lax.ppermute(k, axis_name, perm)
            v = lax.ppermute(v, axis_name, perm)
    acc, m, l = carry

    # Fully-masked rows (none exist for causal self-attention, but guard
    # the division for robustness) normalize to zero.
    out = acc / jnp.maximum(l, 1e-30)
    return out.astype(q.dtype)


# Cache the shard_map closure per (mesh, params), bounded at 8 entries.
# Note a weakref cache would buy nothing here: jax interns Mesh objects
# with strong references (jax._src.mesh._mesh_object_dict), so a mesh
# key never dies. Instead the cache is small and explicitly clearable —
# parallel.mesh.set_current_mesh() calls clear_sharded_cache() whenever
# the active mesh actually changes, releasing retired closures
# deterministically in long-lived processes (Trainer re-creation, tests).


@functools.lru_cache(maxsize=8)
def _sharded_fn(mesh, causal: bool, sm_scale: float, seq_axis: str):
    spec = P(("data", "fsdp"), "model", seq_axis, None)
    body = functools.partial(
        ring_attention, axis_name=seq_axis,
        axis_size=mesh.shape[seq_axis], causal=causal, sm_scale=sm_scale)
    return jax.shard_map(body, mesh=mesh, in_specs=(spec, spec, spec),
                         out_specs=spec, check_vma=False)


def clear_sharded_cache() -> None:
    """Drop cached shard_map closures (call when the active mesh changes)."""
    _sharded_fn.cache_clear()


def ring_attention_sharded(q: jax.Array, k: jax.Array, v: jax.Array, *,
                           mesh, causal: bool = True,
                           sm_scale: Optional[float] = None,
                           seq_axis: str = "seq") -> jax.Array:
    """Ring attention over (B, H, T, D) global arrays on ``mesh``.

    Batch is sharded over (data, fsdp), heads over model, sequence over
    ``seq_axis``. With a size-1 seq axis this degenerates to one local
    flash/XLA-equivalent block — still correct, so callers don't need a
    special case.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    T = q.shape[2]
    cp = mesh.shape[seq_axis]
    if T % cp:
        raise ValueError(f"sequence length {T} not divisible by seq axis {cp}")
    return _sharded_fn(mesh, causal, float(sm_scale), seq_axis)(q, k, v)
