"""Fused single-query flash-decode over the serve engine's slot KV pool.

The decode hot loop's attention is HBM-bandwidth-bound: every token of
every slot streams that slot's whole K/V history from HBM once. The
generic cached path in models/gpt.py pays that stream twice over —
scores materialize against the full ``max_len`` buffer in fp32, the
probability tensor round-trips through XLA fusions — and, with an int8
pool, would need a dequantized fp copy of the cache before the first
dot. This kernel is the decode twin of ops/attention.py's training
kernel: ONE pass over each row's K/V blocks with an online softmax, the
frontier mask read from the device-resident per-row ``pos`` state (never
attend past a row's own frontier), and int8→fp dequantization FUSED into
the score/probability math so quantized K/V is the only cache
representation that ever touches HBM.

Dequant-by-folding (why no fp K/V copy exists even transiently):
the per-position scales are constant across the head_dim contraction, so

    q · (k_int * k_scale) == (q · k_int) * k_scale      (fold into scores)
    p · (v_int * v_scale) == (p * v_scale) · v_int      (fold into probs)

Both folds are lane-dim (1, block_k) elementwise multiplies — no
cross-lane relayout, no (block_k, 1) scale column Mosaic can't express.
Scales are per (slot, head, position): one fp32 scalar per ≤128-lane
K/V row, i.e. per block-of-128-lanes of pool data (head_dim ≤ 128
everywhere this repo runs), ~6% byte overhead at D=64 against the 2-4x
the int8 values save.

Layouts: q (B, H, D) — the single query per row; k/v (B, H, L, D) in
fp32/bf16, or int8 with (B, H, L) f32 scales; lengths (B,) int32 = the
number of valid positions (the engine passes pos + 1: attend kpos <=
pos). Heads fold into the grid's row dim exactly like the training
kernel's (B*H, ...) flattening; each grid step owns one (slot, head)
row and walks only ceil(length / block_k) K/V blocks — blocks past the
frontier are skipped at the compute level (the fori_loop bound is the
row's own frontier), and the diagonal-split idiom from the training
kernel keeps the mask VPU work off the fully-valid blocks. DMA-level
block skipping (not fetching past-frontier blocks at all) belongs to
the ROADMAP-2 paged pool, whose block table this kernel is built to
page over.

Impl ladder (the training kernel's idiom, --decode_impl):
  'auto'             — Pallas when the compile probe passes (TPU),
                       warn_once + XLA otherwise;
  'pallas'           — pin the compiled Mosaic kernel;
  'pallas_interpret' — the same kernel through the Pallas interpreter,
                       so CPU CI exercises this file's exact math;
  'xla'              — the masked-score reference (also the fallback
                       models/gpt.py keeps inline for T > 1 verify
                       blocks and scalar-index prefill).
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128
# Sublane quantum that tiles legally for every KV dtype this kernel
# accepts (f32 needs 8, bf16 16, int8 32 — see the Pallas tiling table).
SUBLANE_QUANTUM = 32
DEFAULT_BLOCK_K = 256

__all__ = ["flash_decode", "flash_decode_paged", "flash_prefill_paged",
           "xla_decode_attention", "xla_decode_attention_paged",
           "resolve_decode_impl", "decode_compile_probe",
           "compile_probe_check", "quantize_kv_rows",
           "quantize_kv_rows_int4", "unpack_int4", "DECODE_IMPLS"]

DECODE_IMPLS = ("auto", "pallas", "pallas_interpret", "xla")


# ---------------------------------------------------------------------------
# Quantization (shared with models/gpt.py's cache writes)
# ---------------------------------------------------------------------------

def quantize_kv_rows(x: jax.Array, valid=None):
    """Per-row symmetric int8 quantization over the trailing (head_dim)
    axis: returns (values int8 same shape, scales f32 x.shape[:-1]).

    One scale per K/V row — for head_dim <= 128 a row is one <=128-lane
    register block, so this is the per-block-of-128 granularity the
    kernel folds into scores/probs. Symmetric round-to-nearest; the
    round-trip error per element is bounded by scale/2 =
    max|row| / 254 (pinned by tests/test_flash_decode.py). All-zero
    rows (parked slots, unwritten tail) quantize to zeros exactly.

    ``valid`` (optional bool, x.shape[:-1]-broadcastable): False rows
    skip the scale chain — scale pinned to 1 for the divide, values and
    the returned scale zeroed. Sentinel-drop rows in a prefill wave
    (ladder padding, parked block-table rows) feed writes that drop at
    the scatter, so their amax/divide/round work was pure waste."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-30) / 127.0
    if valid is not None:
        scale = jnp.where(valid, scale, 1.0)
        xf = jnp.where(valid[..., None], xf, 0.0)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    if valid is not None:
        scale = jnp.where(valid, scale, 0.0)
    return q, scale


def quantize_kv_rows_int4(x: jax.Array, valid=None):
    """Per-row symmetric int4 quantization, two nibbles per byte packed
    along head_dim: returns (packed uint8 x.shape[:-1] + (D//2,),
    scales f32 x.shape[:-1]).

    Same per-block-of-lanes scale granularity as the int8 path — one
    f32 residual scale per K/V row (= per (slot|block, head, position))
    — so the kernels fold it into scores/probs identically; only the
    value bytes halve again. Nibbles are biased (+8) so a packed byte
    holds positions 2d (low) and 2d+1 (high) of the row. scale =
    max|row| / 7: levels [-7, 7], round-trip error per element bounded
    by scale/2 = max|row| / 14 (the tests pin <= max|row| / 7.5 per
    block of lanes). All-zero rows quantize to zeros exactly (packed
    byte 0x88 decodes to 0 after the bias).

    ``valid`` (optional bool, shape x.shape[:-1] broadcastable): rows
    that are False skip the scale chain entirely — their scale is
    pinned to 1 and their values to the zero nibble, so sentinel-drop
    rows (ladder padding, parked block-table rows) never spend the
    amax/divide/round lane work feeding a write that drops anyway."""
    if x.shape[-1] % 2:
        raise ValueError(f"int4 packing needs an even head_dim, "
                         f"got {x.shape[-1]}")
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-30) / 7.0
    if valid is not None:
        scale = jnp.where(valid, scale, 1.0)
        xf = jnp.where(valid[..., None], xf, 0.0)
    q = (jnp.clip(jnp.round(xf / scale[..., None]), -7, 7)
         .astype(jnp.int32) + 8)                      # nibbles in [1, 15]
    packed = (q[..., 0::2] | (q[..., 1::2] << 4)).astype(jnp.uint8)
    if valid is not None:
        scale = jnp.where(valid, scale, 0.0)
    return packed, scale


def unpack_int4(packed: jax.Array) -> jax.Array:
    """Packed uint8 (..., D//2) -> int8 (..., D): the inverse of
    quantize_kv_rows_int4's nibble layout (low nibble first). Shared by
    the XLA fallback and the test oracles; the Pallas kernels inline
    the same two-op unpack per K/V tile."""
    lo = jnp.bitwise_and(packed, 15).astype(jnp.int8) - 8
    hi = jnp.right_shift(packed, 4).astype(jnp.int8) - 8
    return jnp.stack([lo, hi], axis=-1).reshape(
        *packed.shape[:-1], packed.shape[-1] * 2)


# ---------------------------------------------------------------------------
# XLA reference (the fallback + the test oracle)
# ---------------------------------------------------------------------------

def xla_decode_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         lengths: jax.Array, *, k_scale=None, v_scale=None,
                         sm_scale: float | None = None) -> jax.Array:
    """Masked single-query attention in plain jnp: q (B, H, D) against
    k/v (B, H, L, D) with per-row valid ``lengths`` (B,). int8 k/v take
    per-position scales (B, H, L), folded into scores/probs exactly as
    the kernel folds them — the two impls share one numeric contract.
    Packed-int4 k/v (uint8, trailing dim D//2) unpack first and then
    follow the identical scale-fold math."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if k.dtype == jnp.uint8:
        k, v = unpack_int4(k), unpack_int4(v)
    dtype = q.dtype
    s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                   k.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if k_scale is not None:
        s = s * k_scale
    s = s * sm_scale
    mask = jnp.arange(k.shape[2])[None, None, :] < lengths[:, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if v_scale is not None:
        p = p * v_scale
    return jnp.einsum("bhs,bhsd->bhd", p,
                      v.astype(jnp.float32),
                      preferred_element_type=jnp.float32).astype(dtype)


def xla_decode_attention_paged(q: jax.Array, k: jax.Array, v: jax.Array,
                               block_table: jax.Array,
                               lengths: jax.Array, *, k_scale=None,
                               v_scale=None,
                               sm_scale: float | None = None) -> jax.Array:
    """Single-query masked attention DIRECTLY over a block-paged pool —
    the XLA fallback's paged fast path. q (B, H, D); k/v (num_blocks,
    H, page, D) (int8, or packed-int4 uint8, with (num_blocks, H,
    page) scales); block_table (B, nb); lengths (B,). Returns (B, H, D).

    The old fallback gathered each row's chain into contiguous
    (B, H, nb*page, D) rows — a gather PLUS a transpose/reshape copy of
    the whole working set, per layer, per decode step (the measured
    paged-vs-dense CPU decode gap). Here the einsums contract straight
    against the gathered (B, nb, H, page, D) layout, so the relayout
    copy never happens; only the score tensor (tiny) reshapes for the
    softmax. Sentinel table entries (>= num_blocks) clamp to a real
    block and their positions sit past ``lengths``, masked like any
    stale tail."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    N, H, page, _ = k.shape
    B, nb = block_table.shape
    tbl = jnp.minimum(block_table, N - 1)
    gk, gv = k[tbl], v[tbl]                  # (B, nb, H, page, D')
    if k.dtype == jnp.uint8:
        gk, gv = unpack_int4(gk), unpack_int4(gv)
    dtype = q.dtype
    s = jnp.einsum("bhd,bjhpd->bhjp", q.astype(jnp.float32),
                   gk.astype(jnp.float32),
                   preferred_element_type=jnp.float32)
    if k_scale is not None:
        s = s * k_scale[tbl].transpose(0, 2, 1, 3)
    s = s * sm_scale
    kpos = (jnp.arange(nb)[:, None] * page
            + jnp.arange(page)[None, :])     # (nb, page)
    mask = kpos[None, None] < lengths[:, None, None, None]
    s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s.reshape(B, H, nb * page),
                       axis=-1).reshape(B, H, nb, page)
    if v_scale is not None:
        p = p * v_scale[tbl].transpose(0, 2, 1, 3)
    return jnp.einsum("bhjp,bjhpd->bhd", p, gv.astype(jnp.float32),
                      preferred_element_type=jnp.float32).astype(dtype)


# ---------------------------------------------------------------------------
# Pallas kernel
# ---------------------------------------------------------------------------

def _flash_decode_kernel(len_ref, q_ref, k_ref, v_ref, ks_ref, vs_ref,
                         o_ref, *, block_k: int, sm_scale: float,
                         heads: int, quantized: bool,
                         four_bit: bool = False):
    """One grid step == one (slot, head) row: walk the row's K/V blocks
    up to its OWN frontier with an online softmax. Same split-loop idiom
    as the training kernel: blocks fully inside the frontier skip the
    iota/compare mask (pure VPU cost), only the partial frontier block
    masks. ``four_bit`` K/V tiles arrive packed (two nibbles per byte
    along the lane dim) and unpack in-register — half the int8 HBM
    bytes stream in, and the fp representation still never exists."""
    b = pl.program_id(0)
    length = len_ref[b // heads]          # this row's valid positions
    # Dot dtype: int8 K/V feed the MXU in the QUERY's dtype (integers up
    # to 127 are exact in bf16) with f32 accumulation; full-precision
    # pools use the WIDER of (query, pool) — an fp32 pool under a bf16
    # query must not silently lose its precision on the flash path (the
    # XLA reference keeps fp32 operands there too).
    dot_dt = (q_ref.dtype if quantized
              else jnp.promote_types(q_ref.dtype, k_ref.dtype))
    q = q_ref[0].astype(dot_dt)           # (1, D)
    num_kb = lax.div(length + block_k - 1, block_k)
    num_kb_inner = lax.div(length, block_k)   # fully-valid blocks

    def body(j, carry, *, masked: bool):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        if four_bit:
            k = unpack_int4(k)
        # int8 K enters the dot WITHOUT its scale; the scale folds into
        # the (1, block_k) score row below — a lane-dim multiply, never
        # a dequantized K tile.
        s = lax.dot_general(q, k.astype(dot_dt), (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (1, bk)
        if quantized:
            s = s * ks_ref[0, :, pl.ds(j * block_k, block_k)]
        s = s * sm_scale
        if masked:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (1, block_k), 1)
            s = jnp.where(k_pos < length, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))  # (1, 1)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        if quantized:
            # v's scale folds into the probability row (p * s) @ v_int —
            # the normalizer l above sums the UNSCALED p, so the final
            # acc / l division is exactly softmax(s) @ (v_int * scale).
            p = p * vs_ref[0, :, pl.ds(j * block_k, block_k)]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        if four_bit:
            v = unpack_int4(v)
        acc_new = acc * alpha + lax.dot_general(
            p.astype(dot_dt), v.astype(dot_dt), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((1, q_ref.shape[2]), jnp.float32),
        jnp.full((1, 1), NEG_INF, jnp.float32),
        jnp.zeros((1, 1), jnp.float32),
    )
    carry = lax.fori_loop(0, num_kb_inner,
                          functools.partial(body, masked=False), init)
    acc, m, l = lax.fori_loop(num_kb_inner, num_kb,
                              functools.partial(body, masked=True), carry)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _clamp_block_k(L: int, block_k: int) -> tuple[int, int]:
    """(block_k, Lp): the largest SUBLANE_QUANTUM multiple <= the request
    that the padded pool length divides into — same divide-don't-pad
    policy as the training kernel's _clamp_blocks, on the 32-row quantum
    every KV dtype tiles at."""
    Lq = -(-L // SUBLANE_QUANTUM) * SUBLANE_QUANTUM
    b = max(SUBLANE_QUANTUM,
            block_k // SUBLANE_QUANTUM * SUBLANE_QUANTUM)
    b = min(b, Lq)
    while Lq % b:
        b -= SUBLANE_QUANTUM  # terminates at SUBLANE_QUANTUM
    return b, Lq


def decode_pad_copies(max_len: int, head_dim: int) -> bool:
    """True when flash_decode must PAD — i.e. copy — the pool on every
    call: max_len off the 32-row sublane quantum, or a head_dim outside
    the verified-unpadded set (64 / 128-multiples). On the HBM-bound
    decode hot path that copy roughly doubles per-step traffic, so the
    engine warns at construction instead of paying it silently."""
    return (max_len % SUBLANE_QUANTUM != 0
            or not (head_dim == 64 or head_dim % 128 == 0))


def flash_decode(q: jax.Array, k: jax.Array, v: jax.Array,
                 lengths: jax.Array, *, k_scale=None, v_scale=None,
                 sm_scale: float | None = None,
                 block_k: int = DEFAULT_BLOCK_K,
                 interpret: bool = False) -> jax.Array:
    """Single-query flash attention over per-row frontiers (see module
    docstring for layouts). Returns (B, H, D) in q's dtype."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be supplied together")
    if k_scale is not None and k.dtype not in (jnp.int8, jnp.uint8):
        raise ValueError(
            f"scales supplied for non-quantized k/v ({k.dtype}/{v.dtype})")
    quantized = k_scale is not None
    four_bit = quantized and k.dtype == jnp.uint8
    B, H, L, Dk = k.shape
    # Packed int4 stores two lanes per byte: the LOGICAL head_dim is
    # twice the stored trailing dim, and the pads below halve on the
    # packed operands.
    D = Dk * 2 if four_bit else Dk  # jaxlint: disable=tracer-leak -- four_bit is a static Python bool (dtype metadata, not data)
    if q.shape != (B, H, D):
        raise ValueError(f"q shape {q.shape} != {(B, H, D)}")
    block_k, Lp = _clamp_block_k(L, block_k)
    # head_dim padding: same verified rule as the training kernel
    # (ops/attention.py _pad_qkv) — 64 lanes and 128-multiples run
    # unpadded, anything else pads to the 128-lane tile. Packed int4
    # pads pad_D // 2 bytes (a zero byte unpacks to the -8 bias pair,
    # harmless: the matching q lanes are zero-padded so the score
    # contribution is exactly 0, and padded OUTPUT lanes are sliced).
    pad_D = 0 if (D == 64 or D % 128 == 0) else (-D) % 128
    pad_L = Lp - L
    pad_Dk = pad_D // 2 if four_bit else pad_D  # jaxlint: disable=tracer-leak -- four_bit is a static Python bool (dtype metadata, not data)
    if pad_D:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, pad_D)])
    if pad_Dk or pad_L:
        pads = [(0, 0), (0, 0), (0, pad_L), (0, pad_Dk)]
        k, v = jnp.pad(k, pads), jnp.pad(v, pads)
    Dp = D + pad_D
    Dkp = Dk + pad_Dk
    qf = q.reshape(B * H, 1, Dp)
    kf = k.reshape(B * H, Lp, Dkp)
    vf = v.reshape(B * H, Lp, Dkp)
    if k_scale is not None:
        spad = [(0, 0), (0, 0), (0, pad_L)]
        ksf = jnp.pad(k_scale.astype(jnp.float32), spad).reshape(
            B * H, 1, Lp)
        vsf = jnp.pad(v_scale.astype(jnp.float32), spad).reshape(
            B * H, 1, Lp)
    else:
        # Zero-size dummy operands would need their own BlockSpec rules;
        # a (B*H, 1, SUBLANE_QUANTUM-free) tiny array keeps the operand
        # list fixed across modes at negligible cost.
        ksf = vsf = jnp.ones((B * H, 1, LANES), jnp.float32)
    Ls = ksf.shape[2]

    kernel = functools.partial(
        _flash_decode_kernel, block_k=block_k, sm_scale=sm_scale,
        heads=H, quantized=quantized, four_bit=four_bit)
    out = pl.pallas_call(
        kernel,
        grid=(B * H,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1, Dp), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, Lp, Dkp), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, Lp, Dkp), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1, Ls), lambda b: (b, 0, 0)),
            pl.BlockSpec((1, 1, Ls), lambda b: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, Dp), lambda b: (b, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, 1, Dp), q.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel",)),
        interpret=interpret,
    )(jnp.asarray(lengths, jnp.int32), qf, kf, vf, ksf, vsf)
    return out.reshape(B, H, Dp)[:, :, :D]


# ---------------------------------------------------------------------------
# Paged variant: the block-table indirection (ROADMAP-2 / ISSUE 9)
# ---------------------------------------------------------------------------

def _paged_decode_kernel(len_ref, tbl_ref, q_ref, k_ref, v_ref, ks_ref,
                         vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                         page: int, heads: int, sm_scale: float,
                         num_kb: int, quantized: bool,
                         four_bit: bool = False):
    """One grid step == one (row, block-slot) pair of the flattened
    (B*H, max_blocks) grid. The CHUNK ADDRESS is the indirection: the
    BlockSpec index_map reads the scalar-prefetched block table, so the
    DMA for grid step (r, i) fetches pool block table[r // H, i] — the
    paged twin of flash_decode's contiguous pl.ds(i * block_k) walk.
    The online-softmax carry lives in VMEM scratch across the
    sequential block dim (dimension_semantics: the row dim is parallel,
    the block dim arbitrary); blocks at or past the row's frontier are
    skipped at the compute level via pl.when, and the frontier block
    masks by position exactly like the contiguous kernel. int8 dequant
    is the same fold: scales multiply the (1, page) score/probability
    rows, never a dequantized K/V tile."""
    r = pl.program_id(0)
    i = pl.program_id(1)
    length = len_ref[r // heads]

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i * page < length)
    def _block():
        dot_dt = (q_ref.dtype if quantized
                  else jnp.promote_types(q_ref.dtype, k_ref.dtype))
        q = q_ref[0].astype(dot_dt)                      # (1, D)
        k = k_ref[0, 0]                                  # (page, D)
        if four_bit:
            k = unpack_int4(k)
        s = lax.dot_general(q, k.astype(dot_dt), (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (1, page)
        if quantized:
            s = s * ks_ref[0, 0][None, :]
        s = s * sm_scale
        kpos = i * page + lax.broadcasted_iota(jnp.int32, (1, page), 1)
        s = jnp.where(kpos < length, s, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        if quantized:
            p = p * vs_ref[0, 0][None, :]
        v = v_ref[0, 0]
        if four_bit:
            v = unpack_int4(v)
        acc_ref[...] = acc_ref[...] * alpha + lax.dot_general(
            p.astype(dot_dt), v.astype(dot_dt), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[...] = m_new

    @pl.when(i == num_kb - 1)
    def _out():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def flash_decode_paged(q: jax.Array, k: jax.Array, v: jax.Array,
                       block_table: jax.Array, lengths: jax.Array, *,
                       k_scale=None, v_scale=None,
                       sm_scale: float | None = None,
                       interpret: bool = False) -> jax.Array:
    """Single-query flash attention over a BLOCK-PAGED pool.

    q (B, H, D); k/v (num_blocks, H, page, D) — the global block pool,
    fp32/bf16 or int8 with (num_blocks, H, page) f32 scales;
    block_table (B, max_blocks) int32 mapping each row's i-th logical
    chunk to a pool block (entries >= num_blocks are the engine's
    unallocated sentinel — clamped in the index_map, masked/skipped by
    length); lengths (B,) valid positions per row. Returns (B, H, D).

    Unlike flash_decode there is no pool-wide pad path for the block
    dim: ``page`` IS the DMA chunk, so the pool must be built with a
    legal page (fp32 tiles at 8 sublanes, bf16 16, int8 32 — int8 pools
    on real TPUs want page >= 32; the engine's paged_pad_copies warning
    covers this). head_dim follows the same verified rule as
    flash_decode (64 or 128-multiples unpadded; anything else pads
    q AND the pool — a per-call copy the engine warns about)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be supplied together")
    if k_scale is not None and k.dtype not in (jnp.int8, jnp.uint8):
        raise ValueError(
            f"scales supplied for non-quantized k/v ({k.dtype}/{v.dtype})")
    quantized = k_scale is not None
    four_bit = quantized and k.dtype == jnp.uint8
    N, H, page, Dk = k.shape
    D = Dk * 2 if four_bit else Dk  # jaxlint: disable=tracer-leak -- four_bit is a static Python bool (dtype metadata, not data)
    B = q.shape[0]
    if q.shape != (B, H, D):
        raise ValueError(f"q shape {q.shape} != {(B, H, D)}")
    if block_table.ndim != 2 or block_table.shape[0] != B:
        raise ValueError(
            f"block_table shape {block_table.shape} != ({B}, max_blocks)")
    nb = block_table.shape[1]
    pad_D = 0 if (D == 64 or D % 128 == 0) else (-D) % 128
    pad_Dk = pad_D // 2 if four_bit else pad_D  # jaxlint: disable=tracer-leak -- four_bit is a static Python bool (dtype metadata, not data)
    if pad_D:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, pad_D)])
        pads = [(0, 0), (0, 0), (0, 0), (0, pad_Dk)]
        k, v = jnp.pad(k, pads), jnp.pad(v, pads)
    Dp = D + pad_D
    Dkp = Dk + pad_Dk
    qf = q.reshape(B * H, 1, Dp)
    if k_scale is not None:
        ksf = k_scale.astype(jnp.float32)
        vsf = v_scale.astype(jnp.float32)
    else:
        # Fixed operand list across modes (flash_decode's idiom): a
        # 1-block dummy the index_map pins to block 0.
        ksf = vsf = jnp.ones((1, 1, page), jnp.float32)

    def q_map(r, i, lens, tbl):
        return (r, 0, 0)

    def kv_map(r, i, lens, tbl):
        # THE indirection: chunk i of row r DMAs pool block tbl[row, i].
        # Sentinel entries (>= N, the engine's unallocated marker) clamp
        # to a real block — their contents are never read (pl.when skips
        # whole blocks past the frontier, the iota mask the rest).
        return (jnp.minimum(tbl[r // H, i], N - 1), r % H, 0, 0)

    def scale_map(r, i, lens, tbl):
        if not quantized:
            return (0, 0, 0)
        return (jnp.minimum(tbl[r // H, i], N - 1), r % H, 0)

    kernel = functools.partial(
        _paged_decode_kernel, page=page, heads=H, sm_scale=sm_scale,
        num_kb=nb, quantized=quantized, four_bit=four_bit)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, nb),
            in_specs=[
                pl.BlockSpec((1, 1, Dp), q_map),
                pl.BlockSpec((1, 1, page, Dkp), kv_map),
                pl.BlockSpec((1, 1, page, Dkp), kv_map),
                pl.BlockSpec((1, 1, page), scale_map),
                pl.BlockSpec((1, 1, page), scale_map),
            ],
            out_specs=pl.BlockSpec((1, 1, Dp), q_map),
            scratch_shapes=[
                pltpu.VMEM((1, Dp), jnp.float32),
                pltpu.VMEM((1, 1), jnp.float32),
                pltpu.VMEM((1, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, 1, Dp), q.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(lengths, jnp.int32), jnp.asarray(block_table, jnp.int32),
      qf, k, v, ksf, vsf)
    return out.reshape(B, H, Dp)[:, :, :D]


def _paged_prefill_kernel(start_ref, tbl_ref, q_ref, k_ref, v_ref,
                          ks_ref, vs_ref, o_ref, acc_ref, m_ref, l_ref, *,
                          page: int, heads: int, sm_scale: float,
                          num_kb: int, T: int, quantized: bool,
                          four_bit: bool):
    """One grid step == one (row, block-slot) pair, exactly like the
    paged decode kernel — but the query is the row's whole (T, D)
    suffix block at positions start .. start+T-1, so one pass over the
    row's block chain computes the full prefill attention the XLA
    fallback had to GATHER the chain for. The split masked/unmasked
    idiom from the training kernel carries over with a traced split:
    a K/V block wholly at-or-before the first query position is valid
    for every (q, k) pair and skips the iota/compare entirely; only
    blocks overlapping the causal frontier pay the (T, page) mask.
    Block 0 is valid for every query row (kpos 0 <= any qpos), so the
    online-softmax carry is finite from the first executed block and
    later fully-masked rows renormalize cleanly (p underflows to 0
    against a finite m)."""
    r = pl.program_id(0)
    i = pl.program_id(1)
    base = start_ref[r // heads]          # first query position
    end = base + T                        # one past the last query

    @pl.when(i == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when(i * page < end)
    def _block():
        dot_dt = (q_ref.dtype if quantized
                  else jnp.promote_types(q_ref.dtype, k_ref.dtype))
        q = q_ref[0].astype(dot_dt)                      # (T, D)
        k = k_ref[0, 0]                                  # (page, D)
        if four_bit:
            k = unpack_int4(k)
        s = lax.dot_general(q, k.astype(dot_dt), (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (T, page)
        if quantized:
            s = s * ks_ref[0, 0][None, :]
        s = s * sm_scale

        def _accumulate(s):
            m_prev, l_prev = m_ref[...], l_ref[...]
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m_prev - m_new)
            l_ref[...] = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
            if quantized:
                p = p * vs_ref[0, 0][None, :]
            v = v_ref[0, 0]
            if four_bit:
                v = unpack_int4(v)
            acc_ref[...] = acc_ref[...] * alpha + lax.dot_general(
                p.astype(dot_dt), v.astype(dot_dt),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_ref[...] = m_new

        # The split: max kpos of this block is (i+1)*page - 1; when it
        # sits at or before the FIRST query position ``base`` the whole
        # (T, page) tile is causally valid — no iota, no compare, no
        # select. Only frontier-overlapping blocks mask.
        inner = (i + 1) * page <= base + 1

        @pl.when(inner)
        def _unmasked():
            _accumulate(s)

        @pl.when(jnp.logical_not(inner))
        def _frontier():
            kpos = i * page + lax.broadcasted_iota(jnp.int32, (T, page), 1)
            qpos = base + lax.broadcasted_iota(jnp.int32, (T, page), 0)
            _accumulate(jnp.where(kpos <= qpos, s, NEG_INF))

    @pl.when(i == num_kb - 1)
    def _out():
        o_ref[0] = (acc_ref[...] / l_ref[...]).astype(o_ref.dtype)


def flash_prefill_paged(q: jax.Array, k: jax.Array, v: jax.Array,
                        block_table: jax.Array, start: jax.Array, *,
                        k_scale=None, v_scale=None,
                        sm_scale: float | None = None,
                        interpret: bool = False) -> jax.Array:
    """Multi-query (T > 1) flash attention over a BLOCK-PAGED pool —
    the prefill/verify twin of flash_decode_paged, replacing the
    gathered-masked XLA fallback that was the last non-kernel hot path.

    q (B, H, T, D) — row b's suffix queries at positions start[b] ..
    start[b]+T-1 (the serve engine's per-row prefix-hit frontier; 0 for
    a cold prefill). k/v (num_blocks, H, page, D) — the global pool,
    fp32/bf16, int8 with (num_blocks, H, page) f32 scales, or packed
    int4 (uint8, trailing dim D//2) with the same scale shape;
    block_table (B, max_blocks) int32 with the engine's >= num_blocks
    sentinel for unallocated entries (clamped in the index_map, their
    contents never attended: positions past start+T are skipped at the
    grid level and the causal mask covers the frontier block). The pool
    must already contain the suffix K/V (the caller scatters before it
    attends, the same order the XLA path uses). Returns (B, H, T, D).

    Each (row, head) walks only ceil((start+T) / page) blocks — the
    resident-prefix blocks included, which is exactly the read a prefix
    hit pays instead of recomputing the prefix forward — and the chunk
    address is the scalar-prefetched table indirection, so the chain is
    never gathered into a contiguous copy (the per-wave byte cost the
    XLA fallback pays and this kernel exists to kill)."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be supplied together")
    if k_scale is not None and k.dtype not in (jnp.int8, jnp.uint8):
        raise ValueError(
            f"scales supplied for non-quantized k/v ({k.dtype}/{v.dtype})")
    quantized = k_scale is not None
    four_bit = quantized and k.dtype == jnp.uint8
    N, H, page, Dk = k.shape
    D = Dk * 2 if four_bit else Dk  # jaxlint: disable=tracer-leak -- four_bit is a static Python bool (dtype metadata, not data)
    B, _, T, _ = q.shape
    if q.shape != (B, H, T, D):
        raise ValueError(f"q shape {q.shape} != {(B, H, T, D)}")
    if block_table.ndim != 2 or block_table.shape[0] != B:
        raise ValueError(
            f"block_table shape {block_table.shape} != ({B}, max_blocks)")
    nb = block_table.shape[1]
    pad_D = 0 if (D == 64 or D % 128 == 0) else (-D) % 128
    pad_Dk = pad_D // 2 if four_bit else pad_D  # jaxlint: disable=tracer-leak -- four_bit is a static Python bool (dtype metadata, not data)
    if pad_D:
        q = jnp.pad(q, [(0, 0), (0, 0), (0, 0), (0, pad_D)])
        pads = [(0, 0), (0, 0), (0, 0), (0, pad_Dk)]
        k, v = jnp.pad(k, pads), jnp.pad(v, pads)
    Dp = D + pad_D
    Dkp = Dk + pad_Dk
    # (B, H, T, Dp) -> (B*H, T, Dp): heads fold into the row dim, the
    # same flattening as the decode kernels.
    qf = q.reshape(B * H, T, Dp)
    if k_scale is not None:
        ksf = k_scale.astype(jnp.float32)
        vsf = v_scale.astype(jnp.float32)
    else:
        ksf = vsf = jnp.ones((1, 1, page), jnp.float32)

    def q_map(r, i, start, tbl):
        return (r, 0, 0)

    def kv_map(r, i, start, tbl):
        return (jnp.minimum(tbl[r // H, i], N - 1), r % H, 0, 0)

    def scale_map(r, i, start, tbl):
        if not quantized:
            return (0, 0, 0)
        return (jnp.minimum(tbl[r // H, i], N - 1), r % H, 0)

    kernel = functools.partial(
        _paged_prefill_kernel, page=page, heads=H, sm_scale=sm_scale,
        num_kb=nb, T=T, quantized=quantized, four_bit=four_bit)
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B * H, nb),
            in_specs=[
                pl.BlockSpec((1, T, Dp), q_map),
                pl.BlockSpec((1, 1, page, Dkp), kv_map),
                pl.BlockSpec((1, 1, page, Dkp), kv_map),
                pl.BlockSpec((1, 1, page), scale_map),
                pl.BlockSpec((1, 1, page), scale_map),
            ],
            out_specs=pl.BlockSpec((1, T, Dp), q_map),
            scratch_shapes=[
                pltpu.VMEM((T, Dp), jnp.float32),
                pltpu.VMEM((T, 1), jnp.float32),
                pltpu.VMEM((T, 1), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B * H, T, Dp), q.dtype),
        compiler_params=None if interpret else pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary")),
        interpret=interpret,
    )(jnp.asarray(start, jnp.int32), jnp.asarray(block_table, jnp.int32),
      qf, k, v, ksf, vsf)
    return out.reshape(B, H, T, Dp)[:, :, :, :D]


def paged_pad_copies(page: int, head_dim: int) -> bool:
    """True when flash_decode_paged must pad — copy — the POOL on every
    call: head_dim outside the verified-unpadded set. (A page off the
    int8 32-sublane quantum shows up as a compile-probe failure, not a
    pad: the page is the DMA chunk and cannot be padded in place.)"""
    return not (head_dim == 64 or head_dim % 128 == 0)


# ---------------------------------------------------------------------------
# Dispatch: probe + impl ladder
# ---------------------------------------------------------------------------

_PROBE: dict[str, bool] = {}


def _backend() -> str:
    return jax.default_backend()


def compile_probe_check(*, interpret: bool = False) -> None:
    """AOT lower+compile the kernels on tiny shapes in EVERY kv mode
    (fp, int8-with-scales, packed int4), BOTH pool layouts (contiguous
    slot rows and the block-paged table) and BOTH query shapes (the T=1
    decode walk and the T>1 paged prefill), raising on failure. The ONE
    probe harness — decode_compile_probe (the 'auto' gate) and
    bench.py's preflight_decode_impls both call it, so the shapes the
    ladder is judged on can never drift between the two."""
    dt = jnp.float32 if interpret else jnp.bfloat16
    q = jax.ShapeDtypeStruct((2, 2, 64), dt)
    kv = jax.ShapeDtypeStruct((2, 2, 256, 64), dt)
    kv8 = jax.ShapeDtypeStruct((2, 2, 256, 64), jnp.int8)
    kv4 = jax.ShapeDtypeStruct((2, 2, 256, 32), jnp.uint8)
    sc = jax.ShapeDtypeStruct((2, 2, 256), jnp.float32)
    ln = jax.ShapeDtypeStruct((2,), jnp.int32)
    # Paged shapes: an 8-block pool at the int8-legal page (32 rows).
    pkv = jax.ShapeDtypeStruct((8, 2, 32, 64), dt)
    pkv8 = jax.ShapeDtypeStruct((8, 2, 32, 64), jnp.int8)
    pkv4 = jax.ShapeDtypeStruct((8, 2, 32, 32), jnp.uint8)
    psc = jax.ShapeDtypeStruct((8, 2, 32), jnp.float32)
    tbl = jax.ShapeDtypeStruct((2, 4), jnp.int32)
    qT = jax.ShapeDtypeStruct((2, 2, 32, 64), dt)

    def fp(q, k, v, n):
        return flash_decode(q, k, v, n, interpret=interpret)

    def q8(q, k, v, n, ks, vs):
        return flash_decode(q, k, v, n, k_scale=ks, v_scale=vs,
                            interpret=interpret)

    def pfp(q, k, v, t, n):
        return flash_decode_paged(q, k, v, t, n, interpret=interpret)

    def pq8(q, k, v, t, n, ks, vs):
        return flash_decode_paged(q, k, v, t, n, k_scale=ks, v_scale=vs,
                                  interpret=interpret)

    def prefp(q, k, v, t, s):
        return flash_prefill_paged(q, k, v, t, s, interpret=interpret)

    def preq8(q, k, v, t, s, ks, vs):
        return flash_prefill_paged(q, k, v, t, s, k_scale=ks, v_scale=vs,
                                   interpret=interpret)

    jax.jit(fp).lower(q, kv, kv, ln).compile()
    jax.jit(q8).lower(q, kv8, kv8, ln, sc, sc).compile()
    jax.jit(q8).lower(q, kv4, kv4, ln, sc, sc).compile()
    jax.jit(pfp).lower(q, pkv, pkv, tbl, ln).compile()
    jax.jit(pq8).lower(q, pkv8, pkv8, tbl, ln, psc, psc).compile()
    jax.jit(pq8).lower(q, pkv4, pkv4, tbl, ln, psc, psc).compile()
    jax.jit(prefp).lower(qT, pkv, pkv, tbl, ln).compile()
    jax.jit(preq8).lower(qT, pkv8, pkv8, tbl, ln, psc, psc).compile()
    jax.jit(preq8).lower(qT, pkv4, pkv4, tbl, ln, psc, psc).compile()


def decode_compile_probe() -> bool:
    """True iff the flash-decode kernel compiles on the current default
    backend, in BOTH kv modes — 'auto' must not promise a fallback it
    only checked for one mode. Compile-only AOT on tiny shapes, cached
    per process per backend, exactly like ops/attention.py's
    pallas_compile_probe."""
    backend = _backend()
    if backend in _PROBE:
        return _PROBE[backend]
    if backend != "tpu":
        _PROBE[backend] = False
        return False
    try:
        compile_probe_check()
        _PROBE[backend] = True
    except Exception as e:  # Mosaic lowering / compile failure
        warnings.warn(
            "Pallas flash-decode failed to compile on this TPU; decode "
            f"attention falls back to the XLA path. Error: {e}")
        _PROBE[backend] = False
    return _PROBE[backend]


def resolve_decode_impl(impl: str) -> str:
    """'auto' -> 'pallas' when the probe passes, else 'xla' — with a
    warn_once when a TPU lands on the fallback (a silent 2x decode
    slowdown is exactly the failure mode that must not be silent).
    Explicit impls pass through untouched (never probed)."""
    if impl not in DECODE_IMPLS:
        raise ValueError(f"unknown decode impl: {impl!r} "
                         f"(expected one of {DECODE_IMPLS})")
    if impl != "auto":
        return impl
    if decode_compile_probe():
        return "pallas"
    if _backend() == "tpu":
        from nanosandbox_tpu.utils.metrics import warn_once

        warn_once(
            "flash-decode-xla-fallback",
            "[serve] flash-decode Pallas kernel unavailable on this TPU "
            "(compile probe failed) — decode attention is running on the "
            "XLA fallback path, ~2x the HBM traffic per token. Pin "
            "--decode_impl=xla to silence, or fix the kernel regression.")
    return "xla"
