"""TPU ops: Pallas kernels with pure-XLA fallbacks."""

from nanosandbox_tpu.ops.attention import causal_attention  # noqa: F401
