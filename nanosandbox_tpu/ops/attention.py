"""Causal self-attention: Pallas flash-attention (fwd + bwd) for TPU + XLA fallback.

The reference's training core (karpathy/nanoGPT, exercised via
/root/reference/notebooks/colab_nanoGPT_companion.ipynb:71-78) relies on
torch scaled_dot_product_attention/CUDA flash kernels. The TPU-native
equivalent is a Pallas kernel compiled by Mosaic: the forward pass is an
online-softmax (flash) kernel that never materializes the (T, T) score
matrix in HBM, tiled to the MXU (128-lane blocks, f32 accumulation).

The backward pass is two Pallas kernels under jax.custom_vjp sharing the
forward's per-row logsumexp L and the precomputed row term
Drow = rowsum(dO * O): one computes dQ (parallel over query blocks), the
other dK/dV (parallel over key blocks); both recompute P = exp(S - L)
block-by-block instead of saving the (T, T) probability matrix, and both
skip fully-masked blocks at the causal frontier.

Mosaic layout note: per-row softmax stats (L, Drow) are stored
lane-REPLICATED as (..., T, 128) arrays — Mosaic requires the last two
block dims of every operand to tile onto (8, 128) sublane×lane registers,
so a (1, block_q) row-vector block cannot lower; broadcasting each row
stat across the 128-lane minor dim (the same layout jax's own
pallas.ops.tpu.flash_attention uses) makes every BlockSpec legal at the
cost of a 128x blowup on two tiny T-length vectors.

Layouts: q, k, v are (B, H, T, D). D (head_dim) is padded to a multiple of
128 lanes and T to a multiple of the 128-row block inside the Pallas path.
"""

from __future__ import annotations

import functools
import warnings

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _tpu_params(*semantics: str):
    """Mosaic grid-dimension semantics: 'parallel' dims may be executed in
    any order / across cores, letting the pipeline prefetch blocks across
    grid steps instead of serializing them.

    vmem_limit_bytes raises Mosaic's default (~16 MB) VMEM budget check to
    100 MB of the chip's 128: the backward kernels stream q/do/o as
    full-T blocks, whose footprint scales with sequence length — at the
    default budget the backward stops COMPILING between T=8192 and 16384
    (and the 'replicated' stat layout already fails at 8192 with 12
    heads). The limit is a constraint check, not an allocation: small
    kernels are unaffected (124M bench measured identical), and with it
    the single-shard envelope extends through T=32768 (r5, v5e)."""
    return pltpu.CompilerParams(dimension_semantics=semantics,
                                vmem_limit_bytes=100 * 1024 * 1024)

NEG_INF = -1e30
LANES = 128  # minor-dim register width; row stats are replicated across it

__all__ = ["causal_attention", "xla_attention", "flash_attention",
           "flash_attention_dropout", "flash_attention_lse",
           "flash_attention_lse_dropout", "hash_dropout_keep_mask",
           "pallas_compile_probe"]


# ---------------------------------------------------------------------------
# In-kernel dropout mask
# ---------------------------------------------------------------------------
#
# Attention-probability dropout needs the SAME keep-mask in the forward and
# both backward kernels (they recompute P block-by-block instead of saving
# it). pltpu.prng_* can't provide that — reseeding per tile would work on
# hardware but the interpreter returns zero bits, so the CPU test tier
# could never exercise the masked math. Instead the mask is a pure
# counter-based hash (murmur3's fmix32 finalizer) over the GLOBAL
# (q_pos, k_pos) element index, keyed by a per-call seed mixed with the
# batch*head grid index: any (fwd, bwd-dq, bwd-dkv) kernel visiting the
# same score element derives the same bit from plain uint32 VPU ops, in
# compiled and interpret mode alike. ~6 integer ops per element, noise
# against the two MXU matmuls that touch the same tile.

_GOLDEN = 0x9E3779B9  # 2^32 / golden ratio; decorrelates the bh stream


def _fmix32(h: jax.Array) -> jax.Array:
    """murmur3 32-bit finalizer — a cheap bijective avalanche on uint32."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


# The seed operand is a (5,) uint32 vector so the mask can be keyed on
# GLOBAL coordinates under sequence/tensor parallelism (ring attention —
# each ring step sees a different slice of the global score matrix, and
# sharded batches/heads must draw distinct streams):
#   [0] per-call seed   [1] global batch offset of row 0
#   [2] global head offset of head 0   [3] global q position of row 0
#   [4] global k position of col 0
# All zeros for the plain (non-ring) path, which makes the stream id
# reduce to the local bh index — bit-identical to the pre-ring masks.
SEED_WORDS = 5


def _dropout_tile_seed(seed_ref, bh, local_heads: int,
                       hash_heads: int) -> jax.Array:
    """Per-(call, GLOBAL batch*head) uint32 stream key. local_heads is the
    head count of this kernel call's arrays; hash_heads the global head
    count the stream id is linearized over (equal when not head-sharded)."""
    bh = bh.astype(jnp.uint32)
    b = bh // jnp.uint32(local_heads) + seed_ref[1]
    h = bh % jnp.uint32(local_heads) + seed_ref[2]
    gbh = b * jnp.uint32(hash_heads) + h
    return _fmix32(seed_ref[0] ^ (gbh * jnp.uint32(_GOLDEN)))


def _dropout_keep(mix: jax.Array, q_start, k_start, shape: tuple[int, int],
                  seq_len: int, rate: float) -> jax.Array:
    """Boolean keep-mask for the (block_q, block_k) tile whose top-left
    element is (q_start, k_start) in the padded (seq_len, seq_len) score
    matrix. Element identity is positional, so every kernel agrees no
    matter which grid axis it iterates."""
    q_pos = q_start + lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = k_start + lax.broadcasted_iota(jnp.int32, shape, 1)
    idx = (q_pos.astype(jnp.uint32) * jnp.uint32(seq_len)
           + k_pos.astype(jnp.uint32))
    threshold = jnp.uint32(min(int(round(rate * 2**32)), 2**32 - 1))
    return _fmix32(idx ^ mix) >= threshold


def _apply_dropout(x: jax.Array, keep: jax.Array, rate: float) -> jax.Array:
    """Inverted dropout: zero masked elements, rescale kept ones by
    1/(1-rate). Single-sourced so the fwd and both bwd kernels can never
    drift in how kept elements are scaled."""
    return jnp.where(keep, x * (1.0 / (1.0 - rate)), 0.0)


# ---------------------------------------------------------------------------
# XLA reference implementation (also the backward recompute path)
# ---------------------------------------------------------------------------

def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  *, causal: bool = True, sm_scale: float | None = None,
                  dropout_rate: float = 0.0,
                  dropout_rng: jax.Array | None = None) -> jax.Array:
    """Plain attention; XLA fuses this adequately for short-T and CPU tests.

    dropout_rate/dropout_rng apply inverted dropout to the softmax weights
    (nanoGPT's attn_dropout; the reference model regularizes attention
    probabilities as well as residuals).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    q32 = q.astype(jnp.float32) * sm_scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k.astype(jnp.float32))
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    # Saveable under remat_policy='save_attention' (the AD backward of
    # this einsum needs p and v, not o, so saving o prunes the p@v
    # forward recompute — the one piece of XLA-path attention a
    # save-the-output policy can elide).
    from jax.ad_checkpoint import checkpoint_name

    return checkpoint_name(o.astype(q.dtype), "attn_out")


# ---------------------------------------------------------------------------
# Pallas flash forward
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                      block_q: int, block_k: int, sm_scale: float,
                      causal: bool, dropout_rate: float = 0.0,
                      local_heads: int = 1, hash_heads: int = 1,
                      hash_seq_len: int = 0):
    qi = pl.program_id(1)
    if dropout_rate > 0.0:
        mix = _dropout_tile_seed(seed_ref, pl.program_id(0),
                                 local_heads, hash_heads)
        q_off = seed_ref[3].astype(jnp.int32)
        k_off = seed_ref[4].astype(jnp.int32)
    # Keep MXU inputs in their storage dtype (bf16 on TPU) with float32
    # ACCUMULATION — pre-casting to f32 would run the matmuls at the MXU's
    # f32 rate, ~8x slower. Scores are scaled in f32 after the dot instead
    # of scaling q (same math, better bf16 numerics).
    q = q_ref[0]                                           # (block_q, D)
    seq_len = k_ref.shape[1]
    head_dim = q_ref.shape[2]

    if causal:
        # Only iterate k blocks at or before this q block's frontier, and
        # split the walk at the diagonal: blocks strictly below it need no
        # causal mask, so the iota/compare/select VPU work (a real cost —
        # the per-tile matmuls are tiny at head_dim 64, leaving the kernel
        # VPU-bound) only runs on the block(s) the frontier crosses.
        num_kb = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        num_kb_inner = lax.div(qi * block_q, block_k)  # fully-unmasked
    else:
        num_kb = seq_len // block_k
        num_kb_inner = num_kb

    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry, *, masked: bool):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, bk)
        s = s * sm_scale
        if masked:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))  # (bq, 1)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        # The softmax normalizer l accumulates UNMASKED p — dropout applies
        # to the normalized probabilities (o = dropout(softmax(s)) @ v), and
        # masking commutes with the final per-row division by l, so masking
        # only the p@v accumulation implements exactly that.
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        if dropout_rate > 0.0:
            keep = _dropout_keep(mix, q_off + qi * block_q,
                                 k_off + j * block_k,
                                 (block_q, block_k), hash_seq_len,
                                 dropout_rate)
            p_v = _apply_dropout(p, keep, dropout_rate)
        else:
            p_v = p
        acc_new = acc * alpha + lax.dot_general(
            p_v.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q, 1), NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    carry = lax.fori_loop(0, num_kb_inner,
                          functools.partial(body, masked=False), init)
    # For non-causal calls num_kb_inner == num_kb and this loop is empty.
    acc, m, l = lax.fori_loop(num_kb_inner, num_kb,
                              functools.partial(body, masked=True), carry)
    o_ref[0] = (acc / l).astype(o_ref.dtype)
    # Per-row logsumexp, the softmax residual the flash backward needs
    # (recomputing p = exp(s - L) block-by-block instead of saving (T, T)),
    # written lane-replicated: (block_q, 1) broadcast across the 128-lane
    # minor dim so the output block tiles legally onto Mosaic registers.
    lse_ref[0] = jnp.broadcast_to(m + jnp.log(l), (block_q, LANES))


DEFAULT_BLOCK = 512  # measured on v5e: 512x512 runs ~2.3-3x faster than
# 128x128 (fewer grid programs; the MXU pipeline amortizes over bigger
# score tiles) while a 512x512 f32 score tile is only 1 MiB of VMEM.

# The dKV kernel's own best blocking differs from dQ's: it is parallel
# over KEY blocks with an inner loop over q blocks, so a WIDE block_k
# (fewer grid programs, each amortizing the q/do/o streams) wins — r5
# microbench on v5e at (16, 12, 1024, 64): dkv 512x1024 = 1.26 ms vs
# 512x512 = 1.37 ms, and the combined fwd+bwd layer drops ~25% once the
# two backward kernels stop sharing one compromise blocking.
DKV_BLOCK_K = 1024


def _clamp_blocks(T: int, block_q: int, block_k: int) -> tuple[int, int]:
    """Pick per-call block sizes: the largest value <= the requested block
    that DIVIDES the 128-padded sequence length. Dividing (not just
    clamping) matters for T between block multiples — e.g. T=640 must use
    128-row blocks, not pad up to 1024 and burn +60% attention FLOPs on
    pad rows (and it keeps non-causal calls, which forbid T padding,
    working for every 128-multiple T)."""
    Tp128 = -(-T // LANES) * LANES

    def pick(b: int) -> int:
        # Round a caller-supplied block down to the LANES grid first: the
        # divisor search below steps by LANES and only terminates from a
        # LANES multiple (e.g. b=200 would step 200,72,... past 128 and
        # never divide Tp128).
        b = max(LANES, b // LANES * LANES)
        b = min(b, Tp128)
        while Tp128 % b:
            b -= LANES  # terminates at 128, which always divides Tp128
        return b

    return pick(block_q), pick(block_k)


def _pad_qkv(q, k, v, block_q, block_k, causal):
    """Pad head_dim to the 128-lane tile and T to the block size; returns
    padded (B*H, Tp, Dp)-flattened tensors plus the pad bookkeeping."""
    if block_q % 8 or block_k % LANES:
        raise ValueError(
            f"block_q must be a multiple of 8 and block_k of {LANES} "
            f"(got {block_q}, {block_k}): Mosaic tiles blocks onto "
            f"(8, 128) sublane*lane registers")
    B, H, T, D = q.shape
    # Head-dim padding: Mosaic's (8, 128) register tiling accepts a
    # 64-lane minor dim directly (verified compiled + correct on v5e),
    # so GPT-2's D=64 runs UNPADDED — the old unconditional pad-to-128
    # doubled every q/k/v/o/do stream and grad write in HBM. Only the
    # VERIFIED cases skip padding (64 exactly, or full 128-lane
    # multiples); other dims — including 128k+64 shapes like 192, a
    # partial-trailing-tile case never exercised — keep the proven
    # pad-to-128-multiple path.
    pad_D = 0 if (D == 64 or D % 128 == 0) else (-D) % 128
    if pad_D:
        pads = [(0, 0), (0, 0), (0, 0), (0, pad_D)]
        q, k, v = (jnp.pad(x, pads) for x in (q, k, v))
    pad_T = (-T) % max(block_q, block_k)
    if pad_T:
        # Padded key rows would attract softmax mass for padded query rows
        # only; padded queries are sliced off after the kernel, and causal
        # masking keeps real queries from seeing padded (future) keys.
        pads = [(0, 0), (0, 0), (0, pad_T), (0, 0)]
        q, k, v = (jnp.pad(x, pads) for x in (q, k, v))
        if not causal:
            raise ValueError("non-causal pallas path requires T % block == 0")
    Tp, Dp = q.shape[2], q.shape[3]
    flat = lambda x: x.reshape(B * H, Tp, Dp)
    return flat(q), flat(k), flat(v), (B, H, T, D, Tp, Dp, pad_T, pad_D)


def _dropout_seed_arg(seed, dropout_rate: float = 0.0) -> jax.Array:
    """Normalize the optional dropout seed to the (SEED_WORDS,) uint32
    SMEM operand every kernel takes (ignored when dropout_rate == 0).
    Accepts a scalar/(1,) seed (offsets zero — the non-ring path) or a
    full (SEED_WORDS,) vector (ring callers supply global offsets)."""
    if seed is None:
        if dropout_rate > 0.0:
            # A silent constant seed would drop the SAME attention entries
            # every step — a fixed sparsity pattern, not regularization.
            raise ValueError(
                "flash attention dropout needs a per-step seed ((1,) "
                "uint32) when dropout_rate > 0")
        return jnp.zeros((SEED_WORDS,), jnp.uint32)
    seed = jnp.asarray(seed, jnp.uint32).reshape(-1)
    if seed.shape[0] == SEED_WORDS:
        return seed
    return jnp.concatenate(
        [seed[:1], jnp.zeros((SEED_WORDS - 1,), jnp.uint32)])


def _check_dropout_seq_len(dropout_rate: float, padded_len: int) -> None:
    """The keep-mask hashes q_pos * seq_len + k_pos in uint32, which is
    collision-free only while seq_len**2 <= 2**32; beyond that, rows
    would silently share masks (correlated dropout)."""
    if dropout_rate > 0.0 and padded_len > 65536:
        raise ValueError(
            f"flash attention dropout supports sequence lengths up to "
            f"65536 (padded {padded_len}): the positional mask hash "
            "would wrap uint32 and correlate rows")


def _pallas_flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      causal: bool, sm_scale: float,
                      block_q: int = DEFAULT_BLOCK,
                      block_k: int = DEFAULT_BLOCK,
                      interpret: bool = False,
                      dropout_rate: float = 0.0, seed=None,
                      hash_heads: int | None = None,
                      hash_seq_len: int | None = None):
    """Returns (out, lse) — lse is the lane-replicated per-row logsumexp
    with PADDED shape (B*H, Tp, 128); the bwd kernels consume it as-is.

    hash_heads / hash_seq_len: GLOBAL head count and sequence length the
    dropout mask hash is keyed over (ring callers pass the global values
    with per-shard offsets in the seed vector); default local/padded."""
    block_q, block_k = _clamp_blocks(q.shape[2], block_q, block_k)
    qf, kf, vf, (B, H, T, D, Tp, Dp, pad_T, pad_D) = _pad_qkv(
        q, k, v, block_q, block_k, causal)

    hash_heads = hash_heads if hash_heads is not None else H
    hash_seq_len = hash_seq_len if hash_seq_len is not None else Tp
    _check_dropout_seq_len(dropout_rate, hash_seq_len)
    grid = (B * H, Tp // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k,
        sm_scale=sm_scale, causal=causal, dropout_rate=dropout_rate,
        local_heads=H, hash_heads=hash_heads, hash_seq_len=hash_seq_len)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tp, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tp, Dp), lambda b, i: (b, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, LANES), lambda b, i: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tp, Dp), q.dtype),
            jax.ShapeDtypeStruct((B * H, Tp, LANES), jnp.float32),
        ],
        compiler_params=None if interpret else _tpu_params(
            "parallel", "parallel"),
        interpret=interpret,
    )(_dropout_seed_arg(seed, dropout_rate), qf, kf, vf)
    out = out.reshape(B, H, Tp, Dp)[:, :, :T, :D]
    return out, lse


# ---------------------------------------------------------------------------
# Pallas flash backward
# ---------------------------------------------------------------------------
#
# Stat-operand layouts (--attention_stat_layout):
#   'replicated' (default): per-row stats broadcast across the 128-lane
#     minor dim, (B*H, Tp, LANES) f32 — every BlockSpec trivially legal,
#     but the backward streams ~128x more stat bytes from HBM than the
#     information content (~400 MB/layer/step at the 124M bench shape).
#   'compact': the (Tp,) stat vector reshaped to (Tp//LANES, LANES) rows —
#     dense in HBM (the minor dim carries REAL data, so XLA's (8, 128)
#     tiling pads nothing). The catch: inside the kernel the (rows, LANES)
#     tile must become a (block_q, 1) column, a cross-lane -> sublane
#     relayout Mosaic cannot express as a plain reshape. _expand_stat_tile
#     does it with a tiny selection matmul + masked rowsum — ops that
#     always lower (MXU + VPU), no relayout primitive needed.
#
# Standard flash-attention backward split into two kernels sharing the
# forward's per-row logsumexp L and the precomputed row term
# Drow = rowsum(dO * O):
#   dQ_i  = sm_scale * sum_j dS_ij @ K_j
#   dK_j  = sm_scale * sum_i dS_ij^T @ Q_i
#   dV_j  = sum_i P_ij^T @ dO_i
# with P = exp(S*scale - L) recomputed per block (never materialized at
# (T, T)), dP = dO @ V^T, dS = P * (dP - Drow). The causal frontier skips
# fully-masked blocks, halving the work the XLA-recompute backward did.

def _expand_stat_tile(tile: jax.Array, row_offset, block_q: int) -> jax.Array:
    """FULL compact stat tile (R, LANES) -> the (block_q, 1) column for
    global rows [row_offset*LANES, row_offset*LANES + block_q), where
    tile[r, c] holds the stat for global row r*LANES + c.

    The needed cross-lane -> sublane relayout is built from ops Mosaic
    always lowers: a (block_q, R) 0/1 selection matmul (which also absorbs
    the q-block's row offset — Mosaic forbids sub-8-sublane stat blocks
    AND dynamic sublane slicing at unaligned offsets, so selecting rows
    via the contraction sidesteps both) replicates each stat row across
    the 128 q-rows it covers, then a masked rowsum picks each q-row's own
    lane. ~block_q*R MACs + block_q*LANES VPU ops — noise against the
    (bq, bk) @ (bk, D) main matmuls. row_offset may be a traced scalar
    (it is grid-position-dependent)."""
    R, lanes = tile.shape
    sel = (lax.broadcasted_iota(jnp.int32, (block_q, R), 0) // lanes
           + row_offset
           == lax.broadcasted_iota(jnp.int32, (block_q, R), 1))
    # HIGHEST precision: each output element sums exactly ONE tile value,
    # so full-f32 passes make the expansion bit-exact (default MXU f32
    # precision would round lse to ~bf16 and visibly perturb p = exp(s-L));
    # the matmul is tiny, the extra passes are free.
    spread = lax.dot_general(sel.astype(jnp.float32), tile,
                             (((1,), (0,)), ((), ())),
                             preferred_element_type=jnp.float32,
                             precision=lax.Precision.HIGHEST)  # (bq, lanes)
    own_lane = (lax.broadcasted_iota(jnp.int32, (block_q, lanes), 1)
                == lax.broadcasted_iota(jnp.int32, (block_q, lanes), 0)
                % lanes)
    return jnp.sum(jnp.where(own_lane, spread, 0.0), axis=1, keepdims=True)


def _flash_bwd_dq_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, do_ref,
                         lse_ref, dq_ref, *, block_q: int, block_k: int,
                         sm_scale: float, causal: bool, has_dlse: bool,
                         dropout_rate: float = 0.0,
                         stat_layout: str = "replicated",
                         local_heads: int = 1, hash_heads: int = 1,
                         hash_seq_len: int = 0):
    qi = pl.program_id(1)
    if dropout_rate > 0.0:
        mix = _dropout_tile_seed(seed_ref, pl.program_id(0),
                                 local_heads, hash_heads)
        q_off = seed_ref[3].astype(jnp.int32)
        k_off = seed_ref[4].astype(jnp.int32)
    q = q_ref[0]                                     # (bq, D) storage dtype
    do = do_ref[0]
    # The row term Drow = rowsum(dO * O) is computed HERE from the o
    # block instead of arriving as a precomputed lane-replicated f32
    # operand: that operand cost an XLA prepass plus ~350 MB/layer/step
    # of HBM traffic at the 124M bench shape, vs a few VPU ops on data
    # the kernel touches anyway. (bq, 1) column vectors are fine
    # in-register; only memory-ref blocks must tile to (8, 128).
    drow = jnp.sum(do.astype(jnp.float32) * o_ref[0].astype(jnp.float32),
                   axis=1, keepdims=True)            # (bq, 1) f32
    if stat_layout == "compact":
        # lse_ref block: (1, S, Tp//LANES, LANES) full dense rows; the
        # expansion matmul selects this q block's slice.
        row0 = qi * (block_q // LANES)
        lse = _expand_stat_tile(lse_ref[0, 0], row0, block_q)
        if has_dlse:
            # Fold the lse cotangent into the row term
            # (ds = p * (dp - (drow - dlse))).
            drow = drow - _expand_stat_tile(lse_ref[0, 1], row0, block_q)
    else:
        if has_dlse:
            # lse_ref carries [lse | dlse] stacked on the minor dim.
            drow = drow - lse_ref[0][:, LANES:LANES + 1]
        lse = lse_ref[0][:, :1]                      # (bq, 1) f32
    seq_len = k_ref.shape[1]
    if causal:
        num_kb = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        num_kb_inner = lax.div(qi * block_q, block_k)  # fully-unmasked
    else:
        num_kb = seq_len // block_k
        num_kb_inner = num_kb
    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 0)

    def body(j, dq_acc, *, masked: bool):
        k = k_ref[0, pl.ds(j * block_k, block_k), :]
        v = v_ref[0, pl.ds(j * block_k, block_k), :]
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                          # (bq, bk) f32
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            # p~ = keep * p / (1-r) is what multiplied v in the forward, so
            # the mask (and its 1/(1-r) rescale) lands on dp; the row term
            # drow = rowsum(do*o) already equals rowsum(dp_masked * p) and
            # needs no correction.
            keep = _dropout_keep(mix, q_off + qi * block_q,
                                 k_off + j * block_k,
                                 (block_q, block_k), hash_seq_len,
                                 dropout_rate)
            dp = _apply_dropout(dp, keep, dropout_rate)
        ds = p * (dp - drow)
        return dq_acc + lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    dq = lax.fori_loop(0, num_kb_inner, functools.partial(body, masked=False),
                       jnp.zeros((block_q, q.shape[1]), jnp.float32))
    dq = lax.fori_loop(num_kb_inner, num_kb,
                       functools.partial(body, masked=True), dq)
    dq_ref[0] = (dq * sm_scale).astype(dq_ref.dtype)


def _flash_bwd_tiles_kernel(seed_ref, q_ref, k_ref, v_ref, o_ref, do_ref,
                            lse_ref, *out_refs, block_q: int, block_k: int,
                            sm_scale: float, causal: bool, has_dlse: bool,
                            with_dq: bool, dropout_rate: float = 0.0,
                            stat_layout: str = "replicated",
                            local_heads: int = 1, hash_heads: int = 1,
                            hash_seq_len: int = 0):
    """The key-parallel backward walk, shared by BOTH backward strategies.

    Grid (batch*head, key blocks); inner loop over the causal q-block
    range, computing per tile: p = exp(s - L), dv += p~^T dO,
    dp = dO V^T, ds = p (dp - Drow), dk += ds^T Q.

    with_dq=False: out_refs = (dk_ref, dv_ref) — the split strategy's
    dKV kernel (a separate q-parallel kernel computes dQ).
    with_dq=True: out_refs = (dq_ref, dk_ref, dv_ref) — the FUSED
    one-pass strategy: the same ds additionally accumulates dq += ds K
    into an f32 output block that stays RESIDENT in VMEM across the
    (sequential, 'arbitrary'-semantics) key grid dimension and flushes
    once per batch*head. The split backward recomputes s/exp/dp twice
    (once per kernel); fused computes each causal tile once and feeds
    all three gradients — r5 measured 124M bench 147 -> 141.6 ms. Cost:
    a (Tp, D) f32 VMEM accumulator (256 KB at the 124M shape); dq is
    scaled by sm_scale and cast OUTSIDE the kernel (XLA fuses both into
    the unpad copy).
    """
    if with_dq:
        dq_ref, dk_ref, dv_ref = out_refs
    else:
        dk_ref, dv_ref = out_refs
    ki = pl.program_id(1)
    if dropout_rate > 0.0:
        mix = _dropout_tile_seed(seed_ref, pl.program_id(0),
                                 local_heads, hash_heads)
        q_off = seed_ref[3].astype(jnp.int32)
        k_off = seed_ref[4].astype(jnp.int32)
    k = k_ref[0]                                      # (bk, D)
    v = v_ref[0]
    seq_len = q_ref.shape[1]
    num_qb = seq_len // block_q
    if causal:
        start_qb = lax.div(ki * block_k, block_q)
        # q blocks at/after this index sit fully above the diagonal for
        # every key in this block — no mask needed (see the fwd kernel's
        # split-loop note; masking is pure VPU cost).
        diag_end = lax.div((ki + 1) * block_k + block_q - 1, block_q)
    else:
        start_qb = 0
        diag_end = 0
    k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32,
                                                (block_q, block_k), 1)

    if with_dq:
        # The dq accumulator is revisited across ki: zero on first visit.
        @pl.when(ki == 0)
        def _zero_dq():
            dq_ref[0] = jnp.zeros_like(dq_ref[0])

    def body(i, carry, *, masked: bool):
        dk_acc, dv_acc = carry
        q = q_ref[0, pl.ds(i * block_q, block_q), :]
        do = do_ref[0, pl.ds(i * block_q, block_q), :]
        # Drow recomputed in-kernel from o (see _flash_bwd_dq_kernel).
        drow = jnp.sum(
            do.astype(jnp.float32)
            * o_ref[0, pl.ds(i * block_q, block_q), :].astype(jnp.float32),
            axis=1, keepdims=True)                    # (bq, 1) f32
        if stat_layout == "compact":
            row0 = i * (block_q // LANES)
            lse = _expand_stat_tile(lse_ref[0, 0], row0, block_q)
            if has_dlse:
                drow = drow - _expand_stat_tile(lse_ref[0, 1], row0, block_q)
        else:
            stats = lse_ref[0, pl.ds(i * block_q, block_q), :]
            if has_dlse:
                drow = drow - stats[:, LANES:LANES + 1]
            lse = stats[:, :1]                        # (bq, 1) f32
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32) * sm_scale
        if masked:
            q_pos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        p = jnp.exp(s - lse)                          # (bq, bk) f32
        if dropout_rate > 0.0:
            # Same positional mask as fwd/dq; dv sums the MASKED p~ = the
            # probabilities that actually multiplied v in the forward.
            keep = _dropout_keep(mix, q_off + i * block_q,
                                 k_off + ki * block_k,
                                 (block_q, block_k), hash_seq_len,
                                 dropout_rate)
            p_v = _apply_dropout(p, keep, dropout_rate)
        else:
            p_v = p
        pb = p_v.astype(do.dtype)
        dv_acc = dv_acc + lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (bk, D)
        dp = lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
        if dropout_rate > 0.0:
            dp = _apply_dropout(dp, keep, dropout_rate)
        ds = (p * (dp - drow)).astype(q.dtype)
        dk_acc = dk_acc + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)       # (bk, D)
        if with_dq:
            dq_blk = dq_ref[0, pl.ds(i * block_q, block_q), :]
            dq_ref[0, pl.ds(i * block_q, block_q), :] = (
                dq_blk + lax.dot_general(
                    ds, k, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32))  # f32 accum
        return dk_acc, dv_acc

    D = k.shape[1]
    init = (jnp.zeros((block_k, D), jnp.float32),
            jnp.zeros((block_k, D), jnp.float32))
    if causal:
        carry = lax.fori_loop(start_qb, diag_end,
                              functools.partial(body, masked=True), init)
        dk, dv = lax.fori_loop(diag_end, num_qb,
                               functools.partial(body, masked=False), carry)
    else:
        dk, dv = lax.fori_loop(0, num_qb,
                               functools.partial(body, masked=False), init)
    dk_ref[0] = (dk * sm_scale).astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


# Backward strategy: 'fused' (one pass, dq resident — the r5 default) or
# 'split' (q-parallel dQ kernel + key-parallel dKV walk). Both strategies
# share _flash_bwd_tiles_kernel for the dk/dv math, so they cannot drift
# there; tests/test_attention.py pins fused-vs-split gradient parity so
# the split path stays exercised. NOT an automatic fallback: the compile
# probe degrades auto -> XLA attention, never fused -> split.
BWD_IMPL = "fused"


def _pallas_flash_bwd(q, k, v, o, lse, do, *, causal: bool, sm_scale: float,
                      block_q: int = DEFAULT_BLOCK,
                      block_k: int = DEFAULT_BLOCK,
                      interpret: bool = False, dlse=None,
                      dropout_rate: float = 0.0, seed=None,
                      stat_layout: str = "replicated",
                      hash_heads: int | None = None,
                      hash_seq_len: int | None = None):
    """lse arrives compact and T-padded from the forward: (B*H, Tp, 1) f32.

    stat_layout picks the HBM operand the kernels read it through:
    'replicated' broadcasts both row stats across the 128-lane minor dim
    (transiently, here); 'compact' reshapes the dense vector to
    (Tp//LANES, LANES) rows and the kernels expand tiles in-register
    (_expand_stat_tile) — ~128x less stat traffic.

    dlse (optional, (B, H, T) f32): cotangent of the logsumexp output for
    callers of flash_attention_lse. Since d lse / d s = p, the extra term
    folds into the existing row stat: ds = p * (dp - (drow - dlse)).
    dV has no lse dependence (dv = p^T do only)."""
    if stat_layout not in ("replicated", "compact"):
        raise ValueError(f"unknown attention stat_layout: {stat_layout!r} "
                         "(expected 'replicated' or 'compact')")
    block_q, block_k = _clamp_blocks(q.shape[2], block_q, block_k)
    # The dKV kernel gets its own (wider) key blocking — see DKV_BLOCK_K.
    dkv_block_k = _clamp_blocks(q.shape[2], block_q,
                                max(block_k, DKV_BLOCK_K))[1]
    qf, kf, vf, (B, H, T, D, Tp, Dp, pad_T, pad_D) = _pad_qkv(
        q, k, v, block_q, max(block_k, dkv_block_k), causal)
    dof = _pad_qkv(do, do, do, block_q, block_k, causal)[0]
    of = _pad_qkv(o, o, o, block_q, block_k, causal)[0]
    # Drow is NOT built here — both kernels recompute it in-register from
    # (do, o), which they read anyway. When the caller supplies a dlse
    # cotangent (flash_attention_lse), it rides along in the same stats
    # operand so the kernels keep a single stats ref.
    has_dlse = dlse is not None
    dlsef = None
    if has_dlse:
        d = dlse.astype(jnp.float32)
        if pad_T:
            d = jnp.pad(d, [(0, 0), (0, 0), (0, pad_T)])
        dlsef = d.reshape(B * H, Tp, 1)
    if stat_layout == "compact":
        # (B*H, S, Tp//LANES, LANES): dense rows, S in {1, 2} stacks
        # [lse, dlse?] on a dedicated dim so one contiguous block serves
        # each q-block's slice of both stats.
        parts = [lse[..., 0].reshape(B * H, Tp // LANES, LANES)]
        if has_dlse:
            parts.append(dlsef[..., 0].reshape(B * H, Tp // LANES, LANES))
        statsf = jnp.stack(parts, axis=1)
        S = len(parts)
        # Both kernels take the FULL (tiny: Tp*4 bytes/bh) stats block —
        # Mosaic requires the last two block dims be 8/128-divisible OR
        # equal to the array dims, and block_q//LANES rows is neither.
        full_stats = pl.BlockSpec((1, S, Tp // LANES, LANES),
                                  lambda b, i: (b, 0, 0, 0))
        dq_stats_spec = dkv_stats_spec = full_stats
    else:
        statsf = jnp.broadcast_to(lse, (B * H, Tp, LANES))
        if has_dlse:
            statsf = jnp.concatenate(
                [statsf, jnp.broadcast_to(dlsef, (B * H, Tp, LANES))],
                axis=-1)
        W = statsf.shape[-1]  # LANES or 2*LANES
        dq_stats_spec = pl.BlockSpec((1, block_q, W), lambda b, i: (b, i, 0))
        dkv_stats_spec = pl.BlockSpec((1, Tp, W), lambda b, j: (b, 0, 0))

    hash_heads = hash_heads if hash_heads is not None else H
    hash_seq_len = hash_seq_len if hash_seq_len is not None else Tp
    _check_dropout_seq_len(dropout_rate, hash_seq_len)
    seed_arg = _dropout_seed_arg(seed, dropout_rate)

    unpad = lambda g: g.reshape(B, H, Tp, Dp)[:, :, :T, :D]
    if BWD_IMPL == "fused":
        # One pass over the causal tiles computing all three grads; dq is
        # an f32 accumulator block resident across the (sequential) key
        # grid dimension, scaled+cast outside (XLA fuses both into the
        # unpad copy). dkv_stats_spec already serves the per-q-block
        # stats reads this kernel does.
        grid_f = (B * H, Tp // block_k)
        dq, dk, dv = pl.pallas_call(
            functools.partial(_flash_bwd_tiles_kernel, block_q=block_q,
                              block_k=block_k, sm_scale=sm_scale,
                              causal=causal, has_dlse=has_dlse,
                              with_dq=True,
                              dropout_rate=dropout_rate,
                              stat_layout=stat_layout, local_heads=H,
                              hash_heads=hash_heads,
                              hash_seq_len=hash_seq_len),
            grid=grid_f,
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),
                pl.BlockSpec((1, Tp, Dp), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, block_k, Dp), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, Dp), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, Tp, Dp), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, Tp, Dp), lambda b, j: (b, 0, 0)),
                dkv_stats_spec,
            ],
            out_specs=[
                pl.BlockSpec((1, Tp, Dp), lambda b, j: (b, 0, 0)),
                pl.BlockSpec((1, block_k, Dp), lambda b, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, Dp), lambda b, j: (b, j, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((B * H, Tp, Dp), jnp.float32),
                jax.ShapeDtypeStruct((B * H, Tp, Dp), k.dtype),
                jax.ShapeDtypeStruct((B * H, Tp, Dp), v.dtype),
            ],
            # The key grid dim is 'arbitrary' (sequential): the resident
            # dq block's read-modify-write across ki requires it.
            compiler_params=None if interpret else _tpu_params(
                "parallel", "arbitrary"),
            interpret=interpret,
        )(seed_arg, qf, kf, vf, of, dof, statsf)
        return (unpad(dq * sm_scale).astype(q.dtype),
                unpad(dk).astype(k.dtype), unpad(dv).astype(v.dtype))

    grid_q = (B * H, Tp // block_q)
    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_q=block_q,
                          block_k=block_k, sm_scale=sm_scale, causal=causal,
                          has_dlse=has_dlse, dropout_rate=dropout_rate,
                          stat_layout=stat_layout, local_heads=H,
                          hash_heads=hash_heads, hash_seq_len=hash_seq_len),
        grid=grid_q,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tp, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tp, Dp), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
            dq_stats_spec,
        ],
        out_specs=pl.BlockSpec((1, block_q, Dp), lambda b, i: (b, i, 0)),
        # Grads leave the kernel already in the input dtype: the f32
        # accumulators are rounded on the register->VMEM write, which
        # halves the grad HBM writes AND deletes the XLA cast pass that a
        # f32 out_shape forced afterwards (r5 microbench: the three
        # (B*H, Tp, 128-padded) f32 grad tensors cost ~1 ms/layer in
        # write+cast traffic at the 124M bench shape).
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, Dp), q.dtype),
        compiler_params=None if interpret else _tpu_params(
            "parallel", "parallel"),
        interpret=interpret,
    )(seed_arg, qf, kf, vf, of, dof, statsf)

    grid_k = (B * H, Tp // dkv_block_k)
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_tiles_kernel, block_q=block_q,
                          block_k=dkv_block_k, sm_scale=sm_scale,
                          causal=causal, has_dlse=has_dlse,
                          with_dq=False,
                          dropout_rate=dropout_rate,
                          stat_layout=stat_layout, local_heads=H,
                          hash_heads=hash_heads, hash_seq_len=hash_seq_len),
        grid=grid_k,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, Tp, Dp), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, dkv_block_k, Dp), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, dkv_block_k, Dp), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, Tp, Dp), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, Tp, Dp), lambda b, j: (b, 0, 0)),
            dkv_stats_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, dkv_block_k, Dp), lambda b, j: (b, j, 0)),
            pl.BlockSpec((1, dkv_block_k, Dp), lambda b, j: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B * H, Tp, Dp), k.dtype),
            jax.ShapeDtypeStruct((B * H, Tp, Dp), v.dtype),
        ],
        compiler_params=None if interpret else _tpu_params(
            "parallel", "parallel"),
        interpret=interpret,
    )(seed_arg, qf, kf, vf, of, dof, statsf)

    return (unpad(dq).astype(q.dtype), unpad(dk).astype(k.dtype),
            unpad(dv).astype(v.dtype))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = True, sm_scale: float | None = None,
                    interpret: bool = False, stat_layout: str = "replicated"):
    """Flash attention: Pallas forward AND backward (both causal-aware).

    stat_layout ('replicated' | 'compact') picks the backward's softmax-
    stat operand layout; forward math is identical either way."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    out, _ = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                               interpret=interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, sm_scale, interpret,
                    stat_layout="replicated"):
    from jax.ad_checkpoint import checkpoint_name

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    o, lse = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                               interpret=interpret)
    # Store the residual COMPACT (B*H, Tp, 1): the lane-replicated
    # (..., 128) form would be the largest per-layer activation held
    # across the whole backward (128x a (B, H, T) vector); the backward
    # re-broadcasts it transiently right before its pallas_call.
    #
    # checkpoint_name tags make these residuals SAVEABLE under
    # remat_policy='save_attention' (models/gpt.py): a jax.checkpoint
    # region discards custom_vjp residuals by default, which would
    # re-run this whole forward kernel during the backward — tagging
    # o and lse (q/k/v recompute from the block input via one cheap
    # dense matmul) is what actually elides the O(T^2) recompute.
    o = checkpoint_name(o, "attn_out")
    return o, (q, k, v, o, checkpoint_name(lse[..., :1], "attn_lse"))


def _flash_bwd_rule(causal, sm_scale, interpret, stat_layout, res, do):
    q, k, v, o, lse = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _pallas_flash_bwd(q, k, v, o, lse, do, causal=causal,
                             sm_scale=sm_scale, interpret=interpret,
                             stat_layout=stat_layout)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention_dropout(q, k, v, seed, causal: bool = True,
                            sm_scale: float | None = None,
                            dropout_rate: float = 0.0,
                            interpret: bool = False,
                            stat_layout: str = "replicated"):
    """Flash attention with attention-probability dropout IN the kernels.

    Semantically o = dropout(softmax(s)) @ v — identical regularization to
    xla_attention's dropout path (nanoGPT's attn_dropout, the reference's
    exercised ``--dropout`` key, ipynb:74-77) but at flash-kernel speed:
    round 3's convergence runs fell to the ~10%-MFU XLA fallback solely
    because dropout wasn't expressible here (r3 VERDICT weak #1).

    seed: (1,) uint32 array. The keep-mask is a counter-based hash of the
    global element position keyed by (seed, batch*head), so the forward
    and both backward kernels reconstruct the same mask without ever
    materializing it; the same (seed, shapes) pair always yields the same
    mask, making the op a pure function of its inputs (remat-safe).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    out, _ = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                               interpret=interpret,
                               dropout_rate=dropout_rate, seed=seed)
    return out


def _flash_dropout_fwd_rule(q, k, v, seed, causal, sm_scale, dropout_rate,
                            interpret, stat_layout="replicated"):
    from jax.ad_checkpoint import checkpoint_name

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    o, lse = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                               interpret=interpret,
                               dropout_rate=dropout_rate, seed=seed)
    o = checkpoint_name(o, "attn_out")  # see _flash_fwd_rule
    return o, (q, k, v, o, checkpoint_name(lse[..., :1], "attn_lse"), seed)


def _flash_dropout_bwd_rule(causal, sm_scale, dropout_rate, interpret,
                            stat_layout, res, do):
    q, k, v, o, lse, seed = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    dq, dk, dv = _pallas_flash_bwd(q, k, v, o, lse, do, causal=causal,
                                   sm_scale=sm_scale, interpret=interpret,
                                   dropout_rate=dropout_rate, seed=seed,
                                   stat_layout=stat_layout)
    return dq, dk, dv, None


flash_attention_dropout.defvjp(_flash_dropout_fwd_rule,
                               _flash_dropout_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse(q, k, v, causal: bool = True,
                        sm_scale: float | None = None,
                        interpret: bool = False,
                        stat_layout: str = "replicated"):
    """Flash attention that ALSO returns the per-row logsumexp.

    Returns (out (B, H, T, D), lse (B, H, T) f32) where
    lse = log sum_k exp(s_k * sm_scale). This is the block primitive ring
    attention composes: per-chunk (out_j, lse_j) pairs merge exactly via
    out = sum_j exp(lse_j - logsumexp_j lse_j) * out_j, so the ring can
    run the real Mosaic kernel per block instead of materializing
    (Tc, Tc) score tensors (round-2 VERDICT weak #1). Differentiable in
    both outputs: the lse cotangent folds into the backward's row stat
    (see _pallas_flash_bwd).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    out, lse = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                                 interpret=interpret)
    return out, _compact_lse(lse, q.shape)


def _compact_lse(lse, qshape):
    """(B*H, Tp, LANES) lane-replicated -> (B, H, T) compact."""
    B, H, T, _ = qshape
    return lse[:, :T, 0].reshape(B, H, T)


def _flash_lse_fwd_rule(q, k, v, causal, sm_scale, interpret,
                        stat_layout="replicated"):
    from jax.ad_checkpoint import checkpoint_name

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    o, lse = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                               interpret=interpret)
    o = checkpoint_name(o, "attn_out")  # see _flash_fwd_rule
    return ((o, _compact_lse(lse, q.shape)),
            (q, k, v, o, checkpoint_name(lse[..., :1], "attn_lse")))


def _flash_lse_bwd_rule(causal, sm_scale, interpret, stat_layout, res, cts):
    q, k, v, o, lse = res
    do, dlse = cts
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _pallas_flash_bwd(q, k, v, o, lse, do, causal=causal,
                             sm_scale=sm_scale, interpret=interpret,
                             dlse=dlse, stat_layout=stat_layout)


flash_attention_lse.defvjp(_flash_lse_fwd_rule, _flash_lse_bwd_rule)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def flash_attention_lse_dropout(q, k, v, seed, causal: bool = True,
                                sm_scale: float | None = None,
                                dropout_rate: float = 0.0,
                                interpret: bool = False,
                                stat_layout: str = "replicated",
                                hash_heads: int | None = None,
                                hash_seq_len: int | None = None):
    """flash_attention_lse + in-kernel dropout keyed on GLOBAL coordinates
    — the block primitive regularized ring attention composes.

    seed: (SEED_WORDS,) uint32 [seed, b_off, h_off, q_off, k_off] (or a
    (1,) seed for the degenerate unsharded case). hash_heads /
    hash_seq_len are the GLOBAL head count and sequence length the mask
    hash is keyed over, so every ring step (and the dq/dkv backward
    kernels recomputing P) reconstructs the same mask for the same global
    score element regardless of which shard computes it.

    The returned lse is the logsumexp of the UNMASKED scores (dropout
    applies to normalized probabilities; the normalizer is mask-free), so
    ring merging of (out_j, lse_j) pairs over dropout blocks is exact:
    the masked probabilities are rescaled by the same global normalizer
    the unmasked merge computes.
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    out, lse = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                                 interpret=interpret,
                                 dropout_rate=dropout_rate, seed=seed,
                                 hash_heads=hash_heads,
                                 hash_seq_len=hash_seq_len)
    return out, _compact_lse(lse, q.shape)


def _flash_lse_dropout_fwd_rule(q, k, v, seed, causal, sm_scale,
                                dropout_rate, interpret,
                                stat_layout="replicated",
                                hash_heads=None, hash_seq_len=None):
    from jax.ad_checkpoint import checkpoint_name

    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    o, lse = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                               interpret=interpret,
                               dropout_rate=dropout_rate, seed=seed,
                               hash_heads=hash_heads,
                               hash_seq_len=hash_seq_len)
    o = checkpoint_name(o, "attn_out")  # see _flash_fwd_rule
    return ((o, _compact_lse(lse, q.shape)),
            (q, k, v, o, checkpoint_name(lse[..., :1], "attn_lse"), seed))


def _flash_lse_dropout_bwd_rule(causal, sm_scale, dropout_rate, interpret,
                                stat_layout, hash_heads, hash_seq_len,
                                res, cts):
    q, k, v, o, lse, seed = res
    do, dlse = cts
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    dq, dk, dv = _pallas_flash_bwd(q, k, v, o, lse, do, causal=causal,
                                   sm_scale=sm_scale, interpret=interpret,
                                   dlse=dlse, dropout_rate=dropout_rate,
                                   seed=seed, stat_layout=stat_layout,
                                   hash_heads=hash_heads,
                                   hash_seq_len=hash_seq_len)
    return dq, dk, dv, None


flash_attention_lse_dropout.defvjp(_flash_lse_dropout_fwd_rule,
                                   _flash_lse_dropout_bwd_rule)


def hash_dropout_keep_mask(seed, B: int, H: int, Tq: int, Tk: int, *,
                           q_off=0, k_off=0, b_off=0, h_off=0,
                           hash_heads: int | None = None,
                           hash_seq_len: int | None = None,
                           rate: float = 0.1) -> jax.Array:
    """The EXACT (B, H, Tq, Tk) keep-mask the Pallas kernels derive, as
    plain jnp ops — shared by the XLA ring block (so pallas and xla ring
    impls drop identical elements for the same seed) and by tests
    verifying the in-kernel mask against a dense reference."""
    seed = _dropout_seed_arg(seed, rate)
    hash_heads = hash_heads if hash_heads is not None else H
    if hash_seq_len is None:
        # Match the kernels' default: they hash over the BLOCK-PADDED
        # length, which (clamped blocks always divide the 128-padded T)
        # is T rounded up to a multiple of 128 — not the raw Tq.
        hash_seq_len = -(-Tq // LANES) * LANES
    bh = jnp.arange(B * H, dtype=jnp.uint32)
    b = bh // jnp.uint32(H) + seed[1] + jnp.uint32(b_off)
    h = bh % jnp.uint32(H) + seed[2] + jnp.uint32(h_off)
    mix = _fmix32(seed[0] ^ ((b * jnp.uint32(hash_heads) + h)
                             * jnp.uint32(_GOLDEN)))        # (B*H,)
    q_pos = (seed[3].astype(jnp.int32) + q_off
             + jnp.arange(Tq))[:, None]
    k_pos = (seed[4].astype(jnp.int32) + k_off
             + jnp.arange(Tk))[None, :]
    idx = (q_pos.astype(jnp.uint32) * jnp.uint32(hash_seq_len)
           + k_pos.astype(jnp.uint32))                       # (Tq, Tk)
    threshold = jnp.uint32(min(int(round(rate * 2**32)), 2**32 - 1))
    keep = _fmix32(idx[None] ^ mix[:, None, None]) >= threshold
    return keep.reshape(B, H, Tq, Tk)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def _jax_tpu_flash(q, k, v, sm_scale):
    """The jax-shipped Mosaic flash kernel (impl='pallas_jax'). Kept as an
    opt-in alternative: isolated fwd+bwd microbenchmarks on v5e slightly
    favor it, but in the full GPT-2 train step it measures ~15% SLOWER than
    this file's kernel (664 vs 563 ms/step at batch 32) and OOMs at batch
    64 — its backward saves more residuals. Returns None when unavailable
    so callers fall back to the custom kernel. Sequence lengths that are
    not 128-aligned (e.g. the Trainer's tiny init dummy batch) are zero
    padded here; causal masking keeps real queries from seeing the pad."""
    try:
        from jax.experimental.pallas.ops.tpu.flash_attention import (
            flash_attention as jflash)
    except ImportError:
        return None
    T = q.shape[2]
    pad_T = (-T) % 128
    if pad_T:
        pads = [(0, 0), (0, 0), (0, pad_T), (0, 0)]
        q, k, v = (jnp.pad(x, pads) for x in (q, k, v))
    out = jflash(q, k, v, causal=True, sm_scale=sm_scale)
    return out[:, :, :T, :] if pad_T else out


_PALLAS_PROBE: dict[str, bool] = {}


def pallas_compile_probe() -> bool:
    """True iff the custom Pallas kernel (fwd AND bwd) compiles on the
    current default backend. Compiled once per process per backend; the
    result gates 'auto' dispatch so one kernel regression can never take
    down default-config runs (it degrades to the XLA path with a warning).

    Compile-only (AOT lower+compile on tiny shapes), so the probe is cheap
    and safe to call while tracing an outer jit.

    Multi-host note: with process_count > 1 the probe runs a cross-process
    broadcast so all hosts agree on one verdict — every process that built
    the distributed runtime MUST reach its first attention call, or the
    barrier deadlocks. A single-process diagnostic tool running inside an
    initialized multi-process runtime (e.g. a rank-0-only script) should
    set NANOSANDBOX_ATTENTION_PROBE=local to skip the collective (or pin
    --attention_impl explicitly, which never probes).
    """
    backend = jax.default_backend()
    if backend in _PALLAS_PROBE:
        return _PALLAS_PROBE[backend]
    if backend != "tpu":
        # Compiled Mosaic kernels only exist on TPU; interpret mode is a
        # separate explicit impl.
        _PALLAS_PROBE[backend] = False
        return False
    import os

    if os.environ.get("NANOSANDBOX_ATTENTION_PROBE") == "local":
        _PALLAS_PROBE[backend] = _probe_locally()
        return _PALLAS_PROBE[backend]
    if jax.process_count() > 1:
        # Multi-host SPMD: a per-host probe could diverge (e.g. one host
        # fails compile transiently) and hosts would then lower DIFFERENT
        # programs — a silent hang at the first collective. All hosts
        # follow process 0's verdict; if a host then genuinely cannot
        # compile the kernel it fails loudly, which beats divergence.
        from jax.experimental import multihost_utils

        local = _probe_locally()
        verdict = bool(multihost_utils.broadcast_one_to_all(
            jnp.asarray(local)))
        if verdict and not local:
            raise RuntimeError(
                "Pallas flash kernel compiled on process 0 but not on "
                f"process {jax.process_index()} — refusing to diverge")
        _PALLAS_PROBE[backend] = verdict
        return verdict
    _PALLAS_PROBE[backend] = _probe_locally()
    return _PALLAS_PROBE[backend]


def _probe_locally() -> bool:
    try:
        # T=1024 so _clamp_blocks selects the production DEFAULT_BLOCK
        # config — probing a smaller shape would compile 128-row blocks
        # and miss regressions specific to the block size real training
        # runs (e.g. VMEM pressure of the 512x512 score tile).
        x = jax.ShapeDtypeStruct((1, 1, 1024, 64), jnp.bfloat16)

        def fwd(q, k, v):
            return flash_attention(q, k, v, True, None, False)

        def make_loss(layout):
            def loss(q, k, v):
                return flash_attention(
                    q, k, v, True, None, False, layout
                ).astype(jnp.float32).sum()
            return loss

        def make_loss_dropout(layout):
            def loss_dropout(q, k, v, seed):
                return flash_attention_dropout(
                    q, k, v, seed, True, None, 0.1, False, layout
                ).astype(jnp.float32).sum()
            return loss_dropout

        s = jax.ShapeDtypeStruct((1,), jnp.uint32)
        jax.jit(fwd).lower(x, x, x).compile()
        # BOTH stat layouts are part of the verdict: the config default is
        # 'compact', and 'auto' must not promise a fallback it only
        # checked for 'replicated' (round-4 ADVICE #2 — a Mosaic
        # regression in the compact expansion path would otherwise crash
        # the first backward instead of degrading to XLA). The dropout
        # variant is part of the same verdict too, in both layouts:
        # 'auto' promises that regularized (dropout>0) configs run the
        # flash path under whichever layout the config selects.
        for layout in ("replicated", "compact"):
            jax.jit(jax.grad(make_loss(layout),
                             argnums=(0, 1, 2))).lower(x, x, x).compile()
            jax.jit(jax.grad(make_loss_dropout(layout),
                             argnums=(0, 1, 2))).lower(x, x, x, s).compile()
        return True
    except Exception as e:  # Mosaic lowering / compile failure
        warnings.warn(
            "Pallas flash attention failed to compile on this TPU; "
            f"falling back to XLA attention. Error: {e}")
        return False


def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     impl: str = "auto", sm_scale: float | None = None,
                     dropout_rate: float = 0.0,
                     dropout_rng: jax.Array | None = None,
                     stat_layout: str = "replicated") -> jax.Array:
    """Causal attention over (B, H, T, D) tensors.

    impl: 'auto' (Pallas on TPU when it compiles, XLA otherwise — a probe
    compiles the kernel once per process so a kernel regression degrades
    to XLA instead of crashing), 'pallas', 'pallas_interpret' (for CPU
    tests), 'pallas_jax' (jax's library kernel), or 'xla'.

    stat_layout ('replicated' | 'compact'): the flash backward's softmax-
    stat operand layout (--attention_stat_layout); ignored by the
    xla/pallas_jax paths.

    Attention-probability dropout runs INSIDE the flash kernels
    (flash_attention_dropout) for the pallas impls; 'pallas_jax' has no
    dropout hook and falls back to the XLA path when dropout is active.
    The pallas and XLA paths draw different (equally valid) masks from the
    same rng — identical regularization statistics, different bits.
    """
    if impl == "auto":
        impl = "pallas" if pallas_compile_probe() else "xla"
    if dropout_rate > 0.0 and dropout_rng is not None:
        if impl in ("pallas", "pallas_interpret"):
            seed = jax.random.bits(dropout_rng, (1,), jnp.uint32)
            return flash_attention_dropout(q, k, v, seed, True, sm_scale,
                                           float(dropout_rate),
                                           impl == "pallas_interpret",
                                           stat_layout)
        return xla_attention(q, k, v, causal=True, sm_scale=sm_scale,
                             dropout_rate=dropout_rate,
                             dropout_rng=dropout_rng)
    if impl == "xla":
        return xla_attention(q, k, v, causal=True, sm_scale=sm_scale)
    if impl == "pallas":
        return flash_attention(q, k, v, True, sm_scale, False, stat_layout)
    if impl == "pallas_jax":
        out = _jax_tpu_flash(q, k, v, sm_scale if sm_scale is not None
                             else q.shape[-1] ** -0.5)
        if out is None:
            raise ValueError("jax library flash kernel unavailable "
                             "(requires a TPU backend)")
        return out
    if impl == "pallas_interpret":
        return flash_attention(q, k, v, True, sm_scale, True, stat_layout)
    raise ValueError(f"unknown attention impl: {impl!r}")
