"""Causal self-attention: Pallas flash-attention forward for TPU + XLA fallback.

The reference's training core (karpathy/nanoGPT, exercised via
/root/reference/notebooks/colab_nanoGPT_companion.ipynb:71-78) relies on
torch scaled_dot_product_attention/CUDA flash kernels. The TPU-native
equivalent is a Pallas kernel compiled by Mosaic: the forward pass is an
online-softmax (flash) kernel that never materializes the (T, T) score
matrix in HBM, tiled to the MXU (128-lane blocks, f32 accumulation).

The backward pass recomputes attention with the XLA implementation under
jax.custom_vjp — at the reference's context lengths (block_size <= 1024,
ipynb:74) the recompute is cheap and XLA fuses it well; a dedicated Pallas
backward is a later optimization.

Layouts: q, k, v are (B, H, T, D). D (head_dim) is padded to a multiple of
128 lanes inside the Pallas path when needed.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

NEG_INF = -1e30

__all__ = ["causal_attention", "xla_attention", "flash_attention"]


# ---------------------------------------------------------------------------
# XLA reference implementation (also the backward recompute path)
# ---------------------------------------------------------------------------

def xla_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                  *, causal: bool = True, sm_scale: float | None = None,
                  dropout_rate: float = 0.0,
                  dropout_rng: jax.Array | None = None) -> jax.Array:
    """Plain attention; XLA fuses this adequately for short-T and CPU tests.

    dropout_rate/dropout_rng apply inverted dropout to the softmax weights
    (nanoGPT's attn_dropout; the reference model regularizes attention
    probabilities as well as residuals).
    """
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    q32 = q.astype(jnp.float32) * sm_scale
    s = jnp.einsum("bhqd,bhkd->bhqk", q32, k.astype(jnp.float32))
    if causal:
        T = q.shape[2]
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None, :, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0 and dropout_rng is not None:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    return o.astype(q.dtype)


# ---------------------------------------------------------------------------
# Pallas flash forward
# ---------------------------------------------------------------------------

def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, block_q: int,
                      block_k: int, sm_scale: float, causal: bool):
    qi = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32) * sm_scale            # (block_q, D)
    seq_len = k_ref.shape[1]
    head_dim = q_ref.shape[2]

    if causal:
        # Only iterate k blocks at or before this q block's frontier.
        num_kb = lax.div((qi + 1) * block_q + block_k - 1, block_k)
    else:
        num_kb = seq_len // block_k

    q_pos = qi * block_q + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)

    def body(j, carry):
        acc, m, l = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                            preferred_element_type=jnp.float32)  # (bq, bk)
        if causal:
            k_pos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))  # (bq, 1)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32)
        return acc_new, m_new, l_new

    init = (
        jnp.zeros((block_q, head_dim), jnp.float32),
        jnp.full((block_q, 1), NEG_INF, jnp.float32),
        jnp.zeros((block_q, 1), jnp.float32),
    )
    acc, m, l = lax.fori_loop(0, num_kb, body, init)
    o_ref[0] = (acc / l).astype(o_ref.dtype)


def _pallas_flash_fwd(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      causal: bool, sm_scale: float,
                      block_q: int = 128, block_k: int = 128,
                      interpret: bool = False) -> jax.Array:
    B, H, T, D = q.shape
    orig_D = D
    # Pad head_dim to the 128-lane tile and T to the q/k block size.
    pad_D = (-D) % 128
    if pad_D:
        pads = [(0, 0), (0, 0), (0, 0), (0, pad_D)]
        q, k, v = (jnp.pad(x, pads) for x in (q, k, v))
        D += pad_D
    block_q = min(block_q, max(T, 8))
    block_k = min(block_k, max(T, 8))
    pad_T = (-T) % max(block_q, block_k)
    if pad_T:
        # Padded key rows would attract softmax mass for padded query rows
        # only; padded queries are sliced off below, and causal masking keeps
        # real queries from seeing padded (future) keys.
        pads = [(0, 0), (0, 0), (0, pad_T), (0, 0)]
        q, k, v = (jnp.pad(x, pads) for x in (q, k, v))
        if not causal:
            raise ValueError("non-causal pallas path requires T % block == 0")
    Tp = q.shape[2]

    qf = q.reshape(B * H, Tp, D)
    kf = k.reshape(B * H, Tp, D)
    vf = v.reshape(B * H, Tp, D)

    grid = (B * H, Tp // block_q)
    kernel = functools.partial(
        _flash_fwd_kernel, block_q=block_q, block_k=block_k,
        sm_scale=sm_scale, causal=causal)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
            pl.BlockSpec((1, Tp, D), lambda b, i: (b, 0, 0)),
            pl.BlockSpec((1, Tp, D), lambda b, i: (b, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, D), lambda b, i: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B * H, Tp, D), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    out = out.reshape(B, H, Tp, D)
    if pad_T:
        out = out[:, :, :T, :]
    if pad_D:
        out = out[..., :orig_D]
    return out


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def flash_attention(q, k, v, causal: bool = True, sm_scale: float | None = None,
                    interpret: bool = False):
    """Flash forward (Pallas) with XLA-recompute backward."""
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    return _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                             interpret=interpret)


def _flash_fwd_rule(q, k, v, causal, sm_scale, interpret):
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    o = _pallas_flash_fwd(q, k, v, causal=causal, sm_scale=sm_scale,
                          interpret=interpret)
    return o, (q, k, v)


def _flash_bwd_rule(causal, sm_scale, interpret, res, do):
    q, k, v = res
    if sm_scale is None:
        sm_scale = q.shape[-1] ** -0.5
    _, vjp = jax.vjp(
        lambda q_, k_, v_: xla_attention(q_, k_, v_, causal=causal,
                                         sm_scale=sm_scale), q, k, v)
    return vjp(do)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)


# ---------------------------------------------------------------------------
# Dispatch
# ---------------------------------------------------------------------------

def causal_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                     impl: str = "auto", sm_scale: float | None = None,
                     dropout_rate: float = 0.0,
                     dropout_rng: jax.Array | None = None) -> jax.Array:
    """Causal attention over (B, H, T, D) tensors.

    impl: 'auto' (Pallas on TPU, XLA elsewhere), 'pallas', 'pallas_interpret'
    (for CPU tests), or 'xla'. Attention-probability dropout is only
    expressible in the XLA path; when active it overrides the impl choice
    (flash stays the inference/no-dropout fast path).
    """
    if dropout_rate > 0.0 and dropout_rng is not None:
        return xla_attention(q, k, v, causal=True, sm_scale=sm_scale,
                             dropout_rate=dropout_rate,
                             dropout_rng=dropout_rng)
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl == "xla":
        return xla_attention(q, k, v, causal=True, sm_scale=sm_scale)
    if impl == "pallas":
        return flash_attention(q, k, v, True, sm_scale, False)
    if impl == "pallas_interpret":
        return flash_attention(q, k, v, True, sm_scale, True)
    raise ValueError(f"unknown attention impl: {impl!r}")
