"""Tokenizers: char-level (meta.pkl contract), byte-level, GPT-2 BPE.

The reference uses nanoGPT's char-level meta.pkl for tiny-shakespeare and
tiktoken for GPT-2-scale datasets (ipynb:37, SURVEY.md §2.3 #31). The byte
tokenizer is the zero-dependency offline fallback so the OpenWebText-style
pipeline works in air-gapped clusters (proxy ConfigMap may not exist).
"""

from __future__ import annotations

import os
from typing import Protocol


class Tokenizer(Protocol):
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class CharTokenizer:
    """Char-level tokenizer; vocabulary = sorted unique chars of the corpus."""

    def __init__(self, stoi: dict[str, int], itos: dict[int, str]):
        self.stoi = stoi
        self.itos = itos
        self.vocab_size = len(stoi)

    @classmethod
    def from_text(cls, text: str) -> "CharTokenizer":
        chars = sorted(set(text))
        stoi = {ch: i for i, ch in enumerate(chars)}
        itos = {i: ch for i, ch in enumerate(chars)}
        return cls(stoi, itos)

    @classmethod
    def from_meta(cls, meta: dict) -> "CharTokenizer":
        return cls(meta["stoi"], meta["itos"])

    def encode(self, text: str) -> list[int]:
        return [self.stoi[c] for c in text]

    def decode(self, ids) -> str:
        return "".join(self.itos[int(i)] for i in ids)

    def meta(self) -> dict:
        return {"vocab_size": self.vocab_size, "stoi": self.stoi,
                "itos": self.itos, "kind": "char"}


class ByteTokenizer:
    """UTF-8 byte tokenizer, vocab 256. Offline stand-in for BPE."""

    vocab_size = 256

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        return bytes(int(i) for i in ids).decode("utf-8", errors="replace")

    def meta(self) -> dict:
        return {"vocab_size": self.vocab_size, "kind": "byte"}


_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))
DEFAULT_BPE_ASSET = "data/fixtures/bpe_english_prose/tokenizer.json"


class LocalBPETokenizer:
    """Byte-level BPE from a COMMITTED vocab asset — the offline GPT-2-regime
    tokenizer (same 50,257-entry shape as tiktoken's gpt2 encoding, which
    the reference depends on at ipynb:37 but which needs network access).
    Trained deterministically on the committed corpus by
    scripts/make_bpe_vocab.py; every host tokenizes identically with no
    download."""

    def __init__(self, asset: str | None = None):
        from tokenizers import Tokenizer as HFTokenizer

        rel = asset or DEFAULT_BPE_ASSET
        path = rel if os.path.isabs(rel) else os.path.join(_REPO_ROOT, rel)
        if not os.path.exists(path):
            raise FileNotFoundError(
                f"BPE vocab asset {path} not found — run `python "
                "scripts/make_bpe_vocab.py` (after building the xl corpus) "
                "or pass the asset path")
        self.asset = rel
        self.tok = HFTokenizer.from_file(path)
        self.vocab_size = self.tok.get_vocab_size()

    def encode(self, text: str) -> list[int]:
        return self.tok.encode(text).ids

    def decode(self, ids) -> str:
        return self.tok.decode([int(i) for i in ids])

    def meta(self) -> dict:
        return {"vocab_size": self.vocab_size, "kind": "bpe",
                "asset": self.asset}


# Drop-in location for the REAL GPT-2 vocabulary on air-gapped hosts:
# save HF's gpt2 tokenizer file here (e.g.
# `GPT2TokenizerFast.from_pretrained("gpt2").save_pretrained(...)` on any
# online machine, or copy tokenizer.json from the HF hub) and
# get_tokenizer('gpt2') works with no network. Validated structurally on
# load (50,257 entries, <|endoftext|> = 50256) so a wrong file cannot
# silently tokenize into the wrong id space.
GPT2_LOCAL_ASSET = "data/fixtures/gpt2/tokenizer.json"


class GPT2Tokenizer:
    """GPT-2 BPE — tiktoken (the reference's tokenizer dep, ipynb:37)
    when it can reach its cache/CDN, else a vendored HF tokenizer.json
    (GPT2_LOCAL_ASSET). Both produce the canonical GPT-2 ids; encode()
    never emits special tokens (tiktoken's encode_ordinary semantics)."""

    def __init__(self):
        self._hf = None
        try:
            import tiktoken
            self.enc = tiktoken.get_encoding("gpt2")
            self.vocab_size = self.enc.n_vocab  # 50257
            return
        except Exception as tiktoken_err:  # offline / no cache
            path = os.path.join(_REPO_ROOT, GPT2_LOCAL_ASSET)
            if not os.path.exists(path):
                raise RuntimeError(
                    "tiktoken gpt2 encoding unavailable (offline?) and no "
                    f"vendored vocabulary at {path}. Either pre-populate "
                    "the tiktoken cache, or save the real HF gpt2 "
                    "tokenizer.json at that path (see GPT2_LOCAL_ASSET "
                    f"docstring). tiktoken error: {tiktoken_err}"
                ) from tiktoken_err
        from tokenizers import Tokenizer as HFTokenizer

        self._hf = HFTokenizer.from_file(path)
        self.vocab_size = self._hf.get_vocab_size()
        eot = self._hf.token_to_id("<|endoftext|>")
        if self.vocab_size != 50257 or eot != 50256:
            raise ValueError(
                f"{path} is not the real GPT-2 vocabulary (vocab "
                f"{self.vocab_size}, <|endoftext|> id {eot}; expected "
                "50257 / 50256) — refusing to tokenize into a mismatched "
                "id space")

    def encode(self, text: str) -> list[int]:
        if self._hf is not None:
            return self._hf.encode(text, add_special_tokens=False).ids
        return self.enc.encode_ordinary(text)

    def decode(self, ids) -> str:
        if self._hf is not None:
            # skip_special_tokens=False to mirror tiktoken: decode(50256)
            # must render '<|endoftext|>' on both backends.
            return self._hf.decode([int(i) for i in ids],
                                   skip_special_tokens=False)
        return self.enc.decode([int(i) for i in ids])

    def meta(self) -> dict:
        return {"vocab_size": self.vocab_size, "kind": "gpt2"}


def get_tokenizer(kind: str, meta: dict | None = None) -> Tokenizer:
    if kind == "char":
        if meta is None:
            raise ValueError("char tokenizer needs meta.pkl contents")
        return CharTokenizer.from_meta(meta)
    if kind == "byte":
        return ByteTokenizer()
    if kind == "bpe":
        return LocalBPETokenizer((meta or {}).get("asset"))
    if kind == "gpt2":
        return GPT2Tokenizer()  # raises with remediation steps when offline
    raise ValueError(f"unknown tokenizer kind: {kind!r}")
