"""Tokenizers: char-level (meta.pkl contract), byte-level, GPT-2 BPE.

The reference uses nanoGPT's char-level meta.pkl for tiny-shakespeare and
tiktoken for GPT-2-scale datasets (ipynb:37, SURVEY.md §2.3 #31). The byte
tokenizer is the zero-dependency offline fallback so the OpenWebText-style
pipeline works in air-gapped clusters (proxy ConfigMap may not exist).
"""

from __future__ import annotations

from typing import Protocol


class Tokenizer(Protocol):
    vocab_size: int

    def encode(self, text: str) -> list[int]: ...
    def decode(self, ids: list[int]) -> str: ...


class CharTokenizer:
    """Char-level tokenizer; vocabulary = sorted unique chars of the corpus."""

    def __init__(self, stoi: dict[str, int], itos: dict[int, str]):
        self.stoi = stoi
        self.itos = itos
        self.vocab_size = len(stoi)

    @classmethod
    def from_text(cls, text: str) -> "CharTokenizer":
        chars = sorted(set(text))
        stoi = {ch: i for i, ch in enumerate(chars)}
        itos = {i: ch for i, ch in enumerate(chars)}
        return cls(stoi, itos)

    @classmethod
    def from_meta(cls, meta: dict) -> "CharTokenizer":
        return cls(meta["stoi"], meta["itos"])

    def encode(self, text: str) -> list[int]:
        return [self.stoi[c] for c in text]

    def decode(self, ids) -> str:
        return "".join(self.itos[int(i)] for i in ids)

    def meta(self) -> dict:
        return {"vocab_size": self.vocab_size, "stoi": self.stoi,
                "itos": self.itos, "kind": "char"}


class ByteTokenizer:
    """UTF-8 byte tokenizer, vocab 256. Offline stand-in for BPE."""

    vocab_size = 256

    def encode(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids) -> str:
        return bytes(int(i) for i in ids).decode("utf-8", errors="replace")

    def meta(self) -> dict:
        return {"vocab_size": self.vocab_size, "kind": "byte"}


class GPT2Tokenizer:
    """GPT-2 BPE via tiktoken (the reference's tokenizer dep, ipynb:37)."""

    def __init__(self):
        import tiktoken
        self.enc = tiktoken.get_encoding("gpt2")
        self.vocab_size = self.enc.n_vocab  # 50257

    def encode(self, text: str) -> list[int]:
        return self.enc.encode_ordinary(text)

    def decode(self, ids) -> str:
        return self.enc.decode([int(i) for i in ids])

    def meta(self) -> dict:
        return {"vocab_size": self.vocab_size, "kind": "gpt2"}


def get_tokenizer(kind: str, meta: dict | None = None) -> Tokenizer:
    if kind == "char":
        if meta is None:
            raise ValueError("char tokenizer needs meta.pkl contents")
        return CharTokenizer.from_meta(meta)
    if kind == "byte":
        return ByteTokenizer()
    if kind == "gpt2":
        try:
            return GPT2Tokenizer()
        except Exception as e:  # offline / no BPE cache
            raise RuntimeError(
                "tiktoken gpt2 encoding unavailable (offline?); use the byte "
                f"tokenizer or pre-populate the tiktoken cache: {e}") from e
    raise ValueError(f"unknown tokenizer kind: {kind!r}")
