"""Data pipeline: prepare scripts -> {train,val}.bin + meta.pkl -> memmap loader.

Contract from the reference (SURVEY.md §2.3 #28, ipynb:50-56): a prepare step
emits uint16 token bins plus a meta.pkl vocab; the loader samples
random-offset (block_size+1)-token windows from the memmap. Datasets live
under <data_dir>/<dataset>/ (k8s: /data/datasets/<name>, gh_sync.ps1:126-127).
"""

from nanosandbox_tpu.data.loader import BinDataset, BatchLoader  # noqa: F401
