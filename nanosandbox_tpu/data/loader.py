"""Memmapped batch loader with per-host sharding and background prefetch.

Reimplements nanoGPT's get_batch contract (random-offset windows from
train.bin/val.bin memmaps; SURVEY.md §2.3 #28) with the two changes the TPU
architecture demands (SURVEY.md §7 hard part (b)):

  * **Per-host sharding** — under multi-host SPMD every process loads only
    its slice of the global batch. Offsets are drawn from a stream keyed by
    (seed, split, step, process_index) so hosts sample disjoint batches
    without communicating (the DDP analogue was implicit per-rank RNG).
  * **Background prefetch** — a worker thread stages the next batch while
    the current step runs on the chip, hiding host-side gather latency.
    The gather itself is native C++ (csrc/batchgen.cpp) when available.
"""

from __future__ import annotations

import os
import pickle
import queue
import threading

import numpy as np

from nanosandbox_tpu.utils import native


class BinDataset:
    """A prepared dataset directory: {train,val}.bin (+ meta.pkl)."""

    def __init__(self, data_dir: str, dataset: str):
        self.dir = os.path.join(data_dir, dataset)
        self.splits: dict[str, np.ndarray] = {}
        for split in ("train", "val"):
            path = os.path.join(self.dir, f"{split}.bin")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"{path} not found — run `python -m nanosandbox_tpu.data.prepare "
                    f"{dataset} --data_dir={data_dir}` first")
            self.splits[split] = np.memmap(path, dtype=np.uint16, mode="r")
        meta_path = os.path.join(self.dir, "meta.pkl")
        self.meta: dict = {}
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as f:
                self.meta = pickle.load(f)

    @property
    def vocab_size(self) -> int:
        # nanoGPT default: GPT-2 vocab padded to a multiple of 64 when no meta.
        return int(self.meta.get("vocab_size", 50304))

    def tokens(self, split: str) -> int:
        return int(self.splits[split].shape[0])

    def sample_batch(self, split: str, step: int, batch_size: int,
                     block_size: int, *, seed: int = 1337,
                     process_index: int = 0) -> tuple[np.ndarray, np.ndarray]:
        """Sample (x, y) int32 arrays of shape (batch_size, block_size)."""
        data = self.splits[split]
        width = block_size + 1
        split_tag = 0 if split == "train" else 1
        # Mix step/host/split into a single stream id for the native sampler.
        stream = (np.uint64(step) * np.uint64(0x10001)
                  + np.uint64(process_index) * np.uint64(2)
                  + np.uint64(split_tag))
        offsets = native.sample_offsets(seed, int(stream), data.shape[0],
                                        width, batch_size)
        windows = native.gather_windows(data, offsets, width)
        xy = windows.astype(np.int32)
        return xy[:, :-1], xy[:, 1:]


class BatchLoader:
    """Iterator over per-host batches with one-batch-ahead prefetch."""

    # Queue sentinel: the worker died on the exception stored in
    # self._worker_exc. An object(), not None, so a legitimate batch can
    # never be mistaken for it.
    _FAILED = object()

    def __init__(self, dataset: BinDataset, split: str, batch_size: int,
                 block_size: int, *, seed: int = 1337, process_index: int = 0,
                 num_processes: int = 1, start_step: int = 0,
                 prefetch: bool = True):
        if batch_size % num_processes:
            raise ValueError(
                f"global batch_size {batch_size} not divisible by "
                f"num_processes {num_processes}")
        self.dataset = dataset
        self.split = split
        self.global_batch_size = batch_size
        self.local_batch_size = batch_size // num_processes
        self.block_size = block_size
        self.seed = seed
        self.process_index = process_index
        self.step = start_step
        self.native = native.get_lib() is not None
        self._queue: queue.Queue | None = None
        self._worker_exc: BaseException | None = None
        if prefetch:
            self._queue = queue.Queue(maxsize=2)
            self._stop = threading.Event()
            self._thread = threading.Thread(target=self._worker, daemon=True)
            self._thread.start()

    def _load(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        return self.dataset.sample_batch(
            self.split, step, self.local_batch_size, self.block_size,
            seed=self.seed, process_index=self.process_index)

    def _put(self, item) -> None:
        """Blocking put that still honors close() (bounded queue: a dead
        consumer must not wedge the worker forever)."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.1)
                return
            except queue.Full:
                continue

    def _worker(self) -> None:
        step = self.step
        try:
            while not self._stop.is_set():
                batch = self._load(step)
                self._put((step, batch))
                step += 1
        except Exception as e:
            # A worker exception (truncated .bin mid-run, mmap I/O error)
            # used to kill the thread silently and leave __next__ blocked
            # forever on an empty queue. Park the exception and push the
            # sentinel through the queue so the consumer re-raises at its
            # next (and every later) __next__.
            self._worker_exc = e
            self._put(self._FAILED)

    def __iter__(self):
        return self

    def __next__(self) -> tuple[np.ndarray, np.ndarray]:
        if self._queue is not None:
            item = self._queue.get()
            if item is self._FAILED:
                # Re-queue the sentinel: the worker is dead (nothing else
                # will ever be enqueued), so every subsequent __next__
                # must also raise instead of blocking forever.
                self._queue.put(item)
                raise RuntimeError(
                    f"BatchLoader prefetch worker failed on split "
                    f"{self.split!r}: {self._worker_exc!r}"
                ) from self._worker_exc
            step, batch = item
            self.step = step + 1
            return batch
        batch = self._load(self.step)
        self.step += 1
        return batch

    def close(self) -> None:
        if self._queue is not None:
            self._stop.set()
            try:
                while True:
                    self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=2)
