"""Dataset preparation: corpus -> train.bin / val.bin / meta.pkl.

Reimplements the contract of nanoGPT's ``data/<dataset>/prepare.py`` as the
reference exercises it (ipynb:50-56; k8s dataset Job, README.md:48-53,
gh_sync.ps1:124-128): download/read a corpus, tokenize, write uint16 memmap
bins with a 90/10 train/val split and a meta.pkl describing the vocab.

Network access goes through the cluster proxy when configured (the proxy
ConfigMap's env is honored automatically by urllib). When the network is
unavailable, a local source file can be supplied, or — for smoke tests — a
deterministic synthetic corpus is generated (the reference's scale-down
testing philosophy, SURVEY.md §4).
"""

from __future__ import annotations

import os
import pickle
import urllib.request

import numpy as np

from nanosandbox_tpu.data.tokenizer import ByteTokenizer, CharTokenizer, get_tokenizer

TINY_SHAKESPEARE_URL = (
    "https://raw.githubusercontent.com/karpathy/char-rnn/master/data/"
    "tinyshakespeare/input.txt"
)


def _warn_synthetic(what: str) -> None:
    import sys

    print(f"WARNING: {what} unavailable — using a SYNTHETIC corpus. "
          "This is only valid for smoke tests; do not train real models "
          "on it. Pass allow_synthetic=False to fail instead.",
          file=sys.stderr)


def _synthetic_corpus(n_chars: int = 200_000, seed: int = 1337) -> str:
    """Deterministic pseudo-text for offline smoke tests (Tier-0, SURVEY §4)."""
    rng = np.random.default_rng(seed)
    words = ["the", "and", "lord", "king", "thou", "hath", "speak", "good",
             "night", "come", "what", "shall", "more", "love", "death",
             "crown", "sword", "blood", "heart", "light"]
    parts: list[str] = []
    total = 0
    while total < n_chars:
        n = int(rng.integers(4, 12))
        sent = " ".join(words[int(i)] for i in rng.integers(0, len(words), n))
        sent = sent.capitalize() + ".\n"
        parts.append(sent)
        total += len(sent)
    return "".join(parts)[:n_chars]


def fetch_corpus(out_path: str, url: str = TINY_SHAKESPEARE_URL,
                 source_file: str | None = None,
                 allow_synthetic: bool = True) -> str:
    """Obtain the raw corpus text: local file > cached copy > download > synthetic."""
    if source_file and os.path.exists(source_file):
        with open(source_file, "r", encoding="utf-8") as f:
            return f.read()
    if os.path.exists(out_path):
        with open(out_path, "r", encoding="utf-8") as f:
            return f.read()
    try:
        with urllib.request.urlopen(url, timeout=30) as r:
            text = r.read().decode("utf-8")
        os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
        with open(out_path, "w", encoding="utf-8") as f:
            f.write(text)
        return text
    except Exception:
        if not allow_synthetic:
            raise
        _warn_synthetic(f"download of {url}")
        return _synthetic_corpus()


def write_bins(ids: np.ndarray, out_dir: str, meta: dict,
               val_fraction: float = 0.1) -> dict:
    os.makedirs(out_dir, exist_ok=True)
    n = len(ids)
    split = int(n * (1 - val_fraction))
    train_ids = ids[:split].astype(np.uint16)
    val_ids = ids[split:].astype(np.uint16)
    train_ids.tofile(os.path.join(out_dir, "train.bin"))
    val_ids.tofile(os.path.join(out_dir, "val.bin"))
    with open(os.path.join(out_dir, "meta.pkl"), "wb") as f:
        pickle.dump(meta, f)
    return {"train_tokens": len(train_ids), "val_tokens": len(val_ids),
            "vocab_size": meta["vocab_size"]}


def prepare_char_dataset(out_dir: str, source_file: str | None = None,
                         url: str = TINY_SHAKESPEARE_URL,
                         allow_synthetic: bool = True) -> dict:
    """tiny-shakespeare char-level prep (ipynb:52-56 contract)."""
    text = fetch_corpus(os.path.join(out_dir, "input.txt"), url=url,
                        source_file=source_file,
                        allow_synthetic=allow_synthetic)
    tok = CharTokenizer.from_text(text)
    ids = np.asarray(tok.encode(text), dtype=np.uint16)
    return write_bins(ids, out_dir, tok.meta())


# Resolved relative to the repo checkout (shared with tokenizer.py), not
# the CWD, so the fixture preps work from any working directory — e.g.
# the k8s dataset Job runs with the PVC as CWD.
from nanosandbox_tpu.data.tokenizer import _REPO_ROOT  # noqa: E402

REAL_FIXTURE = os.path.join(_REPO_ROOT, "data", "fixtures",
                            "english_prose.txt")


def _prepare_fixture_dataset(out_dir: str, fixture: str, build_hint: str,
                             make_tokenizer, source_file: str | None) -> dict:
    """Shared prep for the committed real-text fixtures: resolve the
    source (explicit file > fixture), fail loudly with the build command
    when absent (no synthetic fallback — real data or a loud failure),
    tokenize, write bins."""
    src = source_file or fixture
    if not os.path.exists(src):
        raise FileNotFoundError(
            f"{src} not found — run `{build_hint}` (or pass --source_file)")
    with open(src, "r", encoding="utf-8") as f:
        text = f.read()
    tok = make_tokenizer(text)
    ids = np.asarray(tok.encode(text), dtype=np.uint16)
    return write_bins(ids, out_dir, tok.meta())


def prepare_english_prose_dataset(out_dir: str,
                                  source_file: str | None = None) -> dict:
    """Char-level prep of the committed REAL-text fixture.

    The zero-egress counterpart of the tiny-shakespeare flow
    (the reference notebook downloads its corpus over the network;
    this environment cannot): ``scripts/make_real_corpus.py`` assembles
    ~4 MB of human-written English from redistributable in-image prose
    and commits it at data/fixtures/english_prose.txt.
    """
    return _prepare_fixture_dataset(
        out_dir, REAL_FIXTURE, "python scripts/make_real_corpus.py",
        CharTokenizer.from_text, source_file)


XL_FIXTURE = os.path.join(_REPO_ROOT, "data", "fixtures",
                          "english_prose_xl.txt")


def prepare_english_prose_bpe_dataset(out_dir: str,
                                      source_file: str | None = None) -> dict:
    """GPT-2-regime prep of the committed XL real-text fixture with the
    committed 50,257-entry byte-BPE vocab (scripts/make_bpe_vocab.py) —
    the zero-egress counterpart of the reference's tiktoken/OpenWebText
    flow (ipynb:37, gh_sync.ps1:144-148). Real text, real BPE tokens, no
    network, no synthetic fallback."""
    return _prepare_fixture_dataset(
        out_dir, XL_FIXTURE,
        "python scripts/make_real_corpus.py --out "
        "data/fixtures/english_prose_xl.txt --max_mb 100 --profile xl",
        lambda _text: get_tokenizer("bpe"), source_file)


def download_openwebtext(num_chars: int, dataset_name: str = "Skylion007/openwebtext"
                         ) -> str:
    """Stream an OpenWebText subset via HF datasets (backlog #22's "small
    OWT subset ... size via env"). Raises if the `datasets` package or the
    network is unavailable — callers decide whether synthetic is acceptable.
    """
    import datasets  # noqa: PLC0415 — optional dep, only needed for OWT

    stream = datasets.load_dataset(dataset_name, split="train", streaming=True)
    chunks: list[str] = []
    total = 0
    for ex in stream:
        doc = ex.get("text", "")
        chunks.append(doc)
        total += len(doc) + 1
        if total >= num_chars:
            break
    return "\n".join(chunks)[:num_chars]


def prepare_bpe_dataset(out_dir: str, source_files: list[str] | None = None,
                        text: str | None = None, tokenizer: str = "gpt2",
                        num_chars: int | None = None,
                        allow_synthetic: bool = True,
                        download: bool = True,
                        allow_byte_fallback: bool = False) -> dict:
    """OpenWebText-style prep (backlog item #22, gh_sync.ps1:144-148).

    Source resolution order: explicit ``text`` > ``source_files`` > streamed
    OpenWebText download (capped at ``num_chars``) > synthetic (only when
    ``allow_synthetic``, with a loud warning). Tokenizes with the requested
    tokenizer ('gpt2' tiktoken, 'bpe' committed offline vocab, 'byte').

    A tokenizer that can't construct (e.g. 'gpt2' offline) FAILS by
    default: silently producing vocab-256 byte bins for a dataset the
    training config budgets 50k vocab for invalidates the run. Pass
    ``allow_byte_fallback=True`` (CLI: --allow_byte_fallback) to opt into
    the downgrade, which is then recorded loudly and in meta.pkl.
    """
    if text is None:
        chunks = []
        for p in source_files or []:
            with open(p, "r", encoding="utf-8") as f:
                chunks.append(f.read())
        text = "\n".join(chunks)
    if not text and download:
        try:
            text = download_openwebtext(num_chars or 10_000_000)
        except Exception:
            if not allow_synthetic:
                raise
    if not text:
        if not allow_synthetic:
            raise ValueError("no source text provided and download failed")
        _warn_synthetic("openwebtext download")
        text = _synthetic_corpus(n_chars=num_chars or 1_000_000)
    if num_chars:
        text = text[:num_chars]
    try:
        tok = get_tokenizer(tokenizer)
    except (RuntimeError, FileNotFoundError, ImportError) as e:
        if not allow_byte_fallback:
            raise RuntimeError(
                f"tokenizer {tokenizer!r} unavailable and byte fallback is "
                "opt-in (pass allow_byte_fallback=True / "
                "--allow_byte_fallback to accept vocab-256 bins)") from e
        import sys

        print(f"WARNING: tokenizer {tokenizer!r} unavailable — downgrading "
              "to the vocab-256 BYTE tokenizer (allow_byte_fallback=True). "
              f"Cause: {e}", file=sys.stderr)
        tok = ByteTokenizer()
    ids = np.asarray(tok.encode(text), dtype=np.uint16)
    return write_bins(ids, out_dir, tok.meta())


def main(argv: list[str] | None = None) -> None:
    import argparse

    ap = argparse.ArgumentParser(description="prepare dataset bins")
    ap.add_argument("dataset", choices=["shakespeare_char", "openwebtext",
                                        "english_prose_char",
                                        "english_prose_bpe"])
    ap.add_argument("--data_dir", default=os.environ.get("DATA_DIR", "data"))
    ap.add_argument("--source_file", default=None)
    ap.add_argument("--num_chars", type=int,
                    default=int(os.environ.get("DATASET_NUM_CHARS", "0")) or None)
    ap.add_argument("--tokenizer", default="gpt2")
    # shakespeare_char is the smoke-test dataset: synthetic fallback stays on
    # by default (reference scale-down philosophy). openwebtext is a REAL
    # training corpus: silent synthetic data would invalidate runs, so it
    # fails loudly unless explicitly allowed (env for the k8s Job).
    # BooleanOptionalAction so BOTH directions are expressible on the CLI
    # (--allow_synthetic / --no-allow_synthetic); None falls through to the
    # DATASET_ALLOW_SYNTHETIC env var, then the per-dataset default.
    ap.add_argument("--allow_synthetic", default=None,
                    action=argparse.BooleanOptionalAction)
    ap.add_argument("--allow_byte_fallback", action="store_true",
                    help="accept a vocab-256 byte downgrade when the "
                         "requested BPE tokenizer is unavailable (off by "
                         "default: a silent downgrade invalidates runs "
                         "configured for a 50k vocab)")
    args = ap.parse_args(argv)
    allow_synth = args.allow_synthetic
    if allow_synth is None:
        env = os.environ.get("DATASET_ALLOW_SYNTHETIC", "")
        allow_synth = (env == "1") if env else (args.dataset == "shakespeare_char")

    out_dir = os.path.join(args.data_dir, args.dataset)
    if args.dataset == "english_prose_char":
        stats = prepare_english_prose_dataset(out_dir,
                                              source_file=args.source_file)
    elif args.dataset == "english_prose_bpe":
        stats = prepare_english_prose_bpe_dataset(
            out_dir, source_file=args.source_file)
    elif args.dataset == "shakespeare_char":
        stats = prepare_char_dataset(out_dir, source_file=args.source_file,
                                     allow_synthetic=allow_synth)
    else:
        stats = prepare_bpe_dataset(
            out_dir, source_files=[args.source_file] if args.source_file else None,
            tokenizer=args.tokenizer, num_chars=args.num_chars,
            allow_synthetic=allow_synth,
            allow_byte_fallback=args.allow_byte_fallback)
    print(f"prepared {args.dataset} -> {out_dir}: {stats}")


if __name__ == "__main__":
    main()
