"""Autoregressive sampling from a trained checkpoint.

nanoGPT ships sample.py alongside train.py (the reference exercises the
trainer only, SURVEY.md §2.3, but generation is part of the nanoGPT
capability surface a user expects). TPU-native decode: a lax.scan over
positions with a fixed block_size context window — fully jit-compiled,
no Python control flow per token.

    python -m nanosandbox_tpu.sample --out_dir=out --start="\\n" \
        --num_samples=3 --max_new_tokens=200 --temperature=0.8 --top_k=40
"""

from __future__ import annotations

import os
import sys
from functools import partial


def generate(model, params, idx, max_new_tokens: int, *, temperature: float,
             top_k: int, rng, block_size: int):
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, T0 = idx.shape
    total = max(T0 + max_new_tokens, block_size + 1)
    # Fixed-shape buffer so the whole decode is one compiled scan; causal
    # attention makes the zero-padding beyond the frontier harmless.
    buf = jnp.zeros((B, total), jnp.int32).at[:, :T0].set(idx)

    def step(carry, i):
        # i = position of the last known token; we sample position i+1.
        buf, rng = carry
        start = jnp.clip(i + 1 - block_size, 0, total - block_size)
        ctx = lax.dynamic_slice(buf, (0, start), (B, block_size))
        logits = model.apply({"params": params}, ctx, deterministic=True)
        pos_in_ctx = i - start
        logits_i = logits[jnp.arange(B), pos_in_ctx, :] / temperature
        if top_k > 0:
            k = min(top_k, logits_i.shape[-1])  # nanoGPT clamps to vocab
            kth = jnp.sort(logits_i, axis=-1)[:, -k][:, None]
            logits_i = jnp.where(logits_i < kth, -1e30, logits_i)
        rng, sub = jax.random.split(rng)
        nxt = jax.random.categorical(sub, logits_i)
        buf = buf.at[:, i + 1].set(nxt.astype(jnp.int32))
        return (buf, rng), None

    (buf, _), _ = lax.scan(step, (buf, rng),
                           jnp.arange(T0 - 1, T0 - 1 + max_new_tokens))
    return buf[:, :T0 + max_new_tokens]


def main(argv: list[str] | None = None) -> list[str]:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", default="out")
    ap.add_argument("--data_dir", default="data")
    ap.add_argument("--dataset", default="shakespeare_char")
    ap.add_argument("--start", default="\n")
    ap.add_argument("--num_samples", type=int, default=1)
    ap.add_argument("--max_new_tokens", type=int, default=200)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top_k", type=int, default=40)
    ap.add_argument("--seed", type=int, default=1337)
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.checkpoint import Checkpointer
    from nanosandbox_tpu.config import GPTConfig, TrainConfig
    from nanosandbox_tpu.data.loader import BinDataset
    from nanosandbox_tpu.data.tokenizer import get_tokenizer
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.train import Trainer, make_optimizer

    ckpt = Checkpointer(args.out_dir)
    step = ckpt.latest_step()
    if step is None:
        raise FileNotFoundError(f"no checkpoint under {args.out_dir}/ckpt")
    # Restore config first to rebuild the model/optimizer shapes.
    import orbax.checkpoint as ocp
    restored_extra = ckpt.mgr.restore(
        step, args=ocp.args.Composite(extra=ocp.args.JsonRestore()))
    cfg = TrainConfig(**{**restored_extra["extra"]["config"],
                         "device": "auto", "init_from": "resume",
                         "out_dir": args.out_dir,
                         "data_dir": args.data_dir})
    if (cfg.attention_impl == "ring" or cfg.mesh_sp > 1
            or cfg.mesh_fsdp > 1 or cfg.mesh_tp > 1):
        # Decode is short-sequence and runs on whatever host invokes it:
        # drop all training-time model/sequence parallelism — Orbax restores
        # checkpoints onto any mesh, and a pure-DP mesh always fits.
        cfg = cfg.replace(attention_impl="auto" if cfg.attention_impl == "ring"
                          else cfg.attention_impl,
                          mesh_sp=1, mesh_fsdp=1, mesh_tp=1, mesh_dp=-1,
                          shard_params=False)
    trainer = Trainer(cfg)
    state, _ = ckpt.restore(trainer.abstract_state, step)
    params = state["params"]

    ds = BinDataset(args.data_dir, args.dataset)
    meta = ds.meta
    tok = get_tokenizer(meta.get("kind", "char"), meta)
    start_ids = tok.encode(args.start) or [0]

    idx = jnp.asarray([start_ids] * args.num_samples, jnp.int32)
    rng = jax.random.key(args.seed)
    gen = jax.jit(partial(generate, trainer.model,
                          max_new_tokens=args.max_new_tokens,
                          temperature=args.temperature, top_k=args.top_k,
                          block_size=cfg.block_size))
    out = gen(params, idx, rng=rng)
    texts = []
    for row in out:
        text = tok.decode([int(t) for t in row])
        texts.append(text)
        print(text)
        print("---------------")
    return texts


if __name__ == "__main__":
    main()
