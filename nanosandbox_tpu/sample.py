"""Autoregressive sampling from a trained checkpoint.

nanoGPT ships sample.py alongside train.py (the reference exercises the
trainer only, SURVEY.md §2.3, but generation is part of the nanoGPT
capability surface a user expects). TPU-native decode: one prefill pass
then a KV-cached lax.scan — one token per step against per-layer cache
buffers, fully jit-compiled, no Python control flow per token. Requests
longer than block_size fall back to the sliding-window full-forward scan.

    python -m nanosandbox_tpu.sample --out_dir=out --start="\\n" \
        --num_samples=3 --max_new_tokens=200 --temperature=0.8 --top_k=40
"""

from __future__ import annotations

import os
import sys
from functools import partial


def _sample_token(logits_i, rng, *, temperature: float, top_k: int,
                  top_p: float = 1.0):
    """One sampling decision from (B, V) logits. temperature=0 is greedy
    (argmax, no RNG consumed) — torch's convention and the determinism
    anchor for the cached-vs-windowed parity tests. top_k and top_p
    (nucleus) compose: k-truncation first, then the smallest probability
    mass >= top_p survives.

    temperature/top_k/top_p may also be (B,) vectors — each row then
    samples under its OWN parameters (the serve engine's continuous
    batch mixes requests with different settings in one step). The
    vector path also accepts ``rng`` as a (B,) batch of typed keys
    (one independent stream per row, so a request's tokens don't
    depend on which other requests share its batch); with a single
    key it splits once and samples all rows from the same stream."""
    import jax
    import jax.numpy as jnp

    logits_i = logits_i.astype(jnp.float32)
    if any(getattr(x, "ndim", 0) >= 1 for x in (temperature, top_k, top_p)):
        return _sample_token_rows(logits_i, rng, temperature=temperature,
                                  top_k=top_k, top_p=top_p)
    if temperature == 0.0:
        return jnp.argmax(logits_i, axis=-1).astype(jnp.int32), rng
    logits_i = logits_i / temperature
    if top_k > 0:
        k = min(top_k, logits_i.shape[-1])  # nanoGPT clamps to vocab
        # lax.top_k, not a full vocab sort: the decode loop runs this every
        # token and a 50k-entry sort costs more than the whole 124M
        # per-token matmul work.
        kth = jax.lax.top_k(logits_i, k)[0][:, -1][:, None]
        logits_i = jnp.where(logits_i < kth, -1e30, logits_i)
    if top_p < 1.0:
        # Nucleus filter: drop tokens outside the smallest set whose
        # probability mass reaches top_p. Sorted once (descending); a
        # token survives if the mass BEFORE it is still < top_p (keeps
        # at least the top-1 token by construction).
        sort_idx = jnp.argsort(-logits_i, axis=-1)
        sorted_logits = jnp.take_along_axis(logits_i, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        mass_before = jnp.cumsum(probs, axis=-1) - probs
        # .at[0].set(True): mass_before[0] == 0 is not < top_p when
        # top_p <= 0, which would mask EVERY token and turn categorical
        # into uniform-over-vocab garbage; the top-1 token always survives.
        keep_sorted = (mass_before < top_p).at[:, 0].set(True)
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(keep_sorted.shape[0])[:, None], sort_idx
        ].set(keep_sorted)
        logits_i = jnp.where(keep, logits_i, -1e30)
    rng, sub = jax.random.split(rng)
    return jax.random.categorical(sub, logits_i).astype(jnp.int32), rng


def _filter_logits_rows(logits_i, *, temperature, top_k, top_p):
    """Per-row temperature/top-k/nucleus filtering of (B, V) float32
    logits: returns categorical-ready logits (filtered entries -1e30).
    Shared by _sample_token_rows and the speculative-verify path
    (serve/spec.py) — the verify step must score draft tokens against
    EXACTLY the distribution the decode step samples from, or rejection
    sampling stops preserving the output distribution, so the filter
    lives in one function both compile.

    Rows with temperature <= 0 are scaled by 1 (the caller takes argmax
    of the RAW logits for those, the scalar greedy contract).

    Costs one full-vocab argsort per call — the descending permutation
    is shared by the per-row kth threshold (lax.top_k needs a static k;
    per-row k does not have one) and the nucleus cumsum. The sort only
    RUNS when some row actually filters (lax.cond below): greedy rows
    never consume the filtered logits (their callers take raw argmax),
    and t>0 rows with top-k/top-p disabled get identity filtering, so
    an all-greedy/unfiltered batch — the serving common case, and every
    speculative-verify step of a greedy workload — skips the whole sort
    at runtime while staying ONE compiled program."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, V = logits_i.shape
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))
    p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))

    x = logits_i / jnp.where(t > 0, t, 1.0)[:, None]

    def _full(x):
        # ONE shared descending permutation serves both filters (the
        # full-vocab sort is this path's hot cost — see docstring).
        # Top-k only demotes entries already below the kth threshold to
        # -1e30, so the pre-filter order still sorts the post-filter
        # array for the nucleus cumsum.
        sort_idx = jnp.argsort(-x, axis=-1)

        # Per-row top-k: the kth-largest value is the keep threshold;
        # rows with k <= 0 (disabled) skip the filter via the mask.
        srt = jnp.take_along_axis(x, sort_idx, axis=-1)
        kth = jnp.take_along_axis(srt, (jnp.clip(k, 1, V) - 1)[:, None],
                                  axis=-1)
        x = jnp.where((k[:, None] > 0) & (x < kth), -1e30, x)

        # Per-row nucleus: same construction as the scalar path with p
        # broadcast per row; p >= 1 rows keep everything exactly (no
        # reliance on cumsum rounding), p <= 0 rows degrade to top-1.
        sorted_logits = jnp.take_along_axis(x, sort_idx, axis=-1)
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        mass_before = jnp.cumsum(probs, axis=-1) - probs
        keep_sorted = ((mass_before < p[:, None]) |
                       (p[:, None] >= 1.0)).at[:, 0].set(True)
        keep = jnp.zeros_like(keep_sorted).at[
            jnp.arange(B)[:, None], sort_idx].set(keep_sorted)
        return jnp.where(keep, x, -1e30)

    # A row filters only when it both samples (t > 0; greedy rows take
    # raw argmax and never read this output) and truncates (k > 0 or
    # p < 1; otherwise the filter is identity on the scaled logits).
    need = jnp.any((t > 0.0) & ((k > 0) | (p < 1.0)))
    return lax.cond(need, _full, lambda x: x, x)


def _sample_token_rows(logits_i, rng, *, temperature, top_k, top_p):
    """Vectorized per-row variant of _sample_token: every parameter is
    broadcast to (B,) and each row is filtered/sampled under its own
    settings (via _filter_logits_rows above). Rows with temperature == 0
    take argmax of the RAW logits (identical to the scalar greedy
    contract, and independent of the other rows' parameters). Branches
    become masks — one compiled shape serves every parameter mix, which
    is what bounds the serve engine's compile count."""
    import jax
    import jax.numpy as jnp

    B = logits_i.shape[0]
    t = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    greedy = jnp.argmax(logits_i, axis=-1).astype(jnp.int32)
    x = _filter_logits_rows(logits_i, temperature=temperature,
                            top_k=top_k, top_p=top_p)

    # jaxlint: disable=tracer-leak -- _is_key_batch reads dtype/ndim only (static)
    if _is_key_batch(rng):
        sampled = jax.vmap(jax.random.categorical)(rng, x).astype(jnp.int32)
    else:
        rng, sub = jax.random.split(rng)
        sampled = jax.random.categorical(sub, x).astype(jnp.int32)
    return jnp.where(t == 0.0, greedy, sampled), rng


def row_keys(seeds, positions):
    """(B,) typed PRNG keys, one per row: fold_in(key(seeds[b]),
    positions[b]). The serve engine's sampling-stream contract — the
    token destined for position q of a request seeded s is always drawn
    from fold_in(key(s), q), whether it comes from a prefill wave or a
    batched decode step — lives here so the two compiled paths can never
    drift apart."""
    import jax

    return jax.vmap(
        lambda s, q: jax.random.fold_in(jax.random.key(s), q)
    )(seeds, positions)


def _is_key_batch(rng) -> bool:
    """True when rng is a (B,) batch of typed PRNG keys (vs one key)."""
    import jax

    try:
        return (jax.dtypes.issubdtype(rng.dtype, jax.dtypes.prng_key)
                and rng.ndim == 1)
    except (AttributeError, TypeError):
        return False


def resolve_start(start: str) -> str:
    """nanoGPT's --start convention: 'FILE:<path>' reads the prompt from a
    file (verbatim, trailing newline included); anything else is the
    prompt text itself."""
    if start.startswith("FILE:"):
        with open(start[len("FILE:"):], "r", encoding="utf-8") as f:
            return f.read()
    return start


def generate(model, params, idx, max_new_tokens: int, *, temperature: float,
             top_k: int, rng, block_size: int, top_p: float = 1.0):
    """KV-cached decode: one prefill over the prompt, then a lax.scan whose
    step runs the model on a SINGLE token against per-layer (B, H, total, D)
    cache buffers (models/gpt.py cache path). Attention reads grow with the
    frontier instead of re-running block_size positions per token — the
    windowed fallback below re-forwards the full context every step, O(T)
    model FLOPs per token vs the cache's O(1).

    Falls back to the windowed path only when the requested total exceeds
    block_size (the learned wpe table defines no positions past it, so a
    sliding window is the only meaning 'longer than block_size' can have)."""
    import jax.numpy as jnp
    from jax import lax

    from nanosandbox_tpu.models.gpt import init_cache

    B, T0 = idx.shape
    total = T0 + max_new_tokens
    if max_new_tokens == 0:
        return idx
    if total > block_size:
        return _generate_windowed(model, params, idx, max_new_tokens,
                                  temperature=temperature, top_k=top_k,
                                  rng=rng, block_size=block_size, top_p=top_p)

    cache = init_cache(model.cfg, B, total)
    logits, cache = model.apply({"params": params}, idx, deterministic=True,
                                cache=cache, cache_index=0)
    nxt, rng = _sample_token(logits[:, -1, :], rng,
                             temperature=temperature, top_k=top_k, top_p=top_p)

    def step(carry, i):
        tok, cache, rng = carry
        logits, cache = model.apply({"params": params}, tok[:, None],
                                    deterministic=True,
                                    cache=cache, cache_index=i)
        nxt, rng = _sample_token(logits[:, 0, :], rng,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p)
        return (nxt, cache, rng), tok

    (last, _, _), ys = lax.scan(step, (nxt, cache, rng),
                                jnp.arange(T0, total - 1))
    new_tokens = jnp.concatenate([ys.T, last[:, None]], axis=1) \
        if max_new_tokens > 1 else last[:, None]
    return jnp.concatenate([idx, new_tokens], axis=1)


def cast_params_for_serving(params, compute_dtype):
    """Inference-standard cast of float32 params to compute_dtype (bf16 on
    TPU): batch-~1 decode is weight-READ-bound — the whole parameter set
    streams from HBM per token — so halving the bytes halves per-token
    latency. No-op when compute_dtype is float32 (CPU configs)."""
    import jax
    import jax.numpy as jnp

    cdt = jnp.dtype(compute_dtype)
    return jax.tree.map(
        lambda a: a.astype(cdt) if a.dtype == jnp.float32 else a, params)


def _generate_windowed(model, params, idx, max_new_tokens: int, *,
                       temperature: float, top_k: int, rng, block_size: int,
                       top_p: float = 1.0):
    """Full-forward sliding-window decode (nanoGPT's crop-and-reforward
    semantics) — the only correct option once positions pass block_size."""
    import jax.numpy as jnp
    from jax import lax

    B, T0 = idx.shape
    total = max(T0 + max_new_tokens, block_size + 1)
    # Fixed-shape buffer so the whole decode is one compiled scan; causal
    # attention makes the zero-padding beyond the frontier harmless.
    buf = jnp.zeros((B, total), jnp.int32).at[:, :T0].set(idx)

    def step(carry, i):
        # i = position of the last known token; we sample position i+1.
        buf, rng = carry
        start = jnp.clip(i + 1 - block_size, 0, total - block_size)
        ctx = lax.dynamic_slice(buf, (0, start), (B, block_size))
        logits = model.apply({"params": params}, ctx, deterministic=True)
        pos_in_ctx = i - start
        logits_i = logits[jnp.arange(B), pos_in_ctx, :]
        nxt, rng = _sample_token(logits_i, rng,
                                 temperature=temperature, top_k=top_k,
                                 top_p=top_p)
        buf = buf.at[:, i + 1].set(nxt)
        return (buf, rng), None

    (buf, _), _ = lax.scan(step, (buf, rng),
                           jnp.arange(T0 - 1, T0 - 1 + max_new_tokens))
    return buf[:, :T0 + max_new_tokens]


def main(argv: list[str] | None = None) -> list[str]:
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--out_dir", default="out")
    ap.add_argument("--data_dir", default="data")
    ap.add_argument("--dataset", default="shakespeare_char")
    ap.add_argument("--start", default="\n",
                    help="prompt text, or FILE:<path> to read it from a "
                         "file (nanoGPT convention)")
    ap.add_argument("--num_samples", type=int, default=1)
    ap.add_argument("--max_new_tokens", type=int, default=200)
    ap.add_argument("--temperature", type=float, default=0.8)
    ap.add_argument("--top_k", type=int, default=40)
    ap.add_argument("--top_p", type=float, default=1.0,
                    help="nucleus sampling mass (1.0 disables)")
    ap.add_argument("--seed", type=int, default=1337)
    ap.add_argument("--spec", default="off",
                    help="speculative decoding: 'ngram' (prompt-lookup "
                         "drafting, zero extra weights) or "
                         "'model:<out_dir>' (a smaller same-tokenizer "
                         "draft checkpoint); routes generation through "
                         "the serve engine's batched verify step — "
                         "greedy outputs identical, sampled outputs "
                         "identically distributed (per-sample seeds "
                         "seed+i instead of one shared stream)")
    ap.add_argument("--spec_k", type=int, default=4,
                    help="draft tokens per verify step (--spec only)")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])
    if args.num_samples < 1:
        # Validate BEFORE the checkpoint restore below: a bad flag should
        # fail in milliseconds, not after loading a model.
        ap.error(f"--num_samples must be >= 1, got {args.num_samples}")
    # Same fail-fast rule for --start=FILE:<path>: a typo'd path must not
    # cost the user a full model restore before erroring.
    start_text = resolve_start(args.start)

    import jax
    import jax.numpy as jnp
    import numpy as np

    from nanosandbox_tpu.data.loader import BinDataset
    from nanosandbox_tpu.data.tokenizer import get_tokenizer
    from nanosandbox_tpu.train import restore_for_inference

    trainer, state, _ = restore_for_inference(args.out_dir,
                                              data_dir=args.data_dir)
    cfg = trainer.cfg
    params = cast_params_for_serving(state["params"], cfg.compute_dtype)

    ds = BinDataset(args.data_dir, args.dataset)
    meta = ds.meta
    tok = get_tokenizer(meta.get("kind", "char"), meta)
    start_ids = tok.encode(start_text) or [0]

    if args.spec != "off":
        # Speculative path: generation runs through the serve engine's
        # batched verify step (serve/spec.py) — the drafter guesses k
        # tokens and one target forward scores them all. Bounded to the
        # cached-decode regime: the windowed fallback has no KV frontier
        # to verify against.
        from nanosandbox_tpu.serve import Engine
        from nanosandbox_tpu.serve.drafters import drafter_from_flag

        total = len(start_ids) + args.max_new_tokens
        if total > cfg.block_size:
            ap.error(f"--spec needs prompt + max_new_tokens <= block_size "
                     f"({total} > {cfg.block_size}); drop --spec to use "
                     "the windowed fallback")
        drafter = drafter_from_flag(args.spec, k=args.spec_k,
                                    data_dir=args.data_dir)
        engine = Engine(trainer.model, params,
                        num_slots=min(args.num_samples, 8),
                        max_len=cfg.block_size, spec=drafter)
        rids = [engine.submit(start_ids, args.max_new_tokens,
                              temperature=args.temperature,
                              top_k=args.top_k, top_p=args.top_p,
                              seed=args.seed + i)
                for i in range(args.num_samples)]
        res = {r.rid: r for r in engine.drain()}
        texts = []
        for rid in rids:
            text = tok.decode(list(res[rid].prompt) + res[rid].tokens)
            texts.append(text)
            print(text)
            print("---------------")
        s = engine.stats()
        print(f"[spec] drafter={s['spec']['drafter']} k={s['spec']['k']} "
              f"acceptance_rate={s['spec_acceptance_rate']} "
              f"accepted_len_mean={s['spec_accepted_len_mean']}",
              file=sys.stderr)
        return texts

    idx = jnp.asarray([start_ids] * args.num_samples, jnp.int32)
    rng = jax.random.key(args.seed)
    gen = jax.jit(partial(generate, trainer.model,
                          max_new_tokens=args.max_new_tokens,
                          temperature=args.temperature, top_k=args.top_k,
                          top_p=args.top_p, block_size=cfg.block_size))
    out = gen(params, idx, rng=rng)
    # ONE batched readback, then host-side decode: int() per element of
    # a live device array costs a device->host round trip PER TOKEN
    # (jaxlint host-sync caught this one).
    # jaxlint: disable=host-sync -- the single final readback of the samples
    out_host = np.asarray(out)
    texts = []
    for row in out_host:
        text = tok.decode([int(t) for t in row])
        texts.append(text)
        print(text)
        print("---------------")
    return texts


if __name__ == "__main__":
    main()
