"""ctypes loader for the native batch-gather library (csrc/batchgen.cpp).

Compiles the shared library on first use with g++ (cached next to the
source); every entry point has a pure-numpy fallback so the framework works
on machines without a toolchain. pybind11 is not in the image, so the
binding is plain ctypes over an ``extern "C"`` surface.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "csrc", "batchgen.cpp")
_LIB_PATH = os.path.join(_REPO_ROOT, "csrc", "libbatchgen.so")

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_load_failed = False


def _build() -> bool:
    cmd = ["g++", "-O3", "-march=native", "-fPIC", "-shared", "-fopenmp",
           _SRC, "-o", _LIB_PATH]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
        return True
    except Exception:
        try:  # retry without -march/-fopenmp for maximum portability
            subprocess.run(["g++", "-O3", "-fPIC", "-shared", _SRC,
                            "-o", _LIB_PATH],
                           check=True, capture_output=True, timeout=120)
            return True
        except Exception:
            return False


def get_lib() -> ctypes.CDLL | None:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    with _lock:
        if _lib is not None or _load_failed:
            return _lib
        if not os.path.exists(_LIB_PATH) or (
                os.path.exists(_SRC)
                and os.path.getmtime(_SRC) > os.path.getmtime(_LIB_PATH)):
            # lockcheck: disable=blocking-under-lock -- build-once by
            # design: the double-checked _lock exists precisely so ONE
            # thread compiles the .so while every other caller waits
            # rather than racing g++ over the same output file; cold
            # path, runs at most once per process.
            if not os.path.exists(_SRC) or not _build():
                _load_failed = True
                return None
        try:
            lib = ctypes.CDLL(_LIB_PATH)
            lib.gather_windows_u16.argtypes = [
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
            lib.sample_offsets.argtypes = [
                ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int64,
                ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
            _lib = lib
        except OSError:
            _load_failed = True
    return _lib


def gather_windows(data: np.ndarray, offsets: np.ndarray, width: int) -> np.ndarray:
    """Gather ``len(offsets)`` windows of ``width`` uint16 tokens from data."""
    assert data.dtype == np.uint16
    offsets = np.ascontiguousarray(offsets, dtype=np.int64)
    B = len(offsets)
    out = np.empty((B, width), dtype=np.uint16)
    lib = get_lib()
    if lib is not None:
        lib.gather_windows_u16(
            data.ctypes.data_as(ctypes.c_void_p), data.shape[0],
            offsets.ctypes.data_as(ctypes.c_void_p), B, width,
            out.ctypes.data_as(ctypes.c_void_p))
        return out
    # numpy fallback: fancy-index a window per row
    idx = offsets[:, None] + np.arange(width)[None, :]
    np.take(data, idx, out=out)
    return out


def sample_offsets(seed: int, stream: int, n_tokens: int, width: int,
                   batch: int) -> np.ndarray:
    """Deterministic offsets in [0, n_tokens - width]; native or numpy path.

    Note: the two paths use different RNGs, so determinism holds per-path.
    The loader records which path is active (BatchLoader.native).
    """
    lib = get_lib()
    if lib is not None:
        out = np.empty(batch, dtype=np.int64)
        lib.sample_offsets(seed, stream, n_tokens, width, batch,
                           out.ctypes.data_as(ctypes.c_void_p))
        return out
    # stream goes into the 128-bit Philox KEY (not the counter): adjacent
    # stream ids get unrelated keystreams, so per-host/per-step draws never
    # overlap the way nearby counter offsets would.
    key = (int(seed) << 64) | (int(stream) & ((1 << 64) - 1))
    rng = np.random.Generator(np.random.Philox(key=key))
    return rng.integers(0, n_tokens - width + 1, size=batch, dtype=np.int64)
