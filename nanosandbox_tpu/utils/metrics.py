"""Metric logging: stdout, JSONL, and TensorBoard event files.

Mirrors the reference's three observability mechanisms (SURVEY.md §5): (1)
stdout every log_interval iters consumed via `kubectl logs -f`
(README.md:59); (2) TensorBoard event files under /data/runs, exported with
`kubectl cp` (README.md:74-87); (3) eval-loss lines every eval_interval.
JSONL is added as a machine-readable mirror of stdout.

Only process 0 writes (multi-host SPMD: every host computes identical
globals, so one writer suffices — the analogue of DDP rank-0 logging).
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Any

_WARNED_ONCE: set[str] = set()


def warn_once(key: str, msg: str) -> None:
    """Process-wide one-shot warning to stderr, deduplicated by ``key``.

    For conditions that are expected exactly once per run but alarming
    when repeated (e.g. Checkpointer.save skipping an already-saved step
    right after resume): the first occurrence is logged so the run
    doesn't LOOK like it silently stopped doing the thing, repeats stay
    quiet so a hot loop can't flood the log.

    Every firing also lands in the process-global metric registry as
    ``warn_once_fired_total{key=...}`` — a scrape sees WHICH one-shot
    conditions a pod hit without anyone tailing stderr."""
    if key in _WARNED_ONCE:
        return
    _WARNED_ONCE.add(key)
    # Lazy import: obs.registry imports RingStat from this module, so a
    # top-level import here would be a cycle.
    from nanosandbox_tpu.obs.registry import global_registry
    global_registry().counter(
        "warn_once_fired_total",
        "One-shot warn_once firings, by dedup key.",
        labelnames=("key",)).labels(key=key).inc()
    print(msg, file=sys.stderr, flush=True)


def reset_for_tests() -> None:
    """Clear the warn_once dedup registry so tests can assert a warning
    fires (and fires once) without ordering against every other test
    that shares the process. The ``warn_once_fired_total`` counter is
    NOT reset — it is a monotonic process-lifetime ledger."""
    _WARNED_ONCE.clear()


class RingStat:
    """Bounded ring of float samples with mean/percentile reads.

    The serving latency signal (TTFT/TPOT/queue-wait in Engine.stats)
    must cost O(1) memory over an unbounded request stream, so samples
    live in a fixed-size deque: percentiles describe the RECENT window,
    which is the operationally useful view (a k8s dashboard wants "how
    slow is it now", not a lifetime average diluted by warmup)."""

    def __init__(self, maxlen: int = 1024):
        from collections import deque

        self._buf = deque(maxlen=maxlen)

    def record(self, x: float) -> None:
        self._buf.append(float(x))

    def __len__(self) -> int:
        return len(self._buf)

    def clear(self) -> None:
        self._buf.clear()

    def mean(self) -> float | None:
        # list(deque) is a single C-level copy (atomic under the GIL):
        # reads may race a recording thread (the HTTP /stats handler vs
        # the engine loop), and Python-level iteration over a deque
        # being appended to raises "deque mutated during iteration".
        buf = list(self._buf)
        if not buf:
            return None
        return sum(buf) / len(buf)

    def percentiles(self, ps: tuple = (50, 90, 99)) -> dict | None:
        """{"p50": ..., "p90": ..., ...} over the window (nearest-rank),
        or None before the first sample."""
        srt = sorted(self._buf)   # C-level snapshot+sort, like mean()
        if not srt:
            return None
        n = len(srt)
        out = {}
        for p in ps:
            rank = max(1, -(-int(p) * n // 100))  # ceil(p/100 * n), >= 1
            out[f"p{int(p)}"] = srt[min(rank, n) - 1]
        return out


class MetricsWriter:
    def __init__(self, log_dir: str, run_name: str = "", enabled: bool = True,
                 tensorboard: bool = True):
        self.enabled = enabled
        self.tb = None
        self.jsonl = None
        if not enabled:
            return
        run = run_name or time.strftime("%Y%m%d-%H%M%S")
        self.dir = os.path.join(log_dir, run)
        os.makedirs(self.dir, exist_ok=True)
        self.jsonl = open(os.path.join(self.dir, "metrics.jsonl"), "a",
                          buffering=1)
        self._pending_headers: list[dict[str, Any]] = []
        self._wrote_any = False
        if tensorboard:
            self.tb = self._make_tb_writer(self.dir)

    @staticmethod
    def _make_tb_writer(log_dir: str):
        """A SummaryWriter from whichever TB package the image ships.

        tensorboardX first: it is a small pure-python dependency pinned in
        docker/Dockerfile, so the /data/runs event-file contract
        (reference README.md:74-87) holds in deployment. torch's writer is
        a dev-machine fallback only — round 1 imported ONLY torch here and
        the shipped image has no torch, so TB silently degraded to JSONL
        (VERDICT.md missing #5).
        """
        try:
            from tensorboardX import SummaryWriter
            return SummaryWriter(log_dir=log_dir)
        except Exception:
            pass
        try:
            from torch.utils.tensorboard import SummaryWriter
            return SummaryWriter(log_dir=log_dir)
        except Exception:
            return None

    def write_header(self, meta: dict[str, Any]) -> None:
        """One provenance record for metrics.jsonl — run policy facts a
        reader needs to interpret the stream but that are not per-step
        scalars (fixed-eval-batch seed policy, which offset sampler the
        loader resolved, rng impl). Round-4 VERDICT weak #5/#7: both
        were undocumented in run artifacts.

        Header-on-first-write: the record is DEFERRED until the first
        ``log()`` so a run that opens a writer and closes it without
        logging a single scalar leaves no half-run artifact (a lone
        header line used to masquerade as a run that produced metrics).
        If scalars were already written, the header lands immediately —
        deferring it would only push it further from the top."""
        if not self.enabled or self.jsonl is None:
            return
        rec = {"header": meta, "time": time.time()}
        if self._wrote_any:
            self.jsonl.write(json.dumps(rec) + "\n")
        else:
            self._pending_headers.append(rec)

    def log(self, step: int, scalars: dict[str, Any]) -> None:
        if not self.enabled:
            return
        for rec in self._pending_headers:
            self.jsonl.write(json.dumps(rec) + "\n")
        self._pending_headers.clear()
        self._wrote_any = True
        rec = {"step": step, "time": time.time(), **scalars}
        self.jsonl.write(json.dumps(rec) + "\n")
        if self.tb is not None:
            for k, v in scalars.items():
                try:
                    self.tb.add_scalar(k, float(v), step)
                except (TypeError, ValueError):
                    pass

    def close(self) -> None:
        if self.jsonl is not None:
            self.jsonl.close()
        if self.tb is not None:
            self.tb.flush()
            self.tb.close()
