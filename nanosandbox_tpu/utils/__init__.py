"""Utilities: native library loading, metric writers, tree helpers."""
