"""Runtime retrace-budget guard + deliberate-host-sync accounting.

The static half of the compile-set contract lives in
``nanosandbox_tpu.analysis`` (jaxlint); this module is the RUNTIME
half. The failure mode both defend against: a Python scalar or
unbucketed shape specializes a jitted step, XLA silently recompiles per
distinct value, and "as fast as the hardware allows" becomes
one-compile-per-request — with nothing in CI to notice.

``compile_budget`` replaces the engine's old hand-rolled
``self.trace_counts[...] += 1`` counters (a trace-time side effect
inside the jitted body — exactly what jaxlint's impure-trace rule
flags) with a wrapper OUTSIDE the traced function: jax calls the
wrapped Python body once per trace, so counting calls counts traces,
and overflowing the declared budget raises ``CompileBudgetExceeded``
immediately — a loud failure at the retrace instead of a silent 10x
serving slowdown.

    reg = TraceBudgetRegistry()
    decode = jax.jit(reg.guard("decode", 1)(decode_fn))
    ...
    reg.counts()             # {"decode": 1}
    with reg.frozen():       # post-warmup: ANY new trace raises
        serve_forever()

``host_sync`` is the blessed wrapper for a DELIBERATE device->host
readback (jaxlint recognizes it and does not flag the call): it reads
the scalar, counts the sync under a name, and lets callers report how
many syncs a window contained (train.py's profiler window does).
"""

from __future__ import annotations

import functools
import threading
from contextlib import contextmanager
from typing import Callable, Dict, Optional


def _obs_counter(metric: str, help: str, name: str):
    """The ``{name=...}`` child of a process-global counter family —
    the obs-registry mirror of this module's ledgers. Lazy import:
    obs.registry is stdlib-only but lives above utils in the package
    graph, and tracecheck must stay importable on a bare Python."""
    from nanosandbox_tpu.obs.registry import global_registry

    return global_registry().counter(metric, help,
                                     labelnames=("name",)).labels(name=name)


class CompileBudgetExceeded(RuntimeError):
    """A guarded function traced more often than its declared budget —
    some call-site input (shape, dtype, Python scalar, pytree
    structure) is not from the closed set the budget promises."""


class _Budget:
    __slots__ = ("name", "max_traces", "traces")

    def __init__(self, name: str, max_traces: int):
        self.name = name
        self.max_traces = max_traces
        self.traces = 0


class TraceBudgetRegistry:
    """A family of named retrace budgets (typically one per Engine or
    Trainer instance, so tests with many engines don't share state).

    Thread-safe: the serve engine traces on a background stepping
    thread while /stats reads counts on HTTP handler threads.
    """

    def __init__(self):
        self._budgets: Dict[str, _Budget] = {}
        self._lock = threading.Lock()
        self._frozen = False

    # ------------------------------------------------------------- budgets

    def register(self, name: str, max_traces: int) -> None:
        if max_traces < 0:
            raise ValueError(f"max_traces must be >= 0, got {max_traces}")
        with self._lock:
            b = self._budgets.get(name)
            if b is None:
                self._budgets[name] = _Budget(name, max_traces)
            else:
                b.max_traces = max_traces

    def guard(self, name: str, max_traces: int,
              ) -> Callable[[Callable], Callable]:
        """Decorator: count every call of the wrapped function (== every
        TRACE once the result is jitted) against the named budget.

        Wrap the function handed TO jax.jit, not the jitted result:

            self._decode = jax.jit(reg.guard("decode", 1)(self._decode_fn))
        """
        self.register(name, max_traces)

        def deco(fn: Callable) -> Callable:
            @functools.wraps(fn)
            def traced(*args, **kwargs):
                self.bump(name)
                return fn(*args, **kwargs)
            traced.__tracecheck_name__ = name
            return traced
        return deco

    def bump(self, name: str) -> int:
        """Record one trace; raises on budget overflow or when frozen.

        A REJECTED trace (frozen registry, or past budget) does NOT
        consume the counter: the raise aborts the jax trace before any
        program is compiled, so counting it would make counts() lie
        about the real compile set — /stats would overreport programs,
        and assert_within_budget() would fail permanently on an engine
        that survived (and kept serving past) one rejected leak."""
        n = None
        with self._lock:
            b = self._budgets.setdefault(name, _Budget(name, 0))
            if self._frozen:
                raise CompileBudgetExceeded(
                    f"retrace of {name!r} (would be trace "
                    f"#{b.traces + 1}) inside a frozen registry: the "
                    "compile set was declared complete (e.g. post-warmup "
                    "serving), so some input left the closed shape/dtype "
                    "set")
            if b.traces + 1 > b.max_traces:
                attempt, budget = b.traces + 1, b.max_traces
            else:
                b.traces += 1
                n = b.traces
        if n is not None:
            # Accepted trace: mirror into the process-global metric
            # registry so a Prometheus scrape sees compiles process-wide
            # (per-engine views stay on each engine's own registry).
            # Compiles are rare by contract, so this is never hot.
            _obs_counter("compile_traces_total",
                         "Accepted jit traces, by guarded program name "
                         "(every budget registry in the process).",
                         name).inc()
            return n
        raise CompileBudgetExceeded(
            f"{name!r} would trace {attempt} times, budget {budget}: a "
            "call-site input is specializing the trace (unbucketed "
            "shape, Python scalar operand, or changed pytree "
            "structure). Find the leak with `python -m "
            "nanosandbox_tpu.analysis` (nonstatic-shape rule) or "
            "raise the budget if the compile set legitimately grew.")

    # ------------------------------------------------------------- queries

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {n: b.traces for n, b in self._budgets.items()}

    def budgets(self) -> Dict[str, int]:
        with self._lock:
            return {n: b.max_traces for n, b in self._budgets.items()}

    def assert_within_budget(self) -> None:
        """Re-check every budget (bump already enforces; this is the
        test-suite's one-line postcondition)."""
        with self._lock:
            over = [(b.name, b.traces, b.max_traces)
                    for b in self._budgets.values()
                    if b.traces > b.max_traces]
        if over:
            raise CompileBudgetExceeded(
                "; ".join(f"{n!r}: {t} traces > budget {m}"
                          for n, t, m in over))

    @contextmanager
    def frozen(self):
        """Inside this context ANY new trace raises — the post-warmup
        serving contract: /healthz went green meaning every program is
        compiled, so a compile after that point is a shape leak eating
        a live request's latency."""
        with self._lock:
            prev, self._frozen = self._frozen, True
        try:
            yield self
        finally:
            with self._lock:
                self._frozen = prev


# Module-level convenience for code without a natural registry owner.
_GLOBAL = TraceBudgetRegistry()


def compile_budget(name: str, max_traces: int, *,
                   registry: Optional[TraceBudgetRegistry] = None,
                   ) -> Callable[[Callable], Callable]:
    """``@compile_budget("step", 1)`` on the function handed to jax.jit:
    raises CompileBudgetExceeded past ``max_traces`` traces. Uses the
    process-global registry unless one is passed."""
    return (registry or _GLOBAL).guard(name, max_traces)


def global_registry() -> TraceBudgetRegistry:
    return _GLOBAL


# ------------------------------------------------------- host-sync ledger

_sync_lock = threading.Lock()
_sync_counts: Dict[str, int] = {}


def host_sync(name: str, value=None) -> Optional[float]:
    """The BLESSED deliberate device->host readback: reads ``value``
    back as a Python float (the hard sync some PJRT transports need
    where block_until_ready is a no-op — see utils/benchmarking.py) and
    counts the sync under ``name`` so windows can be audited. jaxlint's
    host-sync rule recognizes this call and does not flag it; a raw
    float()/np.asarray in a hot path does get flagged."""
    with _sync_lock:
        _sync_counts[name] = _sync_counts.get(name, 0) + 1
    # Mirror into the process-global metric registry: /metrics carries
    # host_syncs_total{name=...} so "did serving start syncing?" is a
    # scrape query, not a log grep. Deliberate syncs are rare (that is
    # the point of the ledger), so this path is never hot.
    _obs_counter("host_syncs_total",
                 "Deliberate device->host readbacks through the blessed "
                 "tracecheck.host_sync wrapper, by ledger name.",
                 name).inc()
    if value is None:
        return None
    return float(value)


def sync_counts() -> Dict[str, int]:
    with _sync_lock:
        return dict(_sync_counts)


def sync_delta(mark: Dict[str, int]) -> Dict[str, int]:
    """Per-kind ledger growth since ``mark`` (a prior ``sync_counts()``
    snapshot), positive entries only — the "how many syncs did this
    window contain" computation both profiler windows (train.py's
    --profile_steps and the serve engine's POST /profile) report."""
    return {k: v - mark.get(k, 0) for k, v in sync_counts().items()
            if v - mark.get(k, 0) > 0}


def sync_count(name: Optional[str] = None) -> int:
    """Total recorded deliberate host syncs (or just ``name``'s) —
    train.py snapshots this around the profiler window to report how
    many syncs the traced region contained."""
    with _sync_lock:
        if name is not None:
            return _sync_counts.get(name, 0)
        return sum(_sync_counts.values())
