"""Shared train-step throughput measurement for bench.py / perf_sweep.

Pipelined timing: enqueue all timed iters, sync once at the end. This is
what the real train loop achieves under JAX async dispatch (it only reads
a scalar back every log_interval); a per-step readback would charge every
step a host<->device round trip — on a tunneled PJRT transport that RTT
is ~100ms+ and would understate sustained throughput by ~2x.
"""

from __future__ import annotations

import time


def measure_train_throughput(cfg, warmup: int, iters: int) -> dict:
    """Train `warmup + iters` steps of cfg's model; returns step_ms,
    tokens_per_sec_per_chip, mfu, and the last loss."""
    import jax

    from nanosandbox_tpu.train import Trainer

    if warmup < 1:
        # The hard-sync below reads the last warmup step's metrics; with
        # no warmup there is nothing to sync on and t0 would include
        # compilation.
        raise ValueError("measure_train_throughput requires warmup >= 1")

    trainer = Trainer(cfg)
    state = trainer.init_state()
    train_step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=True)
    rng = trainer.train_rng(0)
    try:
        for _ in range(warmup):
            xb, yb = next(loader)
            state, m = train_step(state, trainer.to_global(xb),
                                  trainer.to_global(yb), rng)
        # jaxlint: disable=host-sync -- the warmup fence the timing needs
        float(m["loss"])  # hard sync: some PJRT transports make
        # block_until_ready a no-op; a scalar readback always waits.

        t0 = time.perf_counter()
        for _ in range(iters):
            xb, yb = next(loader)
            state, m = train_step(state, trainer.to_global(xb),
                                  trainer.to_global(yb), rng)
        # jaxlint: disable=host-sync -- the stop-the-clock drain being measured
        loss = float(m["loss"])
        step_s = (time.perf_counter() - t0) / iters
    finally:
        loader.close()

    n_chips = len(jax.devices())
    return {
        "step_ms": round(step_s * 1000, 2),
        "tokens_per_sec_per_chip": round(
            cfg.tokens_per_iter / step_s / n_chips, 1),
        "mfu": round(trainer.flops_per_iter() / step_s
                     / trainer.peak_flops(), 4),
        "loss": round(loss, 4),
        # Provenance: the value the measured Trainer ACTUALLY resolved
        # (auto chunk depends on per-device batch/mesh — reporting it from
        # the source keeps sweep artifacts honest, perf_sweep autoconfig).
        "resolved_loss_chunk_size": trainer.loss_chunk_size,
    }
