"""schedcheck — deterministic schedule-fuzz harness for the serve host.

The runtime half of lockcheck, in the tracecheck tradition (a static
pass paired with a dynamic witness): where lockcheck PROVES properties
of the source, schedcheck tries to BREAK them on a live object graph —

  * every engine/fleet/router lock is replaced by a seeded-preemption
    instrumented wrapper that (a) asserts the committed lock order of
    ``budgets/lock_order.json`` at every acquisition and (b) injects a
    tiny sleep with seeded probability right before acquiring, forcing
    the cross-thread interleavings a quiet CI box would never hit;
  * ``sys.setswitchinterval`` is dropped to microseconds for the fuzz
    window, so iterate-while-mutate races ("dictionary changed size
    during iteration") become reliably reproducible instead of
    one-in-a-million;
  * drivers pump concurrent submit/step/stats/drain/debug traffic
    through Engine+EngineLoop, Fleet, PrefixAffinityRouter, and
    DisaggPair under many seeds, recording every violation and every
    crashed thread as data (``Violation``), never as a test-framework
    accident.

Violations collected: ``order`` (acquired an earlier-tier lock while
holding a later-tier one), ``crash`` (a driver thread died — the
dynamic signature of an unguarded shared structure). ``assert_clean()``
raises with the full list. The instrumentation is pure host Python:
zero new compiled programs, zero new audited host syncs (pinned by
test against trace_counts/max_programs and the sync ledger).

CLI smoke: ``python -m nanosandbox_tpu.utils.schedcheck --target=router
--seeds=20`` (router target is jax-free; ``engine`` builds a tiny CPU
model).
"""

from __future__ import annotations

import contextlib
import json
import random
import sys
import threading
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Dict, List, Optional, Sequence, Tuple

DEFAULT_LOCK_ORDER = "budgets/lock_order.json"


def load_order(path: str = DEFAULT_LOCK_ORDER) -> Dict[str, int]:
    """lock name -> tier index from the committed ordering file (the
    same file lockcheck's lock-order-inversion rule enforces)."""
    data = json.loads(Path(path).read_text(encoding="utf-8"))
    tiers = list(data.get("order", ()))
    return {lock: tiers.index(tier)
            for lock, tier in data.get("locks", {}).items()}


@dataclass
class Violation:
    kind: str        # "order" | "crash"
    detail: str
    thread: str
    seed: int


@dataclass
class SchedCheck:
    """One fuzz run's state: seeded preemption, per-thread held-lock
    stacks, order assertions, violation collection."""
    seed: int = 0
    order: Dict[str, int] = field(default_factory=dict)
    preempt_p: float = 0.05
    max_preempt_s: float = 0.0005

    def __post_init__(self):
        self._tls = threading.local()
        # Meta-lock for the shared violation list and counters — a
        # plain stdlib lock on purpose: the harness must not instrument
        # (and thereby fuzz) its own bookkeeping.
        self._meta = threading.Lock()
        self.violations: List[Violation] = []
        self.preemptions = 0
        self.acquires = 0

    # ------------------------------------------------------- thread state
    def _held(self) -> List[str]:
        if not hasattr(self._tls, "held"):
            self._tls.held = []
        return self._tls.held

    def _rng(self) -> random.Random:
        if not hasattr(self._tls, "rng"):
            # Deterministic per-thread stream: same seed + same thread
            # name -> same preemption schedule.
            name = threading.current_thread().name
            self._tls.rng = random.Random(f"{self.seed}:{name}")
        return self._tls.rng

    # ---------------------------------------------------------- recording
    def record(self, kind: str, detail: str) -> None:
        with self._meta:
            self.violations.append(Violation(
                kind=kind, detail=detail,
                thread=threading.current_thread().name, seed=self.seed))

    def note_acquire(self, name: str) -> None:
        """Called by instrumented locks right before acquiring: seeded
        preemption + committed-order assertion."""
        rng = self._rng()
        if rng.random() < self.preempt_p:
            with self._meta:
                self.preemptions += 1
            time.sleep(rng.random() * self.max_preempt_s)
        held = self._held()
        with self._meta:
            self.acquires += 1
        tier = self.order.get(name)
        if tier is None:
            return
        for h in held:
            if h == name:        # RLock re-entry: same lock, no edge
                continue
            ht = self.order.get(h)
            if ht is not None and ht > tier:
                self.record(
                    "order",
                    f"acquiring '{name}' (tier {tier}) while holding "
                    f"'{h}' (tier {ht}) — inverts the committed order")

    def push(self, name: str) -> None:
        self._held().append(name)

    def pop(self, name: str) -> None:
        held = self._held()
        if name in held:
            # Remove the most recent entry (RLock re-entries stack).
            for i in range(len(held) - 1, -1, -1):
                if held[i] == name:
                    del held[i]
                    break

    # ------------------------------------------------------------ results
    def assert_clean(self) -> None:
        if self.violations:
            lines = [f"  [{v.kind}] {v.thread} (seed {v.seed}): "
                     f"{v.detail}" for v in self.violations]
            raise AssertionError(
                f"schedcheck: {len(self.violations)} violation(s):\n"
                + "\n".join(lines))

    def export_metrics(self, registry) -> None:
        """Publish the run onto an obs.MetricRegistry (obs_smoke
        scrapes these next to lockcheck_findings_total)."""
        registry.gauge(
            "schedcheck_violations_total",
            "Lock-order/crash violations in the last schedcheck run."
        ).set(len(self.violations))
        registry.gauge(
            "schedcheck_preemptions_total",
            "Seeded preemptions injected in the last schedcheck run."
        ).set(self.preemptions)
        registry.gauge(
            "schedcheck_acquires_total",
            "Instrumented lock acquisitions in the last schedcheck run."
        ).set(self.acquires)


class _InstrumentedLock:
    """Wraps a Lock/RLock/Condition: order-asserts + seeded-preempts on
    every acquisition, delegates everything else (wait/notify/...) to
    the wrapped object — EngineLoop's Condition keeps its semantics."""

    def __init__(self, inner, name: str, check: SchedCheck):
        self._inner = inner
        self._name = name
        self._check = check

    def acquire(self, *a, **kw):
        self._check.note_acquire(self._name)
        got = self._inner.acquire(*a, **kw)
        if got:
            self._check.push(self._name)
        return got

    def release(self):
        self._inner.release()
        self._check.pop(self._name)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __getattr__(self, attr):
        # Condition.wait releases + reacquires the UNDERLYING lock
        # internally; the held stack keeps the entry, which is correct:
        # order-wise the thread still "owns" the region.
        return getattr(self._inner, attr)


def wrap_lock(owner, attr: str, name: str, check: SchedCheck) -> None:
    """Replace ``owner.attr`` with an instrumented wrapper (idempotent:
    re-wrapping an already-instrumented lock is a no-op; a missing
    attribute is skipped so the fuzz drivers still run against objects
    that lost a lock — which is exactly the regression they exist to
    crash on)."""
    inner = getattr(owner, attr, None)
    if inner is None:
        return
    if isinstance(inner, _InstrumentedLock):
        # Re-instrumenting (a fixture reused across seeds): keep the
        # wrapper, point it at this run's collector.
        inner._check = check
        return
    setattr(owner, attr, _InstrumentedLock(inner, name, check))


# --------------------------------------------------------- instrumenters

def instrument_router(router, check: SchedCheck) -> None:
    wrap_lock(router, "_lock", "PrefixAffinityRouter._lock", check)


def instrument_engine(engine, check: SchedCheck) -> None:
    wrap_lock(engine, "_profile_lock", "Engine._profile_lock", check)
    wrap_lock(engine.flight, "_lock", "FlightRecorder._lock", check)
    wrap_lock(engine.tracer, "_lock", "SpanTracer._lock", check)


def instrument_engine_loop(loop, check: SchedCheck) -> None:
    wrap_lock(loop, "_cond", "EngineLoop._cond", check)
    instrument_engine(loop.engine, check)


def instrument_fleet(fleet, check: SchedCheck) -> None:
    instrument_router(fleet.router, check)
    wrap_lock(fleet.flight, "_lock", "FlightRecorder._lock", check)
    for eng in fleet.replicas.values():
        instrument_engine(eng, check)


def instrument_disagg(pair, check: SchedCheck) -> None:
    wrap_lock(pair.flight, "_lock", "FlightRecorder._lock", check)
    for eng in (pair.prefill, pair.decode):
        instrument_engine(eng, check)


@contextlib.contextmanager
def tight_switch_interval(interval: float = 5e-6):
    """Shrink the GIL switch interval for the fuzz window so structural
    races (iterate vs. mutate) surface reliably, then restore it."""
    prev = sys.getswitchinterval()
    sys.setswitchinterval(interval)
    try:
        yield
    finally:
        sys.setswitchinterval(prev)


# ---------------------------------------------------------------- drivers

def _run_threads(check: SchedCheck,
                 targets: Sequence[Tuple[str, Callable[[], None]]],
                 join_timeout: float = 60.0) -> None:
    """Run the driver callables concurrently; any exception in any
    thread becomes a ``crash`` violation on ``check``."""

    def guard(name, fn):
        def run():
            try:
                fn()
            except Exception as e:  # noqa: BLE001 — ALL crashes are data
                check.record("crash", f"{type(e).__name__}: {e}")
        return threading.Thread(target=run, name=name, daemon=True)

    threads = [guard(name, fn) for name, fn in targets]
    with tight_switch_interval():
        for t in threads:
            t.start()
        for t in threads:
            t.join(join_timeout)
            if t.is_alive():
                check.record("crash", f"thread {t.name} failed to "
                                      f"finish within {join_timeout}s")


def fuzz_router(seed: int, *, n_replicas: int = 4, iters: int = 300,
                order: Optional[Dict[str, int]] = None) -> SchedCheck:
    """Concurrent route/update/flap/stats traffic through one
    PrefixAffinityRouter — the pure-host, jax-free target. Pre-lock
    this crashed with 'dictionary changed size during iteration'
    within a handful of seeds."""
    from nanosandbox_tpu.serve.router import (NoReadyReplicaError,
                                              PrefixAffinityRouter)

    check = SchedCheck(seed=seed, order=order if order is not None
                       else _try_order())
    names = [f"r{i}" for i in range(n_replicas)]
    router = PrefixAffinityRouter(names, page=16)
    instrument_router(router, check)
    rng = random.Random(seed)
    chains = [[f"d{g}-{j}" for j in range(1 + g % 4)] for g in range(8)]
    for name in names:
        router.update_replica(name, ready=True)

    def route_loop():
        r = random.Random(seed + 1)
        for i in range(iters):
            try:
                router.route(chains[r.randrange(len(chains))],
                             failover=(i % 17 == 0))
            except NoReadyReplicaError:
                pass          # flapper may have emptied the ready set
            router.match_tokens(names[i % n_replicas],
                                chains[i % len(chains)])

    def update_loop():
        r = random.Random(seed + 2)
        for i in range(iters):
            name = names[r.randrange(n_replicas)]
            router.update_replica(
                name, ready=(r.random() > 0.1),
                queued=r.randrange(8), active=r.randrange(4),
                brownout=r.randrange(3))
            router.observe_digests(name,
                                   chains[r.randrange(len(chains))])

    def refresh_loop():
        r = random.Random(seed + 3)
        for i in range(iters):
            name = names[r.randrange(n_replicas)]
            router.refresh_summary(
                name, chains[r.randrange(len(chains))])
            if i % 13 == 0:
                router.forget(name)

    def flap_loop():
        r = random.Random(seed + 4)
        for i in range(iters):
            extra = f"extra{r.randrange(3)}"
            if r.random() < 0.5:
                router.add_replica(extra)
                router.update_replica(extra, ready=True)
            else:
                router.remove_replica(extra)

    def stats_loop():
        for _ in range(iters):
            router.stats()
            router.ready_replicas()

    _run_threads(check, [("route", route_loop), ("update", update_loop),
                         ("refresh", refresh_loop), ("flap", flap_loop),
                         ("stats", stats_loop)])
    rng.random()             # keep rng referenced (symmetry with docs)
    return check


def fuzz_engine_loop(loop, seed: int, *, n_requests: int = 4,
                     budget: int = 3, vocab: int = 50,
                     order: Optional[Dict[str, int]] = None,
                     reader_iters: int = 60) -> SchedCheck:
    """Concurrent submit + debug-view + stats traffic through a RUNNING
    EngineLoop (caller owns loop.start()/loop.stop()): the handler-
    thread traffic pattern, with prefix_summary marshalled through
    loop.call exactly as the HTTP handler now does."""
    check = SchedCheck(seed=seed, order=order if order is not None
                       else _try_order())
    instrument_engine_loop(loop, check)
    rng = random.Random(seed)
    prompts = [[rng.randrange(vocab) for _ in range(4 + 3 * i)]
               for i in range(n_requests)]

    def submit_loop():
        pending = [loop.submit(prompt=p, max_new_tokens=budget)
                   for p in prompts]
        for p in pending:
            if not p.done.wait(60):
                raise TimeoutError("request did not finish under fuzz")
            if p.error is not None:
                raise p.error

    def debug_loop():
        eng = loop.engine
        for i in range(reader_iters):
            loop.stats()
            eng.stats()
            eng.debug_slots()
            eng.debug_scheduler()
            eng.debug_kvpool()
            if i % 5 == 0:
                try:
                    loop.call(lambda e: e.prefix_summary(), timeout=30)
                except RuntimeError:
                    pass      # loop already stopped at tail of fuzz

    def flight_loop():
        for _ in range(reader_iters):
            loop.engine.flight.events()
            loop.engine.flight.counts()
            loop.engine.tracer.export_chrome()

    _run_threads(check, [("submit", submit_loop),
                         ("debug", debug_loop),
                         ("flight", flight_loop)], join_timeout=120.0)
    return check


def fuzz_fleet(fleet, seed: int, *, n_requests: int = 4, budget: int = 3,
               vocab: int = 50,
               order: Optional[Dict[str, int]] = None,
               reader_iters: int = 80) -> SchedCheck:
    """One stepping thread (the fleet's single-threaded contract) vs.
    concurrent stats/merged-ledger/router readers."""
    check = SchedCheck(seed=seed, order=order if order is not None
                       else _try_order())
    instrument_fleet(fleet, check)
    rng = random.Random(seed)
    shared = [rng.randrange(vocab) for _ in range(18)]
    prompts = [shared + [rng.randrange(vocab) for _ in range(1 + i)]
               if i % 2 == 0
               else [rng.randrange(vocab) for _ in range(5 + 2 * i)]
               for i in range(n_requests)]

    def step_loop():
        for p in prompts:
            fleet.submit(p, budget)
        while fleet.has_work():
            fleet.step()

    def stats_loop():
        for _ in range(reader_iters):
            fleet.stats()
            fleet.retry_after_s()
            fleet.router.stats()

    def ledger_loop():
        for _ in range(reader_iters):
            fleet.merged_flight_events()

    _run_threads(check, [("step", step_loop), ("stats", stats_loop),
                         ("ledger", ledger_loop)], join_timeout=120.0)
    return check


def fuzz_disagg(pair, seed: int, *, n_requests: int = 4, budget: int = 3,
                vocab: int = 50,
                order: Optional[Dict[str, int]] = None,
                reader_iters: int = 80) -> SchedCheck:
    """One migration-pump stepping thread vs. concurrent stats and
    merged-ledger readers on a DisaggPair."""
    check = SchedCheck(seed=seed, order=order if order is not None
                       else _try_order())
    instrument_disagg(pair, check)
    rng = random.Random(seed)
    prompts = [[rng.randrange(vocab) for _ in range(5 + 3 * i)]
               for i in range(n_requests)]

    def step_loop():
        for i, p in enumerate(prompts):
            pair.submit(p, budget, temperature=0.0, seed=seed + i)
        while pair.has_work():
            pair.step()

    def stats_loop():
        for _ in range(reader_iters):
            pair.stats()
            pair.retry_after_s()

    def ledger_loop():
        for _ in range(reader_iters):
            pair.merged_flight_events()

    _run_threads(check, [("step", step_loop), ("stats", stats_loop),
                         ("ledger", ledger_loop)], join_timeout=120.0)
    return check


def _try_order() -> Dict[str, int]:
    try:
        return load_order()
    except (OSError, ValueError):
        return {}


# -------------------------------------------------------------------- CLI

def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="python -m nanosandbox_tpu.utils.schedcheck",
        description="Seeded schedule-fuzz smoke over the serve host "
                    "locks (runtime half of lockcheck).")
    ap.add_argument("--target", choices=("router", "engine"),
                    default="router",
                    help="router = jax-free PrefixAffinityRouter fuzz; "
                         "engine = tiny CPU EngineLoop fuzz")
    ap.add_argument("--seeds", type=int, default=20)
    ap.add_argument("--lock-order", default=None, metavar="FILE")
    args = ap.parse_args(argv)

    order = (load_order(args.lock_order) if args.lock_order
             else _try_order())
    loop = _tiny_loop() if args.target == "engine" else None
    bad = 0
    total_pre = 0
    try:
        for seed in range(args.seeds):
            if args.target == "router":
                check = fuzz_router(seed, order=order)
            else:
                check = fuzz_engine_loop(loop, seed, order=order)
            total_pre += check.preemptions
            if check.violations:
                bad += 1
                for v in check.violations:
                    print(f"seed {seed}: [{v.kind}] {v.thread}: "
                          f"{v.detail}", file=sys.stderr)
    finally:
        if loop is not None:
            loop.stop()
            loop.join(30)
    print(f"schedcheck: {args.seeds} seed(s), target={args.target}, "
          f"{total_pre} preemption(s) injected, "
          f"{bad} seed(s) with violations")
    return 1 if bad else 0


def _tiny_loop():
    """One started EngineLoop over the standard 2-layer CPU test model,
    shared across every CLI seed (the compile cost dominates; the fuzz
    re-instruments the same locks per seed)."""
    import jax
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT
    from nanosandbox_tpu.serve import Engine
    from nanosandbox_tpu.serve.http import EngineLoop

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=32, block_size=64,
                    vocab_size=50, dropout=0.0,
                    compute_dtype="float32", attention_impl="xla")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    eng = Engine(model, params, num_slots=2, max_len=64, paged=True)
    loop = EngineLoop(eng)
    loop.start()
    return loop


if __name__ == "__main__":
    sys.exit(main())
