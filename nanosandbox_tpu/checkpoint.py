"""Checkpoint save/restore via Orbax, keeping the reference's /data contract.

The reference delegates checkpointing to nanoGPT's ``out_dir`` torch.save
(SURVEY.md §5 checkpoint/resume; --out_dir at ipynb:72,109), persisted on
the PVC at /data so pod restarts resume (README.md:76, 96-97). Here the
same layout contract holds — checkpoints under <out_dir>/ckpt — but the
mechanism is Orbax multi-host array checkpointing: every host participates
in save/restore of sharded arrays (vs. rank-0 torch.save), which is the
only correct scheme once params are FSDP-sharded.
"""

from __future__ import annotations

import os
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp


def _manager(out_dir: str, keep: int = 3) -> ocp.CheckpointManager:
    ckpt_dir = os.path.abspath(os.path.join(out_dir, "ckpt"))
    os.makedirs(ckpt_dir, exist_ok=True)
    options = ocp.CheckpointManagerOptions(max_to_keep=keep, create=True)
    return ocp.CheckpointManager(ckpt_dir, options=options)


class Checkpointer:
    """Thin wrapper: save(step, state, extra) / restore latest."""

    def __init__(self, out_dir: str, keep: int = 3):
        self.out_dir = out_dir
        self.mgr = _manager(out_dir, keep)

    def save(self, step: int, state: Any, extra: dict | None = None,
             wait: bool = False) -> None:
        if step in (self.mgr.all_steps() or []):
            # Resume re-evals at the restored step; don't re-save. Say so
            # once — a resumed run that never logs a save otherwise looks
            # like checkpointing silently stopped (the repeats stay quiet:
            # every eval_interval hit would re-trigger this).
            from nanosandbox_tpu.utils.metrics import warn_once
            warn_once(f"ckpt-skip:{self.out_dir}",
                      f"[checkpoint] step {step} already exists under "
                      f"{self.out_dir}/ckpt; skipping save (expected once "
                      "right after --init_from=resume)")
            return
        args = {"state": ocp.args.StandardSave(state)}
        if extra is not None:
            args["extra"] = ocp.args.JsonSave(extra)
        self.mgr.save(step, args=ocp.args.Composite(**args))
        if wait:
            self.mgr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self.mgr.latest_step()

    def restore(self, abstract_state: Any, step: int | None = None
                ) -> tuple[Any, dict]:
        step = step if step is not None else self.mgr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint in {self.out_dir}/ckpt")
        try:
            restored = self.mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state),
                    extra=ocp.args.JsonRestore(),
                ),
            )
        except KeyError:  # checkpoint saved without an "extra" item
            restored = self.mgr.restore(
                step,
                args=ocp.args.Composite(
                    state=ocp.args.StandardRestore(abstract_state)),
            )
        extra = restored.get("extra") or {}
        return restored["state"], dict(extra)

    def close(self) -> None:
        self.mgr.wait_until_finished()
        self.mgr.close()


def abstract_like(state: Any) -> Any:
    """ShapeDtypeStruct tree (with shardings) for restore-into-sharded."""
    def conv(x):
        if isinstance(x, jax.Array):
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if isinstance(x, np.ndarray):
            return jax.ShapeDtypeStruct(x.shape, x.dtype)
        return x
    return jax.tree.map(conv, state)
