"""`python -m nanosandbox_tpu.serve` — serve a trained checkpoint.

Restores the latest checkpoint under --out_dir (the same
restore_for_inference dance sample.py uses), casts params to the
serving dtype, and exposes the continuous-batching engine over HTTP:

    python -m nanosandbox_tpu.serve --out_dir=out --port=8000 &
    curl -s localhost:8000/generate -d '{"prompt": "ROMEO:", \
        "max_new_tokens": 64, "temperature": 0.8, "top_k": 40}'
    curl -s localhost:8000/metrics            # Prometheus exposition
    curl -s 'localhost:8000/trace?rid=0'      # Perfetto-loadable trace
    curl -s localhost:8000/profile -d '{"steps": 50}'   # profiler window
"""

from __future__ import annotations

import argparse
import contextlib
import sys


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(prog="python -m nanosandbox_tpu.serve")
    ap.add_argument("--router", action="store_true",
                    help="run the FLEET ROUTER front tier instead of an "
                         "engine replica (ISSUE 15): an asyncio proxy "
                         "routing POST /generate across --replicas by "
                         "radix-prefix affinity with health/load "
                         "fallback and failover re-routing. Loads no "
                         "checkpoint and touches no accelerator — the "
                         "k8s router Deployment runs exactly this")
    ap.add_argument("--replicas", default="",
                    help="router mode: comma-separated replica base "
                         "URLs (http://host:port), or a "
                         "dns+http://name:port spec resolved every "
                         "health interval — point it at the headless "
                         "Service (serve-replicas.disttrain) and the "
                         "rotation tracks pod scale-up/down and "
                         "readiness automatically")
    ap.add_argument("--health_interval_s", type=float, default=2.0,
                    help="router mode: seconds between per-replica "
                         "health + load + prefix-summary polls; a "
                         "draining/dead replica leaves rotation within "
                         "one interval")
    ap.add_argument("--router_page", type=int, default=16,
                    help="router mode: KV page size the replicas run "
                         "(must match their --kv_page_size, or prefix "
                         "fingerprints will never match)")
    ap.add_argument("--no_affinity", action="store_true",
                    help="router mode: disable prefix-affinity scoring "
                         "(pure least-loaded routing — the comparison "
                         "baseline, and the right mode for dense or "
                         "cache-less replicas)")
    ap.add_argument("--out_dir", default="out")
    ap.add_argument("--data_dir", default="data")
    ap.add_argument("--dataset", default="shakespeare_char")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=8000)
    ap.add_argument("--num_slots", type=int, default=8,
                    help="concurrent request capacity (decode batch rows)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel degree: shard ONE engine over "
                         "the first N devices — Megatron weight "
                         "placements, the KV pool (and its scale "
                         "planes) row-sharded along heads over the "
                         "``model`` mesh axis, slot state replicated. "
                         "Greedy outputs are token-identical to tp=1; "
                         "the comms contract is CI-pinned in "
                         "budgets/serve_tp_cpu8.json and exported on "
                         "/metrics at startup (serve_tp_degree + "
                         "serve_collective_bytes_per_token). Requires "
                         "n_head %% tp == 0 and N local devices; 1 = "
                         "the single-chip engine, unchanged")
    ap.add_argument("--max_len", type=int, default=0,
                    help="per-slot KV length; 0 = block_size")
    ap.add_argument("--device", default="auto")
    ap.add_argument("--no_pipeline", action="store_true",
                    help="synchronous decode loop (debugging baseline); "
                         "default keeps one decode step in flight")
    ap.add_argument("--prefill_chunk", type=int, default=0,
                    help="per-step prefill token budget (must be one of "
                         "the prefill buckets; 0 = off): admission "
                         "waves are paced and long prompts split into "
                         "chunk-sized prefills interleaved with decode "
                         "steps, so a prefill storm cannot spike active "
                         "requests' TPOT. Paged engines only for the "
                         "splitting half; the compile set does not grow")
    ap.add_argument("--no_preemption", action="store_true",
                    help="disable deadline-driven preemption-by-"
                         "eviction (default on: when the highest-"
                         "priority queued request would miss its "
                         "deadline waiting on slots/KV blocks, the "
                         "lowest-priority active request is evicted — "
                         "its blocks donate to the prefix cache and it "
                         "resumes token-identically as a prefix hit)")
    ap.add_argument("--brownout", default="on", choices=("on", "off"),
                    help="SLO-driven brownout degradation ladder "
                         "(default on): under sustained deadline burn "
                         "the engine steps through shrink-scan -> "
                         "suspend-spec -> shed-batch -> interactive-"
                         "only, with hysteresis; each transition is a "
                         "flight/metrics event. Costs nothing without "
                         "deadlines")
    ap.add_argument("--scan_k", type=int, default=1,
                    help="decode steps fused into one compiled dispatch "
                         "(lax.scan megaprogram ladder): the host "
                         "dispatches once per up-to-k tokens instead of "
                         "once per token, finish detection lags the "
                         "chunk. 1 = the classic per-token loop; "
                         "ignored under --spec (the verify readback "
                         "gates the next frontier)")
    ap.add_argument("--paged", default="on", choices=("on", "off"),
                    help="block-paged KV pool + radix prefix cache "
                         "(default on): admission reserves each "
                         "request's actual block need instead of a "
                         "worst-case max_len row, and prompts sharing "
                         "a resident prefix skip its prefill chunks. "
                         "'off' restores the dense per-slot rows")
    ap.add_argument("--role", default="both",
                    choices=("both", "prefill", "decode"),
                    help="disaggregated serving tier (ISSUE 16): "
                         "'prefill' pods run chunked prefill waves and "
                         "export {block chain, first token, seed} as a "
                         "202 on migrate-flagged /generate; 'decode' "
                         "pods adopt them via /internal/adopt with zero "
                         "prefill dispatches (and warm only the admit/"
                         "decode programs — the strict-subset compile "
                         "set). 'both' (default) is classic colocated "
                         "serving. The router frontend discovers the "
                         "role from /stats and phase-tiers routing "
                         "when both tiers are ready")
    ap.add_argument("--kv_page_size", type=int, default=16,
                    help="positions per KV block (paged pool); int8 "
                         "pools on real TPUs want >= 32 (sublane "
                         "tiling quantum)")
    ap.add_argument("--kv_pool_blocks", type=int, default=0,
                    help="paged pool size in blocks; 0 = num_slots * "
                         "max_len / page (byte-identical to the dense "
                         "pool)")
    ap.add_argument("--no_prefix_cache", action="store_true",
                    help="disable radix prefix reuse (paged pool only)")
    ap.add_argument("--kv_dtype", default=None,
                    choices=("fp32", "bf16", "int8", "int4"),
                    help="KV-pool storage mode (default: the serving "
                         "compute dtype). int8 stores per-position "
                         "scales alongside the values: ~2x less HBM per "
                         "cached token than bf16, so 2x the slots at "
                         "constant HBM and ~2x less decode read traffic. "
                         "int4 packs two nibbles per byte (same scale "
                         "format): ~2x int8's slot capacity again, at "
                         "a coarser 4-bit quantization grid")
    ap.add_argument("--decode_impl", default=None,
                    choices=("auto", "pallas", "pallas_interpret", "xla"),
                    help="cached-decode attention impl (flash-decode "
                         "ladder, ops/flash_decode.py); 'auto' probes "
                         "the Pallas kernel and warn_once-falls back to "
                         "xla. The resolved impl is exported on /metrics")
    ap.add_argument("--spec", default="off",
                    help="speculative decoding: 'ngram' (prompt-lookup "
                         "drafting) or 'model:<out_dir>' (smaller "
                         "same-tokenizer draft checkpoint); up to "
                         "spec_k+1 tokens per target forward, greedy "
                         "outputs unchanged (forces the synchronous "
                         "loop)")
    ap.add_argument("--spec_k", type=int, default=4,
                    help="draft tokens per verify step (--spec only)")
    ap.add_argument("--shardcheck_budget", default=None,
                    help="shardcheck comms budget to export as "
                         "shardcheck_collectives_total{program=,kind=} "
                         "gauges on /metrics at startup (the pinned "
                         "comms contract this engine runs under); "
                         "default budgets/serve_cpu8.json, skipped "
                         "silently when absent — an EXPLICIT path must "
                         "exist; '' disables")
    ap.add_argument("--deadline_s", type=float, default=0.0,
                    help="default per-request SLO deadline in seconds "
                         "(submit -> finish), applied to requests that "
                         "send none; 0 = best-effort. Deadline-carrying "
                         "requests land in the SLO ledger "
                         "(serve_slo_* + serve_goodput_tokens_total on "
                         "/metrics) and are SHED from the queue once "
                         "expired (finish_reason 'shed')")
    ap.add_argument("--watchdog_dir", default=None,
                    help="directory for anomaly-watchdog dumps (flight "
                         "ledger + span ring + stats snapshot per "
                         "trip); default: a tempdir created on the "
                         "first trip")
    ap.add_argument("--no_watchdogs", action="store_true",
                    help="disable the anomaly watchdogs (TTFT spike, "
                         "admission stall, pool thrash, post-warmup "
                         "retrace, stuck slot)")
    ap.add_argument("--faults", default=None,
                    help="arm a deterministic fault-injection plan "
                         "(serve/faults.py) for chaos drills: "
                         "'site@step[xN][:param]' entries comma-"
                         "separated, or a canned plan name "
                         "('chaos-smoke', 'chaos-full'). Steps are "
                         "relative to the END of warmup. NEVER default "
                         "on: production pays zero cost without it")
    ap.add_argument("--no_recovery", action="store_true",
                    help="disable the crash-safe engine supervisor "
                         "(quarantine + device-state rebuild + "
                         "re-admission on poisoned steps/watchdog "
                         "trips/dispatch crashes); without it a "
                         "dispatch crash kills the serving loop and a "
                         "persistently poisoned row terminates "
                         "'failed' after 3 strikes instead of "
                         "recovering")
    ap.add_argument("--warmup", choices=("full", "buckets"), default="full",
                    help="'full' compiles every (wave-size, bucket) "
                         "prefill pair before binding the port (the "
                         "/healthz readiness contract); 'buckets' "
                         "compiles one single-request prefill per bucket "
                         "and leaves larger waves to compile lazily")
    args = ap.parse_args(argv if argv is not None else sys.argv[1:])

    if args.router:
        # Front-tier mode: no checkpoint, no jax — just the router
        # proxy over the replica fleet.
        from nanosandbox_tpu.serve.http import RouterFrontend

        replicas = [u for u in args.replicas.split(",") if u.strip()]
        if not replicas:
            raise SystemExit("--router needs --replicas=<url,url,...> "
                             "or --replicas=dns+http://name:port")
        fe = RouterFrontend(
            replicas, host=args.host, port=args.port,
            page=args.router_page,
            health_interval_s=args.health_interval_s,
            affinity=not args.no_affinity).start()
        print(f"[serve-router] routing {replicas} "
              f"(affinity={'off' if args.no_affinity else 'on'}, "
              f"page={args.router_page}, health every "
              f"{args.health_interval_s}s); listening on "
              f"{args.host}:{fe.port} (POST /generate, GET /healthz "
              "/debug/router /metrics)", file=sys.stderr, flush=True)
        try:
            while True:
                import time

                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            fe.stop()
        return

    from nanosandbox_tpu.data.loader import BinDataset
    from nanosandbox_tpu.data.tokenizer import get_tokenizer
    from nanosandbox_tpu.sample import cast_params_for_serving
    from nanosandbox_tpu.serve.engine import Engine
    from nanosandbox_tpu.serve.http import EngineLoop, make_server
    from nanosandbox_tpu.train import restore_for_inference

    # Load the shardcheck budget BEFORE the restore + warmup compiles:
    # a typo'd path or corrupt file must fail in milliseconds, not
    # after minutes of prefill-grid compilation. (The export itself
    # happens post-warmup, next to the other /metrics publishing.)
    # None (flag not given) falls back to the committed default and is
    # skipped when absent; an EXPLICIT path — even one spelling out the
    # default — must exist (argparse cannot tell a typed-out default
    # from the fallback, so the sentinel is None, not the path).
    shardcheck_budget = None
    implicit_budget = args.shardcheck_budget is None
    # A tensor-parallel engine runs under the TP comms contract — the
    # implicit default follows the --tp flag so the exported gauges
    # describe the engine actually serving. The committed contract is
    # pinned at tp=2; any OTHER degree gets no implicit budget (its
    # program names and bytes would describe a different engine —
    # misleading gauges are worse than none) and must pass an explicit
    # --shardcheck_budget regenerated at that degree.
    if args.tp > 1:
        default_budget = ("budgets/serve_tp_cpu8.json" if args.tp == 2
                          else None)
        if default_budget is None and implicit_budget:
            print(f"[serve] no committed shardcheck budget for tp="
                  f"{args.tp} (the pinned contract is tp=2) — skipping "
                  "the /metrics budget export; pass --shardcheck_budget="
                  "<path> regenerated at this degree to restore it",
                  file=sys.stderr, flush=True)
    else:
        default_budget = "budgets/serve_cpu8.json"
    budget_path = (default_budget if implicit_budget
                   else args.shardcheck_budget)
    if budget_path:
        import os

        if os.path.exists(budget_path):
            from nanosandbox_tpu.analysis.shardcheck import load_budget

            try:
                shardcheck_budget = load_budget(budget_path)
            except ValueError as e:
                raise SystemExit(f"--shardcheck_budget: {e}")
        elif not implicit_budget:
            raise SystemExit(
                f"--shardcheck_budget={budget_path}: no such file (only "
                "the implicit default is skipped when absent)")

    # Fault plan (chaos drills): parsed BEFORE the expensive restore so
    # a typo fails in milliseconds; armed only after warmup — the
    # plan's relative steps aim at live traffic, never at compile time.
    fault_plan = None
    if args.faults:
        from nanosandbox_tpu.serve.faults import FaultPlan

        try:
            fault_plan = FaultPlan.parse(args.faults)
        except ValueError as e:
            raise SystemExit(f"--faults: {e}")
        fault_plan.enabled = False

    trainer, state, step = restore_for_inference(
        args.out_dir, data_dir=args.data_dir, device=args.device)
    params = cast_params_for_serving(state["params"],
                                     trainer.cfg.compute_dtype)

    ds = BinDataset(args.data_dir, args.dataset)
    tok = get_tokenizer(ds.meta.get("kind", "char"), ds.meta)

    from nanosandbox_tpu.serve.drafters import drafter_from_flag

    drafter = drafter_from_flag(args.spec, k=args.spec_k,
                                data_dir=args.data_dir)
    engine = Engine(trainer.model, params, num_slots=args.num_slots,
                    max_len=args.max_len or None,
                    pipeline=not args.no_pipeline, spec=drafter,
                    scan_k=args.scan_k, tp=args.tp,
                    kv_dtype=args.kv_dtype, decode_impl=args.decode_impl,
                    paged=args.paged == "on",
                    kv_page_size=args.kv_page_size,
                    kv_pool_blocks=args.kv_pool_blocks or None,
                    prefix_cache=not args.no_prefix_cache,
                    watchdogs=not args.no_watchdogs,
                    watchdog_dir=args.watchdog_dir,
                    default_deadline_s=args.deadline_s or None,
                    faults=fault_plan,
                    prefill_chunk=args.prefill_chunk or None,
                    preemption=not args.no_preemption,
                    brownout=args.brownout == "on",
                    role=args.role)
    # Warm the compile set BEFORE binding the port: /healthz going green
    # is the readiness contract the k8s manifest and docs promise
    # ("restore + first compile done"), so no live request may ever eat
    # a cold XLA compile. The set is bounded by design —
    # len(admit_ladder) * len(buckets) prefills + admit/release/decode —
    # so this is a fixed startup cost; --warmup=buckets trades lazy
    # wave-size compiles for a faster start.
    rungs = (engine.admit_buckets if args.warmup == "full" else [1])
    lo = 1
    # A decode-tier pod (ISSUE 16) never dispatches a prefill: warming
    # the prefill grid would WIDEN its compile set and break the
    # strict-subset contract the disagg shardcheck re-pin asserts, so
    # the bucket loop is skipped entirely for --role=decode.
    warm_buckets = ([] if args.role == "decode"
                    else engine.sched.buckets)
    for bucket in warm_buckets:
        # Warmup prompt length must actually MAP to this bucket (in
        # (previous rung, bucket]). Prefer leaving room for 2 new
        # tokens — a 1-token request finishes on its prefill-sampled
        # token and would never touch (= compile) the batched decode
        # step. But a bucket reachable ONLY by max_new_tokens=1
        # requests (max_len within 2 of the previous rung) still gets
        # its prefill/admit programs compiled via a 1-token warmup:
        # the post-warmup freeze below makes EVERY admissible request
        # shape's absence an outage, not a lazy compile. Only a bucket
        # no admissible request can map to at all (no length in range
        # even with one new token) is skipped — submit() can never
        # send traffic there, so skipping keeps the readiness contract
        # honest AND freeze-safe.
        length = min(bucket, engine.max_len - 2)
        new_tokens = 2
        lo, prev_lo = bucket + 1, lo
        if length < prev_lo:
            length, new_tokens = min(bucket, engine.max_len - 1), 1
            if length < prev_lo:
                continue
        for k in rungs:
            # k same-bucket submissions land as ONE admission wave,
            # compiling the (k, bucket) prefill.
            for _ in range(k):
                engine.submit([0] * length, new_tokens)
            engine.drain()
            # A warmup prompt's blocks must never serve a prefix hit to
            # the NEXT warmup wave: a hit shrinks the suffix bucket, and
            # the (k, bucket) program this wave exists to compile would
            # silently not compile — a post-freeze outage on the first
            # real prompt that maps there. Same-wave submissions are
            # safe (admission happens before any donation), so flushing
            # between drains closes the hole completely.
            engine.reset_prefix_cache()
    # The scan-chunk rung ladder (--scan_k > 1): one megaprogram per
    # rung, compiled by dispatching each rung once over the parked slot
    # state — the freeze below would otherwise turn the first request
    # mix whose budgets make the chunk policy pick an uncompiled rung
    # into a post-warmup retrace outage.
    if args.role == "decode":
        if args.paged != "on":
            raise SystemExit("--role=decode needs --paged=on: adoption "
                             "is a paged block-chain operation")
        # Warm exactly what the decode tier runs — the rung-1 admit
        # scatter and one decode dispatch — via a throwaway adoption.
        # The adopted blocks are never written (zero-initialized KV is
        # fine for a compile) and the chain is flushed so no real
        # request can prefix-hit it.
        from nanosandbox_tpu.serve.engine import Request as _Request
        ad = engine.begin_adopt(
            _Request(rid=-1, prompt=(0, 0, 0), max_new_tokens=2))
        if ad is not None:
            engine.commit_adopt(ad, 0)
            engine.drain()
            engine.reset_prefix_cache()
    if args.warmup == "full":
        engine.warm_scan_rungs()
    print(f"[serve] warmup: compiled {engine.trace_counts['prefill']} "
          f"prefill program(s) ({args.warmup}), "
          f"{engine.trace_counts['admit']} admit, "
          f"{engine.trace_counts['decode']} decode"
          + (f", {engine.trace_counts.get('verify', 0)} verify "
             f"(spec={args.spec}, k={args.spec_k})"
             if args.spec != "off" else "")
          + f" (pipeline={'on' if engine.pipeline else 'off'}"
          + (f", role={args.role}" if args.role != "both" else "")
          + ")",
          file=sys.stderr, flush=True)
    engine.reset_latency_stats()  # /stats should describe live traffic
    # Post-warmup, ANY compile eats a live request's latency, so the
    # watchdog marks steady in BOTH warmup modes: under --warmup=buckets
    # the deliberate lazy wave compiles are exactly what an operator
    # wants counted and dumped (the freeze doesn't cover that mode);
    # under --warmup=full the tracecheck freeze makes a retrace fatal
    # first, and the mark is a belt-and-braces backstop.
    engine.watchdog.mark_steady()
    # Host health on the same scrape as the engine counters: RSS, open
    # fds, uptime, live jax buffer bytes — sampled per scrape.
    from nanosandbox_tpu.obs import register_process_vitals

    register_process_vitals()
    # Publish the pinned comms contract (shardcheck budget) as gauges on
    # the process-global registry so every /metrics scrape carries the
    # collective counts this deployment is budgeted for — a TP-serving
    # rollout that rewrites the budget becomes visible in the same
    # dashboard that watches its latency.
    if shardcheck_budget is not None:
        from nanosandbox_tpu.analysis.shardcheck import (
            export_collective_bytes_per_token, export_manifest_metrics)
        from nanosandbox_tpu.obs import global_registry

        export_manifest_metrics(shardcheck_budget, global_registry())
        if args.tp > 1:
            # The TP wire cost per token, per program — the startup
            # shardcheck pass normalized onto the scrape next to the
            # serve_tp_degree gauge the engine itself exports.
            export_collective_bytes_per_token(shardcheck_budget,
                                              global_registry())
        print(f"[serve] shardcheck budget {budget_path} exported to "
              "/metrics", file=sys.stderr, flush=True)
    if fault_plan is not None:
        # Arm at the post-warmup step: the plan's relative schedule
        # targets live traffic.
        fault_plan.rearm(engine.steps)
        fault_plan.enabled = True
        print(f"[serve] CHAOS: fault plan armed — "
              f"{fault_plan.describe()}", file=sys.stderr, flush=True)
    supervisor = None
    if not args.no_recovery:
        from nanosandbox_tpu.serve.recovery import EngineSupervisor

        supervisor = EngineSupervisor(engine)
    loop = EngineLoop(engine, supervisor=supervisor)
    loop.start()
    server = make_server(args.host, args.port, loop, tok.encode,
                         lambda ids: tok.decode([int(t) for t in ids]))
    pool_desc = (f"paged pool {engine.kv_pool_blocks} blocks x "
                 f"{engine.kv_page_size} positions"
                 + ("" if args.no_prefix_cache else " + prefix cache")
                 if engine.paged else "dense per-slot rows")
    print(f"[serve] checkpoint step {step}; {args.num_slots} slots x "
          f"{engine.max_len} ctx, tp={engine.tp} "
          f"({pool_desc}, kv_dtype={engine.kv_dtype}, "
          f"decode_impl={engine.decode_impl}, recovery="
          f"{'off' if supervisor is None else 'on'}, "
          f"prefill_chunk={engine.prefill_chunk or 'off'}, preemption="
          f"{'on' if engine.preemption else 'off'}, brownout="
          f"{'on' if engine.brownout is not None else 'off'}); "
          f"prefill buckets "
          f"{engine.sched.buckets}; listening on "
          f"{args.host}:{args.port} (POST /generate /drain /profile, "
          "GET /healthz[?ready=1] /stats /metrics /trace "
          "/debug/requests /debug/slots /debug/kvpool "
          "/debug/scheduler)",
          file=sys.stderr, flush=True)
    # After a FULL warmup the compile set is complete by contract, so
    # freeze the retrace budgets: a compile after /healthz went green
    # is a shape leak eating a live request's latency, and the engine
    # loop dying with CompileBudgetExceeded (failing queued requests
    # with the reason) beats serving it silently. --warmup=buckets
    # deliberately leaves lazy wave compiles, so no freeze there.
    freeze = (engine.tracecheck.frozen() if args.warmup == "full"
              else contextlib.nullcontext())
    try:
        with freeze:
            server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        loop.stop()
        server.server_close()


if __name__ == "__main__":
    main()
