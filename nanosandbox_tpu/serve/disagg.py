"""Disaggregated prefill/decode serving — paged KV block chains as the
migration wire format (ISSUE 16).

Chunked prefill (ISSUE 13) PACES the prefill storm inside one engine;
disaggregation REMOVES it.  ``DisaggPair`` splits serving into a
prefill tier and a decode tier with the paged block chain as the
handoff unit:

  1. the prefill engine (``role="prefill"``) runs admission waves at
     full tilt — there is no decode traffic to protect, so chunking is
     unnecessary and TTFT is as low as the bucket grid allows;
  2. at the first-token readback the request EXPORTS instead of
     activating: the whole prompt's K/V sits in its block chain, the
     first token was sampled with the fold_in(seed, true_len) key, and
     the record parks in migration limbo (engine._Export);
  3. the pump moves the chain — ``BlockPool.adopt_chain`` reserves the
     destination footprint, ``read_pool_blocks`` / ``write_pool_blocks``
     copy exactly the blocks the destination's radix cache does not
     already hold — and ``commit_adopt`` re-admits the request through
     the decode engine's rung-1 admit program as a pure prefix hit:
     ZERO prefill dispatches on the decode tier, ever (ledger-pinned).

This is the PR 15 failover-restitch argument promoted to the NORMAL
path: decode continues from pos = true_len with fold_in(seed, pos + 1)
row keys, exactly the stream a colocated engine would have produced,
so greedy outputs are token-identical to never having disaggregated
(parity-pinned across paged x kv dtypes x scan_k).

Exactly-once across the handoff: a request in migration is owned by
exactly one record at all times — the export (source side) until
``complete_export``, the active row (destination side) after
``commit_adopt``.  Every failure in between unwinds to the export and
resolves through exactly one of:

  * ``complete_export``  — handoff landed (outcome ``ok``);
  * ``requeue_export``   — decode tier dead / payload refused: the
    request re-enters the PREFILL engine's admission colocated, where
    the re-prefill is a pure prefix hit that resamples the SAME first
    token (outcome ``fallback``);
  * limbo shed           — deadline expired while parked: the engine's
    shed pass sweeps limbo with the admission queue, terminal ``shed``,
    blocks released WITHOUT donation (outcome ``shed``);
  * engine failure       — the source itself dies: abort_all drains
    limbo as terminal ``failed`` (outcome ``failed``).

The ``replica_down`` fault site (serve/faults.py), consulted by the
pump INSIDE the migration window — destination blocks reserved,
nothing committed — hard-kills the decode engine mid-handoff: the
adoption unwinds (``abort_adopt``: blocks freed without donation, a
half-copied chain must never serve a prefix hit), the export falls
back, and the dead tier's in-flight requests restitch onto the prefill
engine colocated (prompt' = prompt + salvaged tokens).  The fuzz pins
exactly one terminal per pair rid through all of it.

``export_to_wire`` / ``adopt_from_wire`` are the HTTP twins of the
in-process transfer: one JSON payload carrying the request, the first
token, and the full prompt chain's blocks (base64 per pool leaf —
quantized pools ride as codes + scales, never dequantized); the
adopter copies only the rows its own radix cache lacks.  The
RouterFrontend proxies this payload between tiers (serve/http.py).

No compiled program is added anywhere: the transfer is host-side
orchestration over fixed-shape eager scatters outside both engines'
guarded compile sets, and the decode tier's set — {decode scan rungs,
admit, release} — is a strict SUBSET of a colocated engine's
(shardcheck-pinned; jits are lazy, a program never dispatched is never
compiled).
"""

from __future__ import annotations

import base64
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nanosandbox_tpu.obs import FlightRecorder, MetricRegistry
from nanosandbox_tpu.serve.engine import (DEFAULT_PRIORITY, Engine,
                                          EngineFailedError, Request,
                                          Result)
from nanosandbox_tpu.serve.paged import blocks_for

PREFILL, DECODE = "prefill", "decode"


@dataclass
class _PairReq:
    """One client request's pair-side journal across tiers/attempts."""
    pair_rid: str                # "prefill:N" — first attempt's rid
    tier: str                    # tier currently owning the request
    engine_rid: int              # rid on that tier's engine
    prompt: tuple
    max_new: int
    kwargs: dict                 # sampling/SLO fields, re-sent on failover
    tokens: List[int] = field(default_factory=list)  # salvaged so far
    submit_t: float = 0.0
    deadline_s: Optional[float] = None
    attempts: int = 1


class DisaggPair:
    """A prefill engine + a decode engine on one host, the migration
    pump between them, and an Engine-shaped submit()/step()/drain()
    surface — the in-process form of the two-tier deployment, so tests
    and ``bench.py --mode=serve --disagg`` measure the architecture
    with zero network in the loop.  The asyncio HTTP tier
    (RouterFrontend + the wire helpers below) drives the SAME engine
    APIs across real pods; this harness is the policy's test bench.

    Parameters mirror Engine where they overlap; ``engine_kw``
    (num_slots, max_len, kv_page_size, scan_k, ...) applies to BOTH
    engines identically — identical compile-relevant config is what
    makes the migrated chain bit-compatible with the destination pool.

    prefill_chunk : chunked prefill on the PREFILL tier only (decode
        never prefills). Default off — a dedicated prefill tier has no
        decode traffic to protect, which is the point.
    faults : a FaultPlan consulted for ``replica_down`` once per
        migration, INSIDE the handoff window (destination blocks
        reserved, nothing committed) — the hardest exactly-once case.
        Engine-level plans go through ``engine_kw``.
    fallback : re-admit work colocated on the prefill engine when the
        decode tier dies (default). False surfaces tier loss as
        'failed' Results — the no-safety-net twin for tests.
    metrics : registry for the PAIR families (migrations, migration
        latency, limbo depth). Each engine always gets its own registry
        (engine.py's one-engine-per-registry rule); per-tier role
        gauges live there as ``serve_engine_role{role=}``.
    """

    def __init__(self, model, params, *,
                 prefill_chunk: Optional[int] = None,
                 faults=None, fallback: bool = True,
                 metrics: Optional[MetricRegistry] = None,
                 **engine_kw):
        if not engine_kw.get("paged", True):
            raise ValueError("disaggregation needs paged=True: the "
                             "block chain is the migration wire format")
        for k in ("role", "metrics", "flight"):
            if k in engine_kw:
                raise ValueError(f"{k!r} is owned by DisaggPair; pass "
                                 f"pair-level options instead")
        self.fallback = bool(fallback)
        self.faults = faults
        if faults is not None:
            faults.arm(0)
        self.prefill = Engine(
            model, params, role=PREFILL, metrics=MetricRegistry(),
            flight=FlightRecorder(namespace=PREFILL),
            prefill_chunk=prefill_chunk, **engine_kw)
        self.decode = Engine(
            model, params, role=DECODE, metrics=MetricRegistry(),
            flight=FlightRecorder(namespace=DECODE), **engine_kw)
        self.engines: Dict[str, Engine] = {PREFILL: self.prefill,
                                           DECODE: self.decode}
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self._c_migrations = self.metrics.counter(
            "serve_migrations_total",
            "Prefill->decode handoffs by outcome (ok | fallback | "
            "shed | failed).", labelnames=("outcome",))
        self._h_migration = self.metrics.histogram(
            "serve_migration_seconds",
            "Export-parked -> adoption-committed seconds (limbo wait "
            "+ block transfer + admit scatter).")
        self._g_limbo = self.metrics.gauge(
            "serve_migration_limbo_depth",
            "Exports parked on the prefill tier awaiting adoption.")
        # The pair's OWN recorder: migrate_fallback / replica_down /
        # failover events over pair rids; terminals stay with the
        # engines (one per namespaced rid, even across the handoff).
        self.flight = FlightRecorder()
        self._requests: Dict[str, _PairReq] = {}
        self._by_engine: Dict[Tuple[str, int], str] = {}
        self.steps = 0
        self.submitted = 0
        self.completed = 0
        self.migrations = 0
        self.fallbacks = 0
        self.replica_downs = 0

    # ------------------------------------------------------------ submit
    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               **kwargs) -> str:
        """Submit one request to the PREFILL tier with migrate intent;
        returns its pair id ("prefill:N"). Propagates the engine's
        admission ValueErrors (400) and EngineFailedError (503)."""
        prompt = tuple(int(t) for t in prompt)
        kwargs.pop("migrate", None)      # the pair owns migrate intent
        rid = self.prefill.submit(prompt, max_new_tokens,
                                  migrate=True, **kwargs)
        pair_rid = f"{PREFILL}:{rid}"
        self.submitted += 1
        self._requests[pair_rid] = _PairReq(
            pair_rid=pair_rid, tier=PREFILL, engine_rid=rid,
            prompt=prompt, max_new=int(max_new_tokens),
            kwargs=dict(kwargs), submit_t=time.monotonic(),
            deadline_s=kwargs.get("deadline_s"))
        self._by_engine[(PREFILL, rid)] = pair_rid
        return pair_rid

    # -------------------------------------------------------------- step
    def has_work(self) -> bool:
        return any(eng.has_work() for eng in self.engines.values())

    def step(self) -> List[Result]:
        """One pair step: prefill tier steps (admissions export into
        limbo), the pump migrates every parked export it can place,
        the decode tier steps. Returns PAIR-terminal Results (rid =
        pair id, prompt = the original prompt, tokens stitched across
        tiers)."""
        out: List[Result] = []
        # Limbo membership BEFORE the step classifies this step's
        # terminals: a 'shed'/'failed' whose rid was parked is a
        # migration that never landed (outcome shed/failed), not an
        # admission-queue casualty.
        limbo_rids = {exp.rid for exp in self.prefill.sched.limbo_items()}
        for res in self.prefill.step():
            self._absorb(PREFILL, res, out, limbo_rids=limbo_rids)
        self._pump(out)
        for res in self.decode.step():
            self._absorb(DECODE, res, out)
        self.steps += 1
        self._g_limbo.set(self.prefill.sched.limbo)
        return out

    def drain(self) -> List[Result]:
        out: List[Result] = []
        while self.has_work():
            out.extend(self.step())
        return out

    # -------------------------------------------------------------- pump
    def _pump(self, out: List[Result]) -> None:
        """Move every parked export the decode tier can adopt RIGHT
        NOW; repark on adoption backpressure (no slot / no blocks) —
        the decode tier's own finishes free capacity next step."""
        while True:
            exp = self.prefill.pop_export()
            if exp is None:
                return
            if self.decode.failed:
                self._fall_back(exp, out, cause="decode_tier_down")
                continue
            ad = self.decode.begin_adopt(exp.req)
            if ad is None:
                self.prefill.repark_export(exp)
                return
            # The mid-migration kill window (satellite: replica_down
            # fired mid-migration): destination slot + blocks are
            # reserved, nothing is committed, the export still owns
            # the request. A kill here must unwind to exactly one
            # terminal — the fuzz's hardest case.
            if (self.faults is not None
                    and self.faults.fire("replica_down", self.steps)):
                self.decode.abort_adopt(ad)
                self._kill_decode(out)
                self._fall_back(exp, out, cause="replica_down")
                continue
            src_ids = [exp.alloc.table[i] for i in ad.copy]
            payload = self.prefill.read_pool_blocks(src_ids)
            nbytes = self.decode.write_pool_blocks(ad.dst_blocks, payload)
            try:
                new_rid, done = self.decode.commit_adopt(
                    ad, exp.first_tok, submit_t=exp.submit_t,
                    src=PREFILL)
            except ValueError:
                # Corrupt first token: unwind the half-adoption and
                # fall back — the source resamples the same token from
                # its own clean chain.
                self.decode.abort_adopt(ad)
                self._fall_back(exp, out, cause="bad_first_token")
                continue
            mig_s = time.monotonic() - exp.export_t
            self.prefill.complete_export(
                exp, dst=DECODE, blocks_copied=len(ad.copy),
                bytes_moved=nbytes, migrate_s=mig_s)
            self._h_migration.observe(mig_s)
            self._c_migrations.labels(outcome="ok").inc()
            self.migrations += 1
            pair_rid = self._by_engine.pop((PREFILL, exp.req.rid), None)
            if pair_rid is not None:
                fr = self._requests[pair_rid]
                fr.tier, fr.engine_rid = DECODE, new_rid
                self._by_engine[(DECODE, new_rid)] = pair_rid
            if done is not None:
                self._absorb(DECODE, done, out)

    def _fall_back(self, exp, out: List[Result], *, cause: str) -> None:
        """Resolve one unplaceable export: requeue colocated on the
        prefill engine (the re-prefill is a pure prefix hit resampling
        the SAME first token — token-identical to the migration that
        never happened), or surface 'failed' when falling back is
        impossible/disabled."""
        if self.fallback and not self.prefill.failed:
            self.prefill.requeue_export(exp)
            self._c_migrations.labels(outcome="fallback").inc()
            self.fallbacks += 1
            self.flight.record("migrate_fallback",
                               rid=f"{PREFILL}:{exp.req.rid}",
                               step=self.steps, cause=cause)
            return
        self.prefill.block_pool.release(exp.alloc, donate=False)
        self._c_migrations.labels(outcome="failed").inc()
        pair_rid = self._by_engine.pop((PREFILL, exp.req.rid), None)
        if pair_rid is None:
            return
        fr = self._requests.pop(pair_rid)
        self.completed += 1
        out.append(Result(rid=pair_rid, prompt=fr.prompt,
                          tokens=fr.tokens + [exp.first_tok],
                          finish_reason="failed"))

    def _kill_decode(self, out: List[Result]) -> None:
        """The replica_down site: hard-kill the decode tier
        (abort_all — permanent failure; its in-flight requests come
        back terminal 'failed' and restitch onto the prefill engine
        colocated)."""
        self.replica_downs += 1
        self.flight.record("replica_down", replica=DECODE,
                           step=self.steps)
        for res in self.decode.abort_all("replica_down"):
            self._absorb(DECODE, res, out)

    # ------------------------------------------------------------ absorb
    def _absorb(self, tier: str, res: Result, out: List[Result],
                limbo_rids: frozenset = frozenset()) -> None:
        """Map one engine Result back to its pair request: terminal,
        or a colocated restitch when the decode tier died under it."""
        pair_rid = self._by_engine.pop((tier, res.rid), None)
        if pair_rid is None:
            return                       # warmup traffic / direct submits
        fr = self._requests[pair_rid]
        if res.rid in limbo_rids:
            # A terminal for a PARKED export: the migration resolved
            # without ever landing (deadline shed in limbo, or the
            # source died with the export aboard).
            outcome = "shed" if res.finish_reason == "shed" else "failed"
            self._c_migrations.labels(outcome=outcome).inc()
        if (res.finish_reason == "failed" and tier == DECODE
                and self.fallback and self._restitch(fr, res, out)):
            return
        del self._requests[pair_rid]
        self.completed += 1
        out.append(Result(
            rid=pair_rid, prompt=fr.prompt,
            tokens=fr.tokens + list(res.tokens),
            finish_reason=res.finish_reason,
            prefix_digest=res.prefix_digest))

    def _restitch(self, fr: _PairReq, res: Result,
                  out: List[Result]) -> bool:
        """Re-admit one dead decode tier's victim COLOCATED on the
        prefill engine: prompt' = prompt + salvaged tokens with the
        remaining budget — fold_in(seed, abs_position) row keys make
        the resumed greedy stream token-identical (the fleet failover
        argument, one tier over). May resolve to a terminal itself
        (deadline expired, budget met). False = no restitch possible
        (caller emits the 'failed' terminal)."""
        salvaged = fr.tokens + list(res.tokens)
        remaining = fr.max_new - len(salvaged)
        now = time.monotonic()
        if fr.attempts > 2 or self.prefill.failed:
            return False
        if (fr.deadline_s is not None
                and now - fr.submit_t >= fr.deadline_s):
            self.flight.record("failover_shed", rid=fr.pair_rid,
                               step=self.steps, tokens=len(salvaged))
            del self._requests[fr.pair_rid]
            self.completed += 1
            out.append(Result(rid=fr.pair_rid, prompt=fr.prompt,
                              tokens=salvaged, finish_reason="shed"))
            return True
        if remaining <= 0:
            del self._requests[fr.pair_rid]
            self.completed += 1
            out.append(Result(rid=fr.pair_rid, prompt=fr.prompt,
                              tokens=salvaged, finish_reason="length"))
            return True
        kwargs = dict(fr.kwargs)
        if fr.deadline_s is not None:
            kwargs["deadline_s"] = max(
                fr.deadline_s - (now - fr.submit_t), 0.001)
        try:
            rid = self.prefill.submit(fr.prompt + tuple(salvaged),
                                      remaining, **kwargs)
        except (ValueError, EngineFailedError):
            return False
        self.flight.record("failover", rid=fr.pair_rid, step=self.steps,
                           dead=DECODE, replica=PREFILL,
                           new_rid=f"{PREFILL}:{rid}",
                           tokens=len(salvaged))
        fr.tokens = salvaged
        fr.tier, fr.engine_rid, fr.attempts = PREFILL, rid, fr.attempts + 1
        self._by_engine[(PREFILL, rid)] = fr.pair_rid
        return True

    # ------------------------------------------------------------- views
    def retry_after_s(self, slo_class: Optional[str] = None) -> float:
        """Pair backoff hint: admission happens on the prefill tier,
        so its estimate is the binding one; a failed prefill tier
        falls back to the decode engine's (degenerate colocated)."""
        eng = self.prefill if not self.prefill.failed else self.decode
        return eng.retry_after_s(slo_class=slo_class)

    def stats(self) -> dict:
        return {
            "steps": self.steps,
            "submitted": self.submitted,
            "completed": self.completed,
            "in_flight": len(self._requests),
            "migrations": self.migrations,
            "fallbacks": self.fallbacks,
            "replica_downs": self.replica_downs,
            "limbo": self.prefill.sched.limbo,
            "migration_s": self._h_migration.percentiles((50, 90, 99)),
            "tiers": {name: {
                "role": eng.role,
                "active": len(eng._active),
                "queued": eng.sched.queued,
                "completed": eng.completed,
                "migrated": eng.migrated,
                "adopted": eng.adopted,
                "failed": eng.failed,
                "host_dispatches": dict(eng.host_dispatches),
            } for name, eng in self.engines.items()},
        }

    def merged_flight_events(self) -> List[dict]:
        """Both tiers' ledgers plus the pair's own, one stream ordered
        by wall clock — rids are tier-namespaced, so the merge stays
        exactly-once analyzable (the fuzz target)."""
        events: List[dict] = []
        for eng in self.engines.values():
            events.extend(eng.flight.events())
        events.extend(self.flight.events())
        events.sort(key=lambda e: e["wall"])
        return events

    def merged_flight_jsonl(self) -> str:
        import json

        lines = [json.dumps(e, sort_keys=True)
                 for e in self.merged_flight_events()]
        return "\n".join(lines) + ("\n" if lines else "")

    def reset_latency_stats(self) -> None:
        """Benchmark hygiene, pair-wide (the Engine contract)."""
        for eng in self.engines.values():
            eng.reset_latency_stats()
        self.flight.clear()


# ---------------------------------------------------------------- wire
def export_to_wire(engine: Engine, exp) -> dict:
    """Serialize one parked export for cross-process migration: the
    request's scheduling/sampling fields, the sampled first token, and
    the FULL prompt chain's blocks (one base64 entry per pool leaf, in
    jax.tree flatten order — int8/int4 pools ride as codes + scales,
    never dequantized). The full chain travels so the handoff is one
    round trip; the adopter copies only the rows its own radix cache
    lacks (``adopt_from_wire`` slices by its local ``copy`` set).
    Wall clocks do not transfer between processes, so the elapsed SLO
    budget rides as ``waited_s``."""
    req = exp.req
    n_chain = blocks_for(len(req.prompt), engine.kv_page_size)
    leaves = engine.read_pool_blocks(exp.alloc.table[:n_chain])
    return {
        "prompt": list(req.prompt),
        "max_new_tokens": req.max_new_tokens,
        "temperature": req.temperature, "top_k": req.top_k,
        "top_p": req.top_p, "seed": req.seed, "eos_id": req.eos_id,
        "deadline_s": req.deadline_s, "slo_class": req.slo_class,
        "priority": req.priority,
        "first_tok": int(exp.first_tok),
        "waited_s": round(time.monotonic() - exp.submit_t, 6),
        "chain_blocks": n_chain,
        "leaves": [{
            "shape": list(v.shape), "dtype": str(v.dtype),
            "data": base64.b64encode(np.ascontiguousarray(v).tobytes())
            .decode("ascii"),
        } for v in leaves],
    }


def adopt_from_wire(engine: Engine, wire: dict, *,
                    src: str = "") -> Optional[Tuple[int, Optional[Result]]]:
    """Adopt one serialized export into ``engine``: reserve the
    footprint, scatter only the chain rows this engine's radix cache
    lacks, and commit through the rung-1 admit program — zero prefill
    dispatches. Returns (rid, immediately-finished Result or None), or
    None on adoption backpressure (no slot / no blocks: the caller
    answers 503-retryable and the source reparks or falls back)."""
    req = Request(
        rid=-1, prompt=tuple(int(t) for t in wire["prompt"]),
        max_new_tokens=int(wire["max_new_tokens"]),
        temperature=float(wire.get("temperature", 0.0)),
        top_k=int(wire.get("top_k", 0)),
        top_p=float(wire.get("top_p", 1.0)),
        seed=int(wire.get("seed", 0)),
        eos_id=wire.get("eos_id"),
        deadline_s=wire.get("deadline_s"),
        slo_class=wire.get("slo_class", "default"),
        priority=int(wire.get("priority", DEFAULT_PRIORITY)))
    ad = engine.begin_adopt(req)
    if ad is None:
        return None
    rows = []
    for entry in wire["leaves"]:
        buf = base64.b64decode(entry["data"])
        rows.append(np.frombuffer(buf, dtype=np.dtype(entry["dtype"]))
                    .reshape(entry["shape"]))
    try:
        idx = np.asarray(ad.copy, np.int64)
        engine.write_pool_blocks(ad.dst_blocks,
                                 [r[idx] for r in rows])
        rid, done = engine.commit_adopt(
            ad, int(wire["first_tok"]),
            submit_t=time.monotonic() - float(wire.get("waited_s", 0.0)),
            src=src)
    except (ValueError, KeyError):
        engine.abort_adopt(ad)
        raise
    return rid, done
