"""Speculative decoding: fixed-shape batched verification for the Engine.

The decode hot loop's floor is one target-model forward per emitted
token. Speculative decoding (Leviathan et al., "Fast Inference from
Transformers via Speculative Decoding") raises that to up-to-k+1 tokens
per forward: a cheap drafter (serve/drafters.py) guesses the next k
tokens of every slot, ONE batched target forward scores all k+1
positions against the slot KV pool, and rejection sampling keeps the
longest prefix the target agrees with plus one freshly sampled token —
with the output distribution provably identical to non-speculative
decoding (greedy: token-for-token identical, pinned by test).

The TPU discipline survives intact:

  * ONE verify program, ever. The verify block is a fixed
    (num_slots, k+1) shape; per-row draft LENGTHS vary via a mask, so a
    slot whose drafter found nothing (draft_len 0) rides the same
    program as a slot with k hot drafts — mixed spec/non-spec slots
    coexist in one batch, and the compile set stays closed
    (Engine.max_programs() gains {'verify': 1}, plus the ModelDrafter's
    {'draft': 1, 'draft_prefill': ladder x buckets}).

  * Cache-frontier rollback is FREE. The verify forward writes K/V for
    all k+1 positions through the per-row drop-mode scatter in
    models/gpt.py; when only a of k drafts are accepted, the engine
    simply does not advance ``pos`` past the accepted prefix. The stale
    columns beyond the new frontier are overwritten by the very next
    verify block (which spans them by construction: the new frontier
    plus k+1 columns covers everything the rejected tail wrote) before
    any query attends to them — the same argument that already lets a
    released slot's garbage sit in the pool.

  * The sampling-stream contract narrows, it does not break. The token
    destined for position q is still drawn from fold_in(key(seed), q)
    (sample.row_keys); accept/reject coins use an extra fold_in(·, 1)
    so they never correlate with the sample draw. A row with draft_len
    0 therefore emits EXACTLY the token the non-speculative decode step
    would — even at temperature > 0 — so turning spec on is safe for
    workloads the drafter can't help.

Composition with the multi-token decode scan (ISSUE 12): spec KEEPS
the synchronous one-verify-per-dispatch loop — the verify readback
(accepted lengths) gates the next frontier and a host drafter proposes
from the latest tokens, so there is no k-chunk to fuse; Engine forces
scan_k=1 under spec. What spec DOES inherit: a paged verify's T=k+1
block now reads through the flash paged-prefill kernel when the engine
runs a kernel impl (models/gpt.py routes every per-row T>1 paged read
there), so the verify stops paying the gathered chain copy too.

Rejection rule (greedy drafters propose point masses): accept draft d
at position q with probability p_q(d) under the TARGET's filtered
distribution (temperature/top-k/top-p — shared with the decode step via
sample._filter_logits_rows, so verify and decode can never drift); on
the first rejection, resample from p_q with d's mass zeroed and
renormalized (categorical over masked logits does the renormalization).
Greedy rows (temperature 0) reduce to exact-match accept against the
raw-logits argmax. Either way each verify emits between 1 and k+1
tokens per live row — never fewer than plain decode.
"""

from __future__ import annotations

from typing import Optional


class SpecRunner:
    """Owns the speculative state the Engine delegates to: the drafter,
    the compiled verify program, and the acceptance accounting.

    Built by Engine.__init__ (spec=...); the Engine remains the only
    code that touches the slot pool / slot state — SpecRunner's verify
    is a pure function of them, threaded through exactly like the
    decode step (donated on accelerators)."""

    def __init__(self, drafter, *, model, num_slots: int, max_len: int,
                 n_prefill_programs: int, registry, on_accel: bool,
                 kv_dtype=None, decode_impl=None, paged: bool = False,
                 kv_page_size: int = 0, kv_pool_blocks: int = 0):
        import jax

        self.drafter = drafter
        self.model = model
        self.k = int(drafter.k)
        self.num_slots = num_slots
        if self.k < 1:
            raise ValueError(f"drafter k must be >= 1, got {self.k}")
        if max_len < 2:
            raise ValueError("speculative decoding needs max_len >= 2")
        self.programs = {"verify": 1}
        if drafter.kind == "device":
            # kv_dtype and decode_impl ride through to the drafter's OWN
            # pool and model: the engine's verify reads the shared
            # target pool (already in the engine's mode), and a drafter
            # serving an int8 target should not quietly hold a
            # full-precision cache — nor keep running a kernel the
            # operator pinned AWAY from (--decode_impl=xla must reach
            # the drafter's T=1 draft steps too). Paged engines page the
            # drafter pool the same way: one shared block table, two
            # parallel pools indexed by the same block ids.
            self.programs.update(drafter.build(
                target_cfg=model.cfg, num_slots=num_slots, max_len=max_len,
                n_prefill_programs=n_prefill_programs, registry=registry,
                on_accel=on_accel, kv_dtype=kv_dtype,
                decode_impl=decode_impl, paged=paged,
                kv_page_size=kv_page_size, kv_pool_blocks=kv_pool_blocks))
        self._verify = jax.jit(
            registry.guard("verify", self.programs["verify"])(
                self._verify_fn),
            donate_argnums=(1, 2) if on_accel else ())
        # Token-level acceptance counters (host side, monotonic).
        self.steps = 0
        self.drafted = 0
        self.accepted = 0

    def register_metrics(self, registry) -> None:
        """Publish the acceptance ledger on an obs.MetricRegistry — a
        collection-time mirror of the plain ints above, so the verify
        loop itself never touches a metric family (the zero-hot-loop
        telemetry contract). Engine.__init__ calls this with the
        engine's registry; /metrics then carries the speculative signal
        a k8s scrape needs to decide whether spec is earning its k."""
        c_drafted = registry.counter(
            "serve_spec_tokens_drafted_total",
            "Draft tokens proposed to the verify step.")
        c_accepted = registry.counter(
            "serve_spec_tokens_accepted_total",
            "Draft tokens the target model accepted.")
        c_steps = registry.counter(
            "serve_spec_verify_steps_total", "Batched verify dispatches.")
        g_rate = registry.gauge(
            "serve_spec_acceptance_rate",
            "Token-level accepted/drafted over the process lifetime.")

        def collect():
            c_drafted._set_total(self.drafted)
            c_accepted._set_total(self.accepted)
            c_steps._set_total(self.steps)
            # Unconditional set: reset_latency_stats() zeros the ledger
            # after warmup, and a drafted==0 guard would leave the
            # gauge frozen on the degenerate warmup rate — the exact
            # skew the reset exists to prevent.
            g_rate.set(self.accepted / self.drafted if self.drafted
                       else 0.0)

        registry.add_collector(collect)

    # ------------------------------------------------------------------
    def verify(self, params, pool, state, drafts, draft_len):
        """One speculative step over all slots. Returns
        (pool, state, emitted (S, k+1), counts (S,), accepted (S,)) —
        emitted[r, :counts[r]] are row r's new tokens, accepted[r] how
        many of them were drafter guesses (counts = accepted + 1 for
        live rows, 0 for parked ones)."""
        return self._verify(params, pool, state, drafts, draft_len)

    def _verify_fn(self, params, pool, state, drafts, draft_len):
        import jax
        import jax.numpy as jnp
        from jax import lax

        from nanosandbox_tpu.sample import _filter_logits_rows, row_keys

        S, K = drafts.shape
        # Input block per row: [current token, d_1 .. d_K] at positions
        # pos .. pos+K. Offset i's logits predict position pos+i+1.
        toks_in = jnp.concatenate([state["tok"][:, None], drafts], axis=1)
        logits, pool = self.model.apply({"params": params}, toks_in,
                                        deterministic=True, cache=pool,
                                        cache_index=state["pos"],
                                        block_table=state.get("table"))
        logits = logits.astype(jnp.float32)              # (S, K+1, V)
        V = logits.shape[-1]
        t = state["temp"]
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # raw argmax
        in_len = jnp.arange(K)[None, :] < draft_len[:, None]
        rows = jnp.arange(S)

        def _greedy_path(_):
            # All rows greedy: accept is exact argmax match, the +1
            # token is the argmax at the accepted frontier — no filter,
            # no softmax, no PRNG work runs at all.
            accept = (drafts == greedy[:, :K]) & in_len
            a = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)
            return a, greedy[rows, a]

        def _sampled_path(_):
            # The TARGET distribution at every offset, under each row's
            # own sampling settings — the same filter the decode step
            # samples from (sample._filter_logits_rows), which is what
            # makes the rejection rule exact.
            filt = _filter_logits_rows(
                logits.reshape(S * (K + 1), V),
                temperature=jnp.repeat(t, K + 1),
                top_k=jnp.repeat(state["topk"], K + 1),
                top_p=jnp.repeat(state["topp"], K + 1)).reshape(S, K + 1, V)
            probs = jax.nn.softmax(filt, axis=-1)

            # Sampling-stream contract: position q's draw uses
            # fold_in(key(seed), q); the accept coin for q folds in one
            # more step so it never correlates with the draw.
            positions = (state["pos"][:, None] + 1
                         + jnp.arange(K + 1)[None, :])     # (S, K+1)
            keys = row_keys(jnp.repeat(state["seed"], K + 1),
                            positions.reshape(-1)).reshape(S, K + 1)
            coin = jax.vmap(jax.vmap(
                lambda kk: jax.random.uniform(
                    jax.random.fold_in(kk, 1))))(keys)

            # Accept: greedy rows exact-match the argmax; sampled rows
            # flip the p(d) coin. Offsets past the row's draft length
            # never accept (the per-row mask that lets mixed draft
            # lengths share one program).
            p_draft = jnp.take_along_axis(
                probs[:, :K, :], drafts[..., None], axis=-1)[..., 0]
            accept = jnp.where(t[:, None] == 0.0, drafts == greedy[:, :K],
                               coin[:, :K] < p_draft) & in_len
            a = jnp.cumprod(accept.astype(jnp.int32), axis=1).sum(axis=1)

            # The +1 token at offset a: on a rejection, resample from
            # the target distribution with the rejected draft's mass
            # removed (the point-mass residual max(0, p - q)
            # normalized); when every draft was accepted (or none were
            # proposed) it is a FULL sample from p — the bonus token,
            # and for draft_len 0 rows exactly the non-speculative
            # decode draw, key and all.
            rejected = a < draft_len
            filt_a = filt[rows, a]                               # (S, V)
            d_a = drafts[rows, jnp.minimum(a, K - 1)]
            resample_mask = rejected[:, None] & (jnp.arange(V)[None, :]
                                                 == d_a[:, None])
            sample_logits = jnp.where(resample_mask, -1e30, filt_a)
            sampled = jax.vmap(jax.random.categorical)(
                keys[rows, a], sample_logits).astype(jnp.int32)
            return a, jnp.where(t == 0.0, greedy[rows, a], sampled)

        # ONE program either way (XLA cond, not a retrace): the all-
        # greedy batch — the serving common case — runs the cheap
        # branch; any sampled row switches the whole batch to the full
        # rejection-sampling path (greedy rows inside it still get their
        # exact-match/argmax semantics via the per-row masks).
        a, out = lax.cond(jnp.any(t > 0.0), _sampled_path, _greedy_path,
                          None)
        # Poison sentinel (engine._poison_guard's verify twin): a row
        # whose logits went non-finite anywhere in its verify block
        # would otherwise emit a plausible token (argmax over NaN is 0)
        # and silently poison its KV history — map its fresh token to
        # the out-of-vocab sentinel the engine's retire loop already
        # checks for, at zero extra readback.
        ok = jnp.isfinite(logits).all(axis=(1, 2))
        out = jnp.where(ok, out, jnp.int32(V))

        active = state["active"]
        live = active.astype(jnp.int32)
        off = jnp.arange(K + 1)[None, :]
        drafts_pad = jnp.concatenate(
            [drafts, jnp.zeros((S, 1), jnp.int32)], axis=1)
        emitted = jnp.where(off < a[:, None], drafts_pad,
                            jnp.where(off == a[:, None], out[:, None], 0))
        counts = (a + 1) * live
        new_state = dict(state,
                         pos=state["pos"] + (a + 1) * live,
                         tok=jnp.where(active, out, state["tok"]))
        return pool, new_state, emitted, counts, a * live

    # ------------------------------------------------------------------
    def shardcheck_programs(self, mesh, *, aparams, apool, astate,
                            buckets=(), rungs=(), suffix: str = "",
                            expect=None, replicated_io: bool = True,
                            ) -> list:
        """ProgramSpecs for the verify program (and, for a device
        drafter, its draft/draft_prefill programs) — the speculative
        half of Engine.shardcheck_programs. The engine passes the
        abstract pool/state with its OWN placements plus the matching
        expectation: replicated + comms-free for the single-chip
        contract, live TP shardings + budget-pinned comms for a
        tensor-parallel engine (``replicated_io=False`` drops the
        all-replicated jit constraints so the declared shardings win)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from nanosandbox_tpu.analysis.shardcheck import (Expectations,
                                                         ProgramSpec)

        if expect is None:
            expect = Expectations(comms_free=True)
        rep = NamedSharding(mesh, PartitionSpec())
        jit_kwargs = ({"in_shardings": rep, "out_shardings": rep}
                      if replicated_io else {})
        drafts = jax.ShapeDtypeStruct((self.num_slots, self.k), jnp.int32,
                                      sharding=rep)
        dlen = jax.ShapeDtypeStruct((self.num_slots,), jnp.int32,
                                    sharding=rep)
        args = (aparams, apool, astate, drafts, dlen)
        specs = [ProgramSpec(
            name=f"spec_verify{suffix}",
            lower=lambda: jax.jit(self._verify_fn,
                                  **jit_kwargs).lower(*args),
            abstract_args=args,
            expect=expect, tags=("serve", "spec"))]
        if self.drafter.kind == "device":
            specs.extend(self.drafter.shardcheck_programs(
                mesh, buckets=buckets, rungs=rungs, suffix=suffix))
        return specs

    def stats(self) -> dict:
        rate: Optional[float] = (self.accepted / self.drafted
                                 if self.drafted else None)
        return {
            "enabled": True,
            "drafter": type(self.drafter).__name__,
            "k": self.k,
            "verify_steps": self.steps,
            "tokens_drafted": self.drafted,
            "tokens_accepted": self.accepted,
            "acceptance_rate": rate,
        }

    def debug(self) -> dict:
        """The GET /debug/scheduler "spec" block: whether the verify
        step is earning its k, from already-host-resident ints — the
        live counterpart of the bench acceptance numbers."""
        return {**self.stats(),
                "drafter_kind": self.drafter.kind,
                "mean_accepted_per_verify": (self.accepted / self.steps
                                             if self.steps else None)}
