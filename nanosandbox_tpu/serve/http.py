"""Thin stdlib HTTP frontend over the continuous-batching Engine.

The Engine is single-threaded by design; EngineLoop is the ONE thread
that touches it. HTTP handler threads (ThreadingHTTPServer) hand
submissions to the loop through a mutex-guarded inbox and block on a
per-request Event until their tokens come back — so N concurrent
clients become N rows of the same batched decode step, which is the
entire point of the subsystem.

No external web framework: the repo's dependency budget is "what the
image already ships", and http.server is plenty for a JSON
POST /generate + GET /healthz surface. Anything fancier (streaming,
cancellation) belongs behind the same EngineLoop seam.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional


class _Pending:
    def __init__(self, kwargs: dict):
        self.kwargs = kwargs
        self.done = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None


class EngineLoop(threading.Thread):
    """Background thread that owns the Engine: drains the submission
    inbox, steps while any request is in flight, sleeps otherwise."""

    def __init__(self, engine):
        super().__init__(daemon=True, name="serve-engine-loop")
        self.engine = engine
        self._cond = threading.Condition()
        self._inbox: list[_Pending] = []
        self._by_rid: dict[int, _Pending] = {}
        self._stopping = False
        # Set when the loop dies on an engine error: /healthz keys off it
        # so a wedged engine flips the pod NotReady (and the liveness
        # probe restarts it) instead of serving 504s behind a green check.
        self.dead: Optional[str] = None

    def submit(self, **kwargs) -> _Pending:
        """Thread-safe: queue a request for the loop thread; returns a
        pending handle whose .done fires when generation finishes."""
        p = _Pending(kwargs)
        with self._cond:  # dead-check under the lock: no append race
            if self.dead is not None:
                p.error = RuntimeError(f"engine loop died: {self.dead}")
                p.done.set()
            else:
                self._inbox.append(p)
                self._cond.notify()
        return p

    def generate(self, timeout: Optional[float] = None, **kwargs):
        """submit + wait; raises the engine's validation error if any."""
        p = self.submit(**kwargs)
        if not p.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify()

    def stats(self) -> dict:
        """Loop-side in-flight accounting for /stats: requests parked in
        the inbox (not yet submitted to the engine) and requests whose
        waiters are still blocked. With the pipelined engine a result can
        retire a step after its last decode dispatch, so `waiting` may
        exceed the engine's `active` count by the readback lag."""
        with self._cond:
            return {"inbox": len(self._inbox),
                    "waiting": len(self._by_rid),
                    "dead": self.dead}

    def run(self) -> None:
        while True:
            with self._cond:
                while (not self._stopping and not self._inbox
                       and not self.engine.has_work()):
                    self._cond.wait()
                if self._stopping:
                    self._fail_all(RuntimeError("server shutting down"))
                    return
                inbox, self._inbox = self._inbox, []
            for p in inbox:
                try:
                    rid = self.engine.submit(**p.kwargs)
                    self._by_rid[rid] = p
                except Exception as e:  # validation error -> the caller
                    p.error = e
                    p.done.set()
            try:
                results = self.engine.step()
            except Exception as e:
                # An engine failure (device OOM, compile error) wedges
                # every in-flight slot: fail ALL waiters immediately
                # instead of letting them block to timeout, mark the loop
                # dead so health checks go red, and exit.
                self.dead = f"{type(e).__name__}: {e}"
                with self._cond:
                    self._fail_all(RuntimeError(
                        f"engine loop died: {self.dead}"))
                raise
            for res in results:
                p = self._by_rid.pop(res.rid, None)
                if p is not None:
                    p.result = res
                    p.done.set()

    def _fail_all(self, err: Exception) -> None:
        """Signal every waiter — queued AND mid-generation (call with
        self._cond held, or from the dying loop thread)."""
        for p in self._inbox:
            p.error = err
            p.done.set()
        self._inbox = []
        for p in self._by_rid.values():
            p.error = err
            p.done.set()
        self._by_rid = {}


def make_server(host: str, port: int, loop: EngineLoop,
                encode: Callable[[str], list],
                decode: Callable[[list], str],
                request_timeout: float = 300.0) -> ThreadingHTTPServer:
    """HTTP server bound to an EngineLoop.

    POST /generate  {"prompt": str | "prompt_tokens": [int], and any of
                     max_new_tokens, temperature, top_k, top_p, seed,
                     eos_id}  ->  {"id", "tokens", "text",
                     "finish_reason"}
    GET  /healthz   -> {"ok": true}
    GET  /stats     -> engine counters (slots, queue, compiles) plus the
                     latency signal (decode_tokens_per_sec,
                     queue_wait_steps_mean, ttft_s/tpot_s percentiles)
                     and loop in-flight accounting under "loop"
    """

    class Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, obj: dict) -> None:
            body = json.dumps(obj).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # stdout stays metrics-only
            pass

        def do_GET(self):
            if self.path == "/healthz":
                if loop.dead is not None or not loop.is_alive():
                    self._json(503, {"ok": False,
                                     "error": loop.dead or "loop not running"})
                else:
                    self._json(200, {"ok": True})
            elif self.path == "/stats":
                stats = loop.engine.stats()
                stats["loop"] = loop.stats()
                self._json(200, stats)
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path != "/generate":
                self._json(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if "prompt_tokens" in payload:
                    prompt = [int(t) for t in payload["prompt_tokens"]]
                else:
                    prompt = encode(str(payload.get("prompt", ""))) or [0]
                kwargs = dict(
                    prompt=prompt,
                    max_new_tokens=int(payload.get("max_new_tokens", 64)),
                    temperature=float(payload.get("temperature", 0.8)),
                    top_k=int(payload.get("top_k", 0)),
                    top_p=float(payload.get("top_p", 1.0)),
                    seed=int(payload.get("seed", 0)),
                )
                if payload.get("eos_id") is not None:
                    kwargs["eos_id"] = int(payload["eos_id"])
            except (ValueError, TypeError, KeyError,
                    json.JSONDecodeError) as e:
                # KeyError: a char tokenizer raises it for prompt chars
                # outside the training vocab — a client error (400), not
                # a handler crash that closes the socket with no reply.
                self._json(400, {"error": f"bad request: {e!r}"})
                return
            try:
                res = loop.generate(timeout=request_timeout, **kwargs)
            except ValueError as e:       # engine admission rules
                self._json(400, {"error": str(e)})
                return
            except TimeoutError as e:
                self._json(504, {"error": str(e)})
                return
            except RuntimeError as e:     # engine loop died / shutdown
                self._json(503, {"error": str(e)})
                return
            self._json(200, {
                "id": res.rid,
                "tokens": res.tokens,
                "text": decode(list(res.prompt) + res.tokens),
                "finish_reason": res.finish_reason,
            })

    return ThreadingHTTPServer((host, port), Handler)
