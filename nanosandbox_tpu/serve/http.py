"""Thin stdlib HTTP frontend over the continuous-batching Engine.

The Engine is single-threaded by design; EngineLoop is the ONE thread
that touches it. HTTP handler threads (ThreadingHTTPServer) hand
submissions to the loop through a mutex-guarded inbox and block on a
per-request Event until their tokens come back — so N concurrent
clients become N rows of the same batched decode step, which is the
entire point of the subsystem.

The loop can drive either the Engine directly or (production default
via ``python -m nanosandbox_tpu.serve``) a recovery.EngineSupervisor
wrapping it — same ``step()`` surface, but detected faults quarantine
and rebuild instead of killing the loop.

Status hygiene (ISSUE 11): the frontend distinguishes *come back
later* from *go away* —

  429 + Retry-After   deadline/queue expiry (a shed Result): the
                      engine is healthy but this request's patience
                      ran out; the Retry-After derives from the
                      scheduler's queue-wait p50.
  503 (+ Retry-After  quarantine / draining / permanent failure /
   while draining)    loop death: this replica cannot take the
                      request — route elsewhere.
  400                 the request itself is malformed (admission
                      rules); retrying it unchanged can never help.

Every /generate response leaves an ``http`` flight-recorder event with
the returned status, so the black box shows what the CLIENT saw next
to what the engine did.

No external web framework: the repo's dependency budget is "what the
image already ships", and http.server is plenty for a JSON
POST /generate + GET /healthz surface. Anything fancier (streaming,
cancellation) belongs behind the same EngineLoop seam.

This module also hosts the FLEET FRONT TIER (ISSUE 15):
``RouterFrontend``, an asyncio proxy that routes POST /generate across
N replica servers by radix-prefix affinity (serve/router.py — the same
policy class the in-process Fleet harness tests), with health-poll
readiness, failover re-routing, and Retry-After hints aggregated over
the ready replica set. See its docstring and docs/playbook.md "Fleet
routing".
"""

from __future__ import annotations

import asyncio
import json
import math
import socket
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

from nanosandbox_tpu.obs import (MetricRegistry, global_registry,
                                 render_prometheus)


class DrainingError(RuntimeError):
    """Raised to submitters while the loop is draining (POST /drain or
    the k8s preStop hook): finish what's in flight, take nothing new."""


class _Pending:
    def __init__(self, kwargs: dict):
        self.kwargs = kwargs
        self.done = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None


@dataclass
class ExportPayload:
    """What a migrate-flagged /generate waiter receives INSTEAD of a
    Result when its request exports (ISSUE 16): the serialized handoff
    (disagg.export_to_wire). The HTTP layer answers 202 with it; the
    router frontend carries it to a decode-tier replica's
    /internal/adopt and then resolves the handoff exactly-once via
    /internal/export_done."""
    rid: int
    wire: dict


class EngineLoop(threading.Thread):
    """Background thread that owns the Engine: drains the submission
    inbox, steps while any request is in flight, sleeps otherwise.

    ``supervisor`` (recovery.EngineSupervisor) makes stepping
    crash-safe: faults recover in place instead of killing the loop.
    ``drain_now()`` flips the loop into drain mode — in-flight requests
    finish, new submissions get DrainingError (503 upstream), and
    readiness goes red so the fleet stops routing here."""

    def __init__(self, engine, supervisor=None,
                 export_timeout_s: float = 60.0):
        super().__init__(daemon=True, name="serve-engine-loop")
        self.engine = engine
        self.supervisor = supervisor
        self._stepper = supervisor if supervisor is not None else engine
        self._cond = threading.Condition()
        self._inbox: list[_Pending] = []
        self._by_rid: dict[int, _Pending] = {}
        self._calls: list[tuple[Callable, _Pending]] = []
        # rid -> (export record, monotonic stamp): handoffs answered 202
        # and awaiting the frontend's /internal/export_done callback.
        # Reclaimed (requeued colocated) after export_timeout_s so a
        # crashed frontend can't strand a request in limbo forever.
        self._exports: dict[int, tuple] = {}
        self.export_timeout_s = float(export_timeout_s)
        self._stopping = False
        self.draining = False
        # Set when the loop dies on an engine error: /healthz keys off it
        # so a wedged engine flips the pod NotReady (and the liveness
        # probe restarts it) instead of serving 504s behind a green check.
        self.dead: Optional[str] = None

    def submit(self, **kwargs) -> _Pending:
        """Thread-safe: queue a request for the loop thread; returns a
        pending handle whose .done fires when generation finishes."""
        p = _Pending(kwargs)
        with self._cond:  # dead-check under the lock: no append race
            if self.dead is not None:
                p.error = RuntimeError(f"engine loop died: {self.dead}")
                p.done.set()
            elif self.draining:
                p.error = DrainingError(
                    "server draining; retry against another replica")
                p.done.set()
            else:
                self._inbox.append(p)
                self._cond.notify()
        return p

    def generate(self, timeout: Optional[float] = None, **kwargs):
        """submit + wait; raises the engine's validation error if any."""
        p = self.submit(**kwargs)
        if not p.done.wait(timeout):
            raise TimeoutError("generation timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def call(self, fn: Callable, timeout: Optional[float] = 30.0):
        """Run ``fn(engine)`` ON the loop thread and return its result.

        The engine is single-threaded by contract — handler threads must
        never touch it directly. This is the marshal the disagg
        endpoints (/internal/adopt, /internal/export_done) use to mutate
        engine state between steps."""
        p = _Pending({})
        with self._cond:
            if self.dead is not None:
                raise RuntimeError(f"engine loop died: {self.dead}")
            self._calls.append((fn, p))
            self._cond.notify()
        if not p.done.wait(timeout):
            raise TimeoutError("engine loop call timed out")
        if p.error is not None:
            raise p.error
        return p.result

    def adopt(self, wire: dict, *, src: str = ""):
        """Adopt one migrated export on this (decode-tier) engine.

        Returns a waiter handle (_Pending) for the adopted request's
        terminal Result, the Result itself when adoption finishes the
        request immediately (max_new_tokens == 1), or None on adoption
        backpressure (no free slot / no free blocks) — the frontend
        should try another decode replica or fall back."""
        from nanosandbox_tpu.serve.disagg import adopt_from_wire

        def fn(eng):
            got = adopt_from_wire(eng, wire, src=src)
            if got is None:
                return None
            rid, done = got
            if done is not None:
                return done
            p = _Pending({})
            self._by_rid[rid] = p  # loop thread: no lock needed
            return p

        return self.call(fn)

    def export_done(self, rid: int, ok: bool, *, dst: str = "",
                    copied_blocks: int = 0, bytes_moved: int = 0):
        """Resolve one proxied handoff (the frontend's callback after
        the adopt leg). ok=True completes the migration (blocks release
        WITH donation — the warm chain keeps serving prefix hits).
        ok=False requeues the request COLOCATED here under its original
        rid and returns a fresh waiter handle the frontend blocks on for
        the terminal Result. Returns True (completed), a _Pending
        (fallback waiter), or None if the export is unknown — already
        reclaimed by timeout, or never existed."""

        def fn(eng):
            entry = self._exports.pop(rid, None)
            if entry is None:
                return None
            exp, _t0 = entry
            if ok:
                eng.complete_export(
                    exp, dst=dst, blocks_copied=copied_blocks,
                    bytes_moved=bytes_moved,
                    migrate_s=time.monotonic() - exp.export_t)
                return True
            p = _Pending({})
            self._by_rid[rid] = p
            eng.requeue_export(exp)
            return p

        return self.call(fn)

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            self._cond.notify()

    def drain_now(self) -> dict:
        """Begin draining (idempotent): refuse new submissions, keep
        stepping until in-flight work retires. Returns a progress view
        — the k8s preStop hook POSTs /drain and the pod's readiness
        goes false the same instant."""
        with self._cond:
            self.draining = True
            self._cond.notify()
            in_flight = len(self._inbox) + len(self._by_rid)
        eng = self.engine
        return {"draining": True,
                "in_flight": in_flight,
                "engine_active": len(getattr(eng, "_active", {})),
                "queued": getattr(getattr(eng, "sched", None),
                                  "queued", 0),
                "drained": not eng.has_work() and in_flight == 0}

    def is_ready(self) -> tuple[bool, str]:
        """Readiness (k8s ``/healthz?ready=1``): can THIS replica take
        a new request right now? False while draining, quarantined,
        permanently failed, or dead — liveness may still be green (a
        draining pod is healthy, just leaving)."""
        if self.dead is not None:
            return False, f"engine loop died: {self.dead}"
        if not self.is_alive():
            return False, "engine loop not running"
        if self.draining:
            return False, "draining"
        eng = self.engine
        if getattr(eng, "failed", False):
            return False, "engine permanently failed"
        if getattr(eng, "quarantined", False):
            return False, ("quarantined: "
                           f"{getattr(eng, 'quarantine_cause', None)}")
        sup = self.supervisor
        if sup is not None and sup.state != "ok":
            return False, f"supervisor state {sup.state}"
        return True, "ok"

    def is_live(self) -> tuple[bool, str]:
        """Liveness (k8s ``/healthz``): is the process worth keeping?
        False once the loop is dead or the engine permanently failed —
        both are restart-to-fix states."""
        if self.dead is not None:
            return False, f"engine loop died: {self.dead}"
        if not self.is_alive():
            return False, "engine loop not running"
        if getattr(self.engine, "failed", False):
            return False, "engine permanently failed"
        sup = self.supervisor
        if sup is not None and sup.state == "failed":
            return False, "supervisor exhausted recovery"
        return True, "ok"

    def stats(self) -> dict:
        """Loop-side in-flight accounting for /stats: requests parked in
        the inbox (not yet submitted to the engine) and requests whose
        waiters are still blocked. With the pipelined engine a result can
        retire a step after its last decode dispatch, so `waiting` may
        exceed the engine's `active` count by the readback lag."""
        with self._cond:
            out = {"inbox": len(self._inbox),
                   "waiting": len(self._by_rid),
                   "draining": self.draining,
                   "dead": self.dead}
        if self.supervisor is not None:
            out["supervisor"] = self.supervisor.stats()
        return out

    def run(self) -> None:
        while True:
            with self._cond:
                while (not self._stopping and not self._inbox
                       and not self._calls
                       and not self.engine.has_work()):
                    # With pending 202'd handoffs, wake on a timer so a
                    # crashed frontend's exports get reclaimed even if
                    # no new traffic arrives to tick the loop.
                    if not self._cond.wait(
                            1.0 if self._exports else None):
                        break
                if self._stopping:
                    self._fail_all(RuntimeError("server shutting down"))
                    return
                inbox, self._inbox = self._inbox, []
                calls, self._calls = self._calls, []
            for fn, p in calls:
                try:
                    p.result = fn(self.engine)
                except Exception as e:
                    p.error = e
                p.done.set()
            for p in inbox:
                try:
                    rid = self.engine.submit(**p.kwargs)
                    self._by_rid[rid] = p
                except Exception as e:  # validation error -> the caller
                    p.error = e
                    p.done.set()
            try:
                results = self._stepper.step()
            except Exception as e:
                # An engine failure (device OOM, compile error) the
                # supervisor could not absorb wedges every in-flight
                # slot: fail ALL waiters immediately instead of letting
                # them block to timeout, mark the loop dead so health
                # checks go red, and exit.
                self.dead = f"{type(e).__name__}: {e}"
                with self._cond:
                    self._fail_all(RuntimeError(
                        f"engine loop died: {self.dead}"))
                raise
            for res in results:
                p = self._by_rid.pop(res.rid, None)
                if p is not None:
                    p.result = res
                    p.done.set()
            self._pump_exports()

    def _pump_exports(self) -> None:
        """Drain the engine's migration limbo: requests that exported
        this step. A migrate-flagged waiter gets an ExportPayload (the
        HTTP layer answers 202 and the frontend carries the chain to
        the decode tier); an export with NO waiter — direct submit, or
        a client that already timed out — can't be proxied by anyone,
        so it falls straight back to colocated decode here."""
        eng = self.engine
        pop = getattr(eng, "pop_export", None)
        if pop is None:
            return
        now = time.monotonic()
        for rid, (exp, t0) in list(self._exports.items()):
            if now - t0 > self.export_timeout_s:
                # Frontend never called back: reclaim. The client's 202
                # is stale, but the request still resolves exactly once
                # (colocated, under its original rid).
                del self._exports[rid]
                eng.requeue_export(exp)
        while True:
            exp = pop()
            if exp is None:
                return
            p = self._by_rid.pop(exp.req.rid, None)
            if p is None:
                eng.requeue_export(exp)
                continue
            try:
                from nanosandbox_tpu.serve.disagg import export_to_wire
                wire = export_to_wire(eng, exp)
            except Exception:
                self._by_rid[exp.req.rid] = p
                eng.requeue_export(exp)
                continue
            self._exports[exp.req.rid] = (exp, now)
            p.result = ExportPayload(rid=exp.req.rid, wire=wire)
            p.done.set()

    def _fail_all(self, err: Exception) -> None:
        """Signal every waiter — queued AND mid-generation (call with
        self._cond held, or from the dying loop thread)."""
        for p in self._inbox:
            p.error = err
            p.done.set()
        self._inbox = []
        for p in self._by_rid.values():
            p.error = err
            p.done.set()
        self._by_rid = {}


def make_server(host: str, port: int, loop: EngineLoop,
                encode: Callable[[str], list],
                decode: Callable[[list], str],
                request_timeout: float = 300.0) -> ThreadingHTTPServer:
    """HTTP server bound to an EngineLoop.

    POST /generate  {"prompt": str | "prompt_tokens": [int], and any of
                     max_new_tokens, temperature, top_k, top_p, seed,
                     eos_id, deadline_s, slo_class, priority}  ->
                     {"id", "tokens", "text", "finish_reason"}.
                     deadline_s arms SLO accounting + queue-time
                     shedding; slo_class/priority order the scheduler
                     queue (interactive > default > batch) and decide
                     preemption; a shed request returns 429 with its
                     class and a Retry-After derived from the
                     queue-wait p50 scaled by the queue mass ahead of
                     that class; a request lost to permanent engine
                     failure returns 503 with its partial tokens. Every
                     response's status lands in the flight recorder as
                     an ``http`` event. With ``"migrate": true`` the
                     request prefills here and answers **202** with the
                     serialized handoff ({"id", "migrate": true,
                     "export": <wire>}) instead of decoding — the
                     disaggregated path (ISSUE 16).
    POST /internal/adopt  body = the 202 ``export`` payload -> adopt the
                     migrated chain on THIS (decode-tier) engine and
                     block until the request finishes; response is
                     /generate-shaped plus ``adopted: true``. 503 +
                     ``retryable: true`` on adoption backpressure (try
                     another decode replica), 400 on an incompatible
                     payload (fall back colocated at the source).
    POST /internal/export_done  {"rid", "ok", "dst"?, "copied_blocks"?,
                     "bytes"?} -> resolve a 202'd handoff at the source:
                     ok=true completes the migration (chain donated to
                     the prefix cache); ok=false requeues COLOCATED and
                     blocks until the fallback finishes, answering with
                     the final /generate-shaped body. 410 once the
                     handoff was reclaimed by timeout.
    POST /drain     begin graceful drain (idempotent): in-flight work
                     finishes, new /generate gets 503 + Retry-After,
                     readiness goes red. The k8s preStop hook calls
                     this; response reports in-flight counts and
                     ``drained``.
    GET  /healthz   liveness -> {"ok": true} (503 once the loop died or
                     the engine permanently failed — restart-to-fix).
                     ?ready=1 -> READINESS: additionally false (503)
                     while draining or quarantined for recovery, with
                     the reason in the body.
    GET  /stats     -> engine counters (slots, queue, compiles) plus the
                     latency signal (decode_tokens_per_sec,
                     queue_wait_steps_mean, ttft_s/tpot_s percentiles),
                     recovery posture under "recovery", and loop
                     in-flight accounting under "loop"
    GET  /metrics   -> Prometheus text exposition: the engine's registry
                     (throughput, TTFT/TPOT, queue depth, compile
                     traces, spec acceptance, recoveries), the
                     process-global one (host-sync/compile ledgers,
                     warn_once firings) and the loop's in-flight
                     gauges, in one scrape
    GET  /trace     -> Chrome trace-event JSON (Perfetto-loadable).
                     ?rid=N: one request's timeline plus the engine
                     spans overlapping it; ?last_s=S: the trailing S
                     seconds; no params: the whole span ring
    POST /profile   {"steps": N} -> arm a jax.profiler window over the
                     next N engine steps; responds immediately with the
                     trace dir ({"dir", "steps"}), completion shows up
                     in /stats under "profile"
    GET  /debug/requests  flight-recorder lifecycle events.
                     ?rid=N: one request's track (404 unknown);
                     ?last_s=S: trailing window; ?format=jsonl: NDJSON
                     dump instead of the {"events": [...]} JSON view
    GET  /debug/slots     per-slot occupancy (rid, progress, staleness)
    GET  /debug/kvpool    paged-pool block states + fragmentation +
                     radix-trie occupancy ({"paged": false} on dense)
    GET  /debug/scheduler queue composition (per-request wait/deadline/
                     bucket), ladders, shed count, spec acceptance
    """

    # Loop in-flight accounting as gauges, collected per scrape — the
    # same numbers /stats carries under "loop", now scrapable.
    loop_reg = MetricRegistry()
    g_inbox = loop_reg.gauge("serve_loop_inbox_depth",
                             "Requests parked in the loop inbox.")
    g_waiting = loop_reg.gauge("serve_loop_waiting",
                               "Requests whose waiters are still blocked.")
    g_dead = loop_reg.gauge("serve_loop_dead",
                            "1 when the engine loop has died, else 0.")
    g_draining = loop_reg.gauge("serve_loop_draining",
                                "1 while the loop is draining, else 0.")

    def _collect_loop():
        s = loop.stats()
        g_inbox.set(s["inbox"])
        g_waiting.set(s["waiting"])
        g_dead.set(0.0 if s["dead"] is None else 1.0)
        g_draining.set(1.0 if s["draining"] else 0.0)

    loop_reg.add_collector(_collect_loop)

    def _retry_after(slo_class=None) -> int:
        # Priority-aware (ISSUE 13): a shed batch request behind a deep
        # interactive queue gets a hint scaled by the queue mass ahead
        # of its class, not the interactive client's optimistic number.
        try:
            return max(1, math.ceil(
                loop.engine.retry_after_s(slo_class=slo_class)))
        except Exception:
            return 1

    class Handler(BaseHTTPRequestHandler):
        def _json(self, code: int, obj: dict,
                  headers: Optional[dict] = None) -> None:
            self._text(code, json.dumps(obj), "application/json",
                       headers=headers)

        def log_message(self, fmt, *args):  # stdout stays metrics-only
            pass

        def _text(self, code: int, body: str, ctype: str,
                  headers: Optional[dict] = None) -> None:
            data = body.encode()
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(data)))
            for k, v in (headers or {}).items():
                self.send_header(k, str(v))
            self.end_headers()
            self.wfile.write(data)

        def _gen_respond(self, code: int, obj: dict,
                         rid: Optional[int] = None,
                         retry_after: bool = False,
                         slo_class: Optional[str] = None) -> None:
            """/generate response with status hygiene: the flight
            recorder keeps what the client was told, 429/503 carry a
            Retry-After the client can actually obey (scaled by the
            requester's priority class when known)."""
            fl = getattr(loop.engine, "flight", None)
            if fl is not None:
                fl.record("http", rid=rid, status=code)
            headers = ({"Retry-After": _retry_after(slo_class)}
                       if retry_after else None)
            self._json(code, obj, headers=headers)

        def _respond_result(self, res, slo_class=None,
                            extra: Optional[dict] = None) -> None:
            """Terminal Result -> HTTP status + body (shared by
            /generate, /internal/adopt and the /internal/export_done
            fallback leg, so every path a request can resolve through
            speaks the same shapes)."""
            if res.finish_reason == "shed":
                # Deadline expired in the queue (or the brownout ladder
                # is shedding this class): the engine is healthy, THIS
                # request lost — 429, try again when the queue has
                # cleared (Retry-After says when, scaled by the
                # requester's class). tokens are non-empty only for a
                # recovery/preemption-requeued victim shed awaiting
                # re-admission (the salvaged pre-fault output).
                cls = slo_class or "default"
                body = {"error": "shed: deadline expired in the "
                                 "queue (or brownout shed)",
                        "id": res.rid, "tokens": res.tokens,
                        "finish_reason": "shed", "slo_class": cls}
                body.update(extra or {})
                self._gen_respond(429, body, rid=res.rid,
                                  retry_after=True, slo_class=cls)
                return
            if res.finish_reason == "failed":
                # Permanent engine failure drained this request: the
                # partial output is salvaged, but the replica is done —
                # clients should route elsewhere.
                body = {"error": "engine failed during generation",
                        "id": res.rid, "tokens": res.tokens,
                        "finish_reason": "failed"}
                body.update(extra or {})
                self._gen_respond(503, body, rid=res.rid)
                return
            body = {
                "id": res.rid,
                "tokens": res.tokens,
                "text": decode(list(res.prompt) + res.tokens),
                "finish_reason": res.finish_reason,
            }
            digest = getattr(res, "prefix_digest", ())
            if digest:
                # What this replica's radix cache now holds for this
                # prompt — the fleet router ingests these from the
                # response body, so affinity needs no tokenizer and no
                # replica-side push (ISSUE 15).
                body["prefix_digest"] = list(digest)
            body.update(extra or {})
            self._gen_respond(200, body, rid=res.rid)

        def do_GET(self):
            url = urllib.parse.urlsplit(self.path)
            if url.path == "/healthz":
                q = urllib.parse.parse_qs(url.query)
                if q.get("ready", ["0"])[0] not in ("0", "", "false"):
                    ready, reason = loop.is_ready()
                    body = {"ok": ready, "ready": ready,
                            "draining": loop.draining}
                    if not ready:
                        body["reason"] = reason
                    self._json(200 if ready else 503, body)
                    return
                live, reason = loop.is_live()
                if live:
                    self._json(200, {"ok": True})
                else:
                    self._json(503, {"ok": False, "error": reason})
            elif url.path == "/stats":
                stats = loop.engine.stats()
                stats["loop"] = loop.stats()
                self._json(200, stats)
            elif url.path == "/metrics":
                try:
                    body = render_prometheus(loop.engine.metrics,
                                             global_registry(), loop_reg)
                except ValueError as e:
                    # Duplicate family across registries (e.g. an engine
                    # constructed ON the global registry): a diagnosable
                    # 500 beats killing every scrape with a dropped
                    # connection.
                    self._json(500, {"error": str(e)})
                    return
                self._text(200, body,
                           "text/plain; version=0.0.4; charset=utf-8")
            elif url.path == "/trace":
                try:
                    q = urllib.parse.parse_qs(url.query)
                    rid = int(q["rid"][0]) if "rid" in q else None
                    last_s = (float(q["last_s"][0])
                              if "last_s" in q else None)
                except (ValueError, TypeError) as e:
                    self._json(400, {"error": f"bad query: {e!r}"})
                    return
                trace = loop.engine.tracer.export_chrome(rid=rid,
                                                         last_s=last_s)
                # In-flight rids export their OPEN spans (duration-so-
                # far, args.incomplete) — a request stuck in the queue
                # is visible here, so an empty result really does mean
                # unknown/rotated.
                if rid is not None and not trace["traceEvents"]:
                    self._json(404, {"error": f"no spans for rid {rid} "
                                              "(unknown id, or rotated "
                                              "out of the span ring)"})
                    return
                self._json(200, trace)
            elif url.path == "/debug/requests":
                try:
                    q = urllib.parse.parse_qs(url.query)
                    rid = int(q["rid"][0]) if "rid" in q else None
                    last_s = (float(q["last_s"][0])
                              if "last_s" in q else None)
                    fmt = q.get("format", ["json"])[0]
                except (ValueError, TypeError) as e:
                    self._json(400, {"error": f"bad query: {e!r}"})
                    return
                flight = loop.engine.flight
                if rid is not None and not flight.events(rid=rid):
                    self._json(404, {"error": f"no flight events for rid "
                                              f"{rid} (unknown id, or "
                                              "rotated out of the ring)"})
                    return
                if fmt == "jsonl":
                    self._text(200, flight.to_jsonl(rid=rid, last_s=last_s),
                               "application/x-ndjson")
                else:
                    self._json(200, {"events": flight.events(
                        rid=rid, last_s=last_s)})
            elif url.path == "/debug/prefix_summary":
                # The fleet router's authoritative index refresh
                # (ISSUE 15): chained fingerprints of every resident
                # radix-cache chain prefix. Host bookkeeping only —
                # but unlike the snapshot-reading /debug views, this
                # WALKS the radix trie (cache.digests() iterates live
                # children dicts the loop thread grows and evicts), so
                # it must run ON the loop thread via the call() marshal:
                # a handler-thread walk racing insert_chain/evict dies
                # with "dictionary changed size during iteration"
                # (schedcheck finding, fuzz_engine_loop).
                try:
                    summary = loop.call(
                        lambda eng: eng.prefix_summary())
                except RuntimeError:
                    # Loop dead: nothing mutates the trie anymore, so
                    # a direct read is safe and keeps the endpoint
                    # usable for post-mortems.
                    summary = loop.engine.prefix_summary()
                except TimeoutError:
                    self._json(503, {"error": "engine loop busy; "
                                              "retry prefix_summary"})
                    return
                self._json(200, summary)
            elif url.path == "/debug/slots":
                self._json(200, loop.engine.debug_slots())
            elif url.path == "/debug/kvpool":
                self._json(200, loop.engine.debug_kvpool())
            elif url.path == "/debug/scheduler":
                self._json(200, loop.engine.debug_scheduler())
            else:
                self._json(404, {"error": f"no route {self.path}"})

        def do_POST(self):
            if self.path == "/drain":
                self._json(200, {"ok": True, **loop.drain_now()})
                return
            if self.path == "/profile":
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    payload = json.loads(self.rfile.read(n) or b"{}")
                    if not isinstance(payload, dict):
                        raise ValueError(
                            f"body must be a JSON object, got "
                            f"{type(payload).__name__}")
                    if payload.get("cancel"):
                        cancelled = loop.engine.cancel_profile()
                        self._json(200, {"ok": True,
                                         "cancelled": cancelled})
                        return
                    # No client-supplied dir: this endpoint is
                    # unauthenticated, and a caller-chosen path would be
                    # a remote mkdir/file-write primitive inside the pod.
                    # The engine picks a fresh tempdir; the response
                    # says where.
                    steps = int(payload.get("steps", 20))
                    res = loop.engine.request_profile(steps)
                except (ValueError, TypeError,
                        json.JSONDecodeError) as e:
                    self._json(400, {"error": f"bad request: {e!r}"})
                    return
                except RuntimeError as e:   # window already in progress
                    self._json(409, {"error": str(e)})
                    return
                self._json(200, {"ok": True, **res})
                return
            if self.path == "/internal/adopt":
                self._do_internal_adopt()
                return
            if self.path == "/internal/export_done":
                self._do_internal_export_done()
                return
            if self.path != "/generate":
                self._json(404, {"error": f"no route {self.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if "prompt_tokens" in payload:
                    prompt = [int(t) for t in payload["prompt_tokens"]]
                else:
                    prompt = encode(str(payload.get("prompt", ""))) or [0]
                kwargs = dict(
                    prompt=prompt,
                    max_new_tokens=int(payload.get("max_new_tokens", 64)),
                    temperature=float(payload.get("temperature", 0.8)),
                    top_k=int(payload.get("top_k", 0)),
                    top_p=float(payload.get("top_p", 1.0)),
                    seed=int(payload.get("seed", 0)),
                )
                if payload.get("eos_id") is not None:
                    kwargs["eos_id"] = int(payload["eos_id"])
                if payload.get("deadline_s") is not None:
                    kwargs["deadline_s"] = float(payload["deadline_s"])
                if payload.get("slo_class") is not None:
                    kwargs["slo_class"] = str(payload["slo_class"])
                if payload.get("priority") is not None:
                    kwargs["priority"] = int(payload["priority"])
                if payload.get("migrate"):
                    # Disaggregated serving (ISSUE 16): run the prefill
                    # here, then answer 202 with the serialized block
                    # chain instead of decoding — the router frontend
                    # carries it to the decode tier.
                    kwargs["migrate"] = True
            except (ValueError, TypeError, KeyError,
                    json.JSONDecodeError) as e:
                # KeyError: a char tokenizer raises it for prompt chars
                # outside the training vocab — a client error (400), not
                # a handler crash that closes the socket with no reply.
                self._gen_respond(400, {"error": f"bad request: {e!r}"})
                return
            try:
                res = loop.generate(timeout=request_timeout, **kwargs)
            except ValueError as e:       # engine admission rules
                self._gen_respond(400, {"error": str(e)})
                return
            except TimeoutError as e:
                self._gen_respond(504, {"error": str(e)})
                return
            except DrainingError as e:
                self._gen_respond(503, {"error": str(e)},
                                  retry_after=True)
                return
            except RuntimeError as e:     # loop died / engine failed
                self._gen_respond(503, {"error": str(e)})
                return
            if isinstance(res, ExportPayload):
                # The request exported: its block chain + first token +
                # seed are the response. 202 = accepted, not finished —
                # the caller (normally the RouterFrontend) must resolve
                # it via the decode tier + /internal/export_done, or
                # this pod reclaims the handoff after export_timeout_s.
                self._gen_respond(202, {"id": res.rid, "migrate": True,
                                        "export": res.wire},
                                  rid=res.rid)
                return
            self._respond_result(res, slo_class=kwargs.get("slo_class"))

        def _do_internal_adopt(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                wire = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(wire, dict) or "leaves" not in wire:
                    raise ValueError("body must be an export payload "
                                     "(disagg.export_to_wire)")
                src = str(wire.get("src", ""))
            except (ValueError, TypeError, json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e!r}"})
                return
            try:
                got = loop.adopt(wire, src=src)
            except (ValueError, KeyError) as e:
                # Malformed/incompatible payload (wrong pool geometry,
                # out-of-vocab first token): the SOURCE should fall
                # back colocated, not retry another decode replica.
                self._json(400, {"error": f"bad export payload: {e!r}"})
                return
            except (RuntimeError, TimeoutError) as e:
                self._json(503, {"error": str(e)})
                return
            if got is None:
                # Adoption backpressure — no free slot or blocks.
                # retryable=True tells the frontend to try another
                # decode replica before falling back.
                self._json(503, {"error": "adoption backpressure: "
                                          "no free slot/blocks",
                                 "retryable": True},
                           headers={"Retry-After": _retry_after()})
                return
            if isinstance(got, _Pending):
                if not got.done.wait(request_timeout):
                    self._json(504, {"error": "generation timed out"})
                    return
                if got.error is not None:
                    self._json(503, {"error": str(got.error)})
                    return
                res = got.result
            else:
                res = got   # finished at admission (max_new_tokens==1)
            self._respond_result(res, slo_class=wire.get("slo_class"),
                                 extra={"adopted": True})

        def _do_internal_export_done(self):
            try:
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                rid = int(payload["rid"])
                ok = bool(payload.get("ok"))
                dst = str(payload.get("dst", ""))
                copied = int(payload.get("copied_blocks", 0))
                nbytes = int(payload.get("bytes", 0))
            except (ValueError, TypeError, KeyError,
                    json.JSONDecodeError) as e:
                self._json(400, {"error": f"bad request: {e!r}"})
                return
            try:
                got = loop.export_done(rid, ok, dst=dst,
                                       copied_blocks=copied,
                                       bytes_moved=nbytes)
            except (RuntimeError, TimeoutError) as e:
                self._json(503, {"error": str(e)})
                return
            if got is None:
                # Already reclaimed by timeout (or never ours): the
                # request is resolving colocated here regardless.
                self._json(410, {"error": f"unknown export rid {rid} "
                                          "(reclaimed or never "
                                          "exported)"})
                return
            if got is True:
                self._json(200, {"ok": True, "id": rid})
                return
            # ok=False: the request was requeued colocated; block for
            # its terminal Result so the frontend can answer the client
            # from this one response (exactly-once, no second round).
            if not got.done.wait(request_timeout):
                self._json(504, {"error": "generation timed out"})
                return
            if got.error is not None:
                self._json(503, {"error": str(got.error)})
                return
            self._respond_result(got.result,
                                 extra={"migrate_fallback": True})

    return ThreadingHTTPServer((host, port), Handler)


# ---------------------------------------------------------------------------
# Fleet router front tier (ISSUE 15): an asyncio HTTP proxy over N
# engine-replica base URLs, routing POST /generate by radix-prefix
# affinity (serve/router.py — the SAME policy class the in-process
# Fleet harness tests) with health-poll-driven readiness, failover
# re-routing, and aggregated Retry-After hints. asyncio rather than
# another thread-per-request server: the front tier holds hundreds of
# in-flight proxied requests that are each 99% waiting on a replica
# socket — an event loop carries that with one thread, and the
# blocking urllib legs run on the default executor pool.
# ---------------------------------------------------------------------------

def _http_json(url: str, *, method: str = "GET", body: Optional[dict]
               = None, timeout: float = 5.0) -> tuple[int, dict, dict]:
    """One blocking JSON HTTP call -> (status, body, headers). HTTP
    error statuses return normally (the proxy forwards them); only
    transport failures raise (URLError / timeout / bad JSON)."""
    data = None if body is None else json.dumps(body).encode()
    req = urllib.request.Request(url, data=data, method=method,
                                 headers={"Content-Type":
                                          "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read() or b"{}"), dict(
                r.headers)
    except urllib.error.HTTPError as e:
        raw = e.read()
        try:
            payload = json.loads(raw or b"{}")
        except json.JSONDecodeError:
            payload = {"error": raw.decode(errors="replace")}
        return e.code, payload, dict(e.headers or {})


def resolve_replicas(spec: str, default_port: int = 8000) -> List[str]:
    """Expand one --replicas entry into base URLs. Plain http://host:port
    entries pass through; ``dns+http://name:port`` resolves the name's
    A records (a k8s HEADLESS Service: one record per ready pod) into
    one URL per address — re-resolved every health interval, which is
    how the router tracks scale-up/down without redeploys."""
    if not spec.startswith("dns+http://"):
        return [spec.rstrip("/")]
    hostport = spec[len("dns+http://"):].rstrip("/")
    host, _, port = hostport.partition(":")
    port = int(port or default_port)
    try:
        infos = socket.getaddrinfo(host, port, proto=socket.IPPROTO_TCP)
    except OSError:
        return []
    addrs = sorted({info[4][0] for info in infos})
    # Bracket IPv6 literals (dual-stack headless Services return AAAA
    # records too) — an unbracketed v6 host:port is not a URL.
    return [f"http://[{a}]:{port}" if ":" in a else f"http://{a}:{port}"
            for a in addrs]


class RouterFrontend:
    """Prefix-affinity routing proxy over replica base URLs.

    Lifecycle: construct, ``start()`` (binds and spawns the event-loop
    thread; ``port`` is the bound port), ``stop()``. Per replica, every
    ``health_interval_s``: GET /healthz?ready=1 (readiness — a
    draining/quarantined/dead replica leaves rotation within one
    interval), GET /stats (queue depth, active rows, brownout level,
    the replica's own retry_after_s estimate), and
    GET /debug/prefix_summary (the authoritative radix digests the
    approximate router index refreshes from).

    POST /generate proxies to the routed replica. Affinity needs the
    prompt's digest chain, which needs token ids: requests carrying
    ``prompt_tokens`` route by affinity; text-only prompts (tokenized
    replica-side) route by load — documented, not hidden. A transport
    failure or 503 marks the replica not-ready and re-routes
    (``fallback``) until the ready set is exhausted; 429/503 responses
    carry a Retry-After aggregated as the MIN over ready replicas'
    polled estimates (never just the shedding replica's) and a body
    naming the ready ``replica_set`` size.

    Disaggregated serving (ISSUE 16): replicas announce their tier via
    /stats ("role": prefill | decode | both). While BOTH tiers have a
    ready member, /generate becomes a two-leg migration proxy — leg 1
    routes phase="prefill" with the migrate flag and gets a 202 export
    (the paged block chain as the wire format); leg 2 carries it to a
    decode replica's /internal/adopt and confirms at the source with
    /internal/export_done (adopt exhaustion => ok=false, the source
    requeues colocated — the client always gets exactly one answer).
    Mixed rollouts and tier outages degrade to the legacy colocated
    flow automatically.

    Own endpoints: GET /healthz[?ready=1] (ready while >= 1 replica
    is), GET /debug/router (router + per-replica view), GET /metrics
    (the serve_router_* families plus the serve_migrations ledger).
    """

    def __init__(self, replicas: List[str], *, host: str = "0.0.0.0",
                 port: int = 8000, page: int = 16,
                 health_interval_s: float = 2.0,
                 request_timeout_s: float = 300.0,
                 affinity: bool = True, index_cap: int = 8192,
                 default_port: int = 8000):
        from nanosandbox_tpu.serve.router import PrefixAffinityRouter

        self._specs = list(replicas)
        self.host = host
        self.port = port
        self.page = int(page)
        self.health_interval_s = float(health_interval_s)
        self.request_timeout_s = float(request_timeout_s)
        self.default_port = int(default_port)
        urls: List[str] = []
        for spec in self._specs:
            urls.extend(resolve_replicas(spec, default_port))
        self.metrics = MetricRegistry()
        # Disaggregated serving (ISSUE 16): the frontend is the
        # migration proxy, so the migration ledger lives here too —
        # same family names the in-process DisaggPair exposes.
        self._m_migrations = self.metrics.counter(
            "serve_migrations_total",
            "Prefill->decode migrations proxied, by outcome.",
            labelnames=("outcome",))
        self._m_migration_s = self.metrics.histogram(
            "serve_migration_seconds",
            "Wall seconds from 202 export to decode-tier adoption.")
        self.router = PrefixAffinityRouter(
            urls or ["http://unresolved.invalid:0"], page=page,
            affinity=affinity, index_cap=index_cap,
            metrics=self.metrics)
        if not urls:
            self.router.remove_replica("http://unresolved.invalid:0")
        self._retry_by_replica: Dict[str, float] = {}
        # Proxy legs block a thread for the request's whole generation
        # (up to request_timeout_s): give them their OWN pool so long
        # decodes can never starve the health polls — which run on the
        # loop's default executor — out of their interval (the
        # "leaves rotation within one health interval" contract).
        from concurrent.futures import ThreadPoolExecutor

        self._proxy_pool = ThreadPoolExecutor(
            max_workers=64, thread_name_prefix="router-proxy")
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._started = threading.Event()
        self._stopping: Optional[asyncio.Event] = None

    # ------------------------------------------------------------ health
    def _poll_replica(self, url: str) -> None:
        """One replica's health refresh (blocking; runs on the
        executor). Any transport failure = not ready. Per-call timeout
        is capped at the health interval: a BLACK-HOLED replica (node
        gone, connections hang instead of refusing) must still leave
        rotation within ~one interval, not after 3 x 5s of sequential
        hangs — the 'leaves rotation within one health interval'
        contract is only as tight as this timeout."""
        t = max(0.25, min(5.0, self.health_interval_s))
        try:
            st, body, _ = _http_json(f"{url}/healthz?ready=1", timeout=t)
            ready = st == 200 and bool(body.get("ready", body.get("ok")))
            reason = body.get("reason", "ok" if ready else "not ready")
            queued = active = brownout = 0
            role = None
            if ready:
                _, stats, _ = _http_json(f"{url}/stats", timeout=t)
                queued = int(stats.get("queued", 0))
                active = int(stats.get("active", 0))
                bo = stats.get("brownout") or {}
                brownout = int(bo.get("level", 0))
                # Phase discovery (ISSUE 16): replicas announce their
                # tier in /stats ("prefill"/"decode"/"both"); the router
                # grows its phase dimension from the polls, no separate
                # registration step.
                r = stats.get("role")
                if r in ("both", "prefill", "decode"):
                    role = r
                retry = stats.get("retry_after_s")
                if retry is not None:
                    self._retry_by_replica[url] = float(retry)
                _, summary, _ = _http_json(f"{url}/debug/prefix_summary",
                                           timeout=t)
                self.router.refresh_summary(
                    url, summary.get("digests") or [])
        except Exception as e:       # noqa: BLE001 — any poll failure
            ready, reason = False, f"unreachable: {type(e).__name__}"
            queued = active = brownout = 0
            role = None
        self.router.update_replica(url, ready=ready, reason=reason,
                                   queued=queued, active=active,
                                   brownout=brownout, role=role,
                                   retry_after_s=self._retry_by_replica
                                   .get(url))

    async def _health_loop(self) -> None:
        loop = asyncio.get_running_loop()
        while not self._stopping.is_set():
            urls: List[str] = []
            for spec in self._specs:
                urls.extend(await loop.run_in_executor(
                    None, resolve_replicas, spec, self.default_port))
            if urls:
                for url in urls:
                    self.router.add_replica(url)
                for known in list(self.router.replicas):
                    if known not in urls:
                        self.router.remove_replica(known)
            else:
                # A resolver blip (kube-dns restart, transient timeout)
                # must not deregister the whole fleet — that would turn
                # one failed lookup into a full 503 outage AND discard
                # every warm prefix index. Keep the known set; the
                # per-replica polls below mark truly-dead ones
                # not-ready, which is the correct degradation.
                urls = list(self.router.replicas)
            await asyncio.gather(*(
                loop.run_in_executor(None, self._poll_replica, url)
                for url in urls), return_exceptions=True)
            try:
                await asyncio.wait_for(self._stopping.wait(),
                                       self.health_interval_s)
            except asyncio.TimeoutError:
                pass

    def retry_after_s(self) -> float:
        """Aggregate backoff hint: min over READY replicas of their
        own polled estimates (satellite 2) — fallback 1s when cold."""
        ready = self.router.ready_replicas()
        vals = [self._retry_by_replica[r] for r in ready
                if r in self._retry_by_replica]
        return min(vals) if vals else 1.0

    # ------------------------------------------------------------- serve
    async def _respond(self, writer: asyncio.StreamWriter, code: int,
                       body: dict, headers: Optional[dict] = None
                       ) -> None:
        phrase = {200: "OK", 202: "Accepted", 400: "Bad Request",
                  404: "Not Found", 410: "Gone",
                  429: "Too Many Requests", 502: "Bad Gateway",
                  503: "Service Unavailable",
                  504: "Gateway Timeout"}.get(code, "OK")
        data = json.dumps(body).encode()
        head = [f"HTTP/1.1 {code} {phrase}",
                "Content-Type: application/json",
                f"Content-Length: {len(data)}", "Connection: close"]
        for k, v in (headers or {}).items():
            head.append(f"{k}: {v}")
        writer.write(("\r\n".join(head) + "\r\n\r\n").encode() + data)
        await writer.drain()
        writer.close()

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request_line = (await reader.readline()).decode()
            parts = request_line.split()
            if len(parts) < 2:
                writer.close()
                return
            method, path = parts[0], parts[1]
            length = 0
            while True:
                line = await reader.readline()
                if line in (b"\r\n", b"\n", b""):
                    break
                name, _, value = line.decode().partition(":")
                if name.strip().lower() == "content-length":
                    length = int(value.strip())
            raw = await reader.readexactly(length) if length else b""
            await self._route_request(method, path, raw, writer)
        except (ConnectionError, asyncio.IncompleteReadError):
            writer.close()
        except Exception as e:      # noqa: BLE001 — proxy must answer
            try:
                await self._respond(writer, 502,
                                    {"error": f"router error: {e!r}"})
            except ConnectionError:
                writer.close()

    async def _route_request(self, method: str, path: str, raw: bytes,
                             writer: asyncio.StreamWriter) -> None:
        url = urllib.parse.urlsplit(path)
        if method == "GET" and url.path == "/healthz":
            ready = bool(self.router.ready_replicas())
            body = {"ok": ready, "ready": ready,
                    "replica_set": len(self.router.ready_replicas()),
                    "replicas": len(self.router.replicas)}
            await self._respond(writer, 200 if ready else 503, body)
            return
        if method == "GET" and url.path == "/debug/router":
            await self._respond(writer, 200, {
                "router": self.router.stats(),
                "retry_after_s": self.retry_after_s(),
                "health_interval_s": self.health_interval_s})
            return
        if method == "GET" and url.path == "/metrics":
            data = render_prometheus(self.metrics).encode()
            writer.write(
                b"HTTP/1.1 200 OK\r\nContent-Type: text/plain; "
                b"version=0.0.4; charset=utf-8\r\nContent-Length: "
                + str(len(data)).encode()
                + b"\r\nConnection: close\r\n\r\n" + data)
            await writer.drain()
            writer.close()
            return
        if method != "POST" or url.path != "/generate":
            await self._respond(writer, 404,
                                {"error": f"no route {path}"})
            return
        try:
            payload = json.loads(raw or b"{}")
            if not isinstance(payload, dict):
                raise ValueError("body must be a JSON object")
        except (ValueError, json.JSONDecodeError) as e:
            await self._respond(writer, 400,
                                {"error": f"bad request: {e!r}"})
            return
        chain: List[str] = []
        if isinstance(payload.get("prompt_tokens"), list) and self.page:
            from nanosandbox_tpu.serve.paged import prefix_digests
            try:
                chain = prefix_digests(
                    [int(t) for t in payload["prompt_tokens"]], self.page)
            except (TypeError, ValueError):
                chain = []
        await self._proxy_generate(payload, chain, writer)

    def _phase_tiering(self) -> bool:
        """True when the fleet is actually disaggregated RIGHT NOW: at
        least one ready prefill-role replica AND one ready decode-role
        replica. Anything less (mixed rollout, decode tier down) routes
        the legacy colocated way — graceful degradation, not an
        outage."""
        views = self.router.replicas
        ready = [views[n] for n in self.router.ready_replicas()
                 if n in views]
        return (any(r.role == "prefill" for r in ready)
                and any(r.role == "decode" for r in ready))

    async def _proxy_generate(self, payload: dict, chain: List[str],
                              writer: asyncio.StreamWriter) -> None:
        from nanosandbox_tpu.serve.router import NoReadyReplicaError

        loop = asyncio.get_running_loop()
        tried: set = set()
        slo = payload.get("slo_class")
        # Disaggregated two-leg flow (ISSUE 16): with both tiers ready,
        # leg 1 routes phase="prefill" with the migrate flag; the 202
        # export it answers with becomes leg 2's /internal/adopt body.
        tiered = self._phase_tiering() and not payload.get(
            "_no_migrate")
        while True:
            try:
                dec = self.router.route(
                    chain, exclude=tried, failover=bool(tried),
                    phase="prefill" if tiered else None)
            except NoReadyReplicaError as e:
                if tiered:
                    # The prefill tier emptied mid-flight: retry the
                    # whole ready set colocated before giving up.
                    tiered = False
                    continue
                await self._respond(
                    writer, 503,
                    {"error": str(e), "replica_set": 0,
                     "tried": sorted(tried)},
                    {"Retry-After": max(1, math.ceil(
                        self.retry_after_s()))})
                return
            name = dec.replica
            if chain:
                # Optimistic insert (the Fleet.submit comment): a
                # same-prefix follower in the same burst must route
                # here too, not wait for this request to finish.
                self.router.observe_digests(name, chain)
            body_out = payload
            if tiered:
                body_out = {k: v for k, v in payload.items()
                            if k != "_no_migrate"}
                body_out["migrate"] = True
            try:
                status, body, headers = await loop.run_in_executor(
                    self._proxy_pool, lambda: _http_json(
                        f"{name}/generate", method="POST",
                        body=body_out,
                        timeout=self.request_timeout_s))
            except Exception as e:   # noqa: BLE001 — transport failure
                self.router.update_replica(
                    name, ready=False,
                    reason=f"unreachable: {type(e).__name__}")
                tried.add(name)
                continue
            if status == 202 and isinstance(body.get("export"), dict):
                await self._migrate_leg(name, body, chain, writer)
                return
            if status == 503:
                # This replica is leaving (drain/quarantine/failure):
                # out of rotation now, re-route the request.
                self.router.update_replica(name, ready=False,
                                           reason="503 from replica")
                tried.add(name)
                continue
            body.setdefault("replica", name)
            extra_headers = {}
            if status == 429:
                # Aggregated hint: the retrying client will be routed
                # to the BEST replica, so the fleet-wide minimum is the
                # binding number, not the shedding replica's own.
                ready = self.router.ready_replicas()
                body["replica_set"] = len(ready)
                agg = self.retry_after_s()
                own = self._retry_by_replica.get(name)
                if own is not None:
                    agg = min(agg, own)
                extra_headers["Retry-After"] = max(1, math.ceil(agg))
            elif "Retry-After" in headers:
                extra_headers["Retry-After"] = headers["Retry-After"]
            if status == 200 and body.get("prefix_digest"):
                self.router.observe_digests(
                    name, list(body["prefix_digest"]))
            await self._respond(writer, status, body, extra_headers)
            return

    async def _migrate_leg(self, src: str, export_body: dict,
                           chain: List[str],
                           writer: asyncio.StreamWriter) -> None:
        """Leg 2 of the disaggregated flow: carry the 202 export from
        ``src`` (the prefill replica) to a decode-tier replica's
        /internal/adopt, then resolve the handoff at the source via
        /internal/export_done. Exactly-once: the source keeps the
        export parked until the callback — adopt success completes it,
        adopt exhaustion makes ok=false requeue it COLOCATED at the
        source, and the frontend answers the client from whichever leg
        actually finished."""
        from nanosandbox_tpu.serve.router import NoReadyReplicaError

        loop = asyncio.get_running_loop()
        wire = export_body["export"]
        rid = export_body.get("id")
        # Payload size ~= the transferred chain: base64 is 4/3 overhead.
        nbytes = sum(len(leaf.get("data", "")) * 3 // 4
                     for leaf in wire.get("leaves", [])
                     if isinstance(leaf, dict))
        t0 = time.monotonic()
        tried = {src}
        while True:
            try:
                dec = self.router.route(chain, exclude=tried,
                                        failover=len(tried) > 1,
                                        phase="decode")
            except NoReadyReplicaError:
                break
            name = dec.replica
            try:
                status, body, headers = await loop.run_in_executor(
                    self._proxy_pool, lambda: _http_json(
                        f"{name}/internal/adopt", method="POST",
                        body=wire, timeout=self.request_timeout_s))
            except Exception as e:   # noqa: BLE001 — transport failure
                self.router.update_replica(
                    name, ready=False,
                    reason=f"unreachable: {type(e).__name__}")
                tried.add(name)
                continue
            if status in (200, 429):
                # Adopted: the decode tier resolved the request (a 429
                # is a post-adoption shed — still terminal THERE).
                # Confirm at the source so it releases the chain WITH
                # donation; best-effort — a lost callback self-heals by
                # the source's export timeout.
                try:
                    await loop.run_in_executor(
                        self._proxy_pool, lambda: _http_json(
                            f"{src}/internal/export_done", method="POST",
                            body={"rid": rid, "ok": True, "dst": name,
                                  "copied_blocks": int(
                                      wire.get("chain_blocks", 0)),
                                  "bytes": nbytes},
                            timeout=10.0))
                except Exception:    # noqa: BLE001 — callback is advisory
                    pass
                self._m_migrations.labels(outcome="ok").inc()
                self._m_migration_s.observe(time.monotonic() - t0)
                body.setdefault("replica", name)
                body["migrated_from"] = src
                if status == 200 and body.get("prefix_digest"):
                    self.router.observe_digests(
                        name, list(body["prefix_digest"]))
                fwd = ({"Retry-After": headers["Retry-After"]}
                       if "Retry-After" in headers else None)
                await self._respond(writer, status, body, fwd)
                return
            if status == 503 and body.get("retryable"):
                tried.add(name)      # backpressure: stays in rotation
                continue
            if status == 503:
                self.router.update_replica(name, ready=False,
                                           reason="503 from replica")
                tried.add(name)
                continue
            break                    # 400/unknown: fall back colocated
        # No decode replica could adopt: ok=false tells the source to
        # requeue colocated (same rid, pure prefix hit) and the call
        # blocks until that fallback finishes — the client still gets
        # exactly one answer.
        self._m_migrations.labels(outcome="fallback").inc()
        try:
            status, body, headers = await loop.run_in_executor(
                self._proxy_pool, lambda: _http_json(
                    f"{src}/internal/export_done", method="POST",
                    body={"rid": rid, "ok": False},
                    timeout=self.request_timeout_s))
        except Exception as e:       # noqa: BLE001 — source died too
            self._m_migrations.labels(outcome="failed").inc()
            await self._respond(
                writer, 502,
                {"error": f"migration fallback failed: {e!r}",
                 "id": rid})
            return
        body.setdefault("replica", src)
        fwd = ({"Retry-After": headers["Retry-After"]}
               if "Retry-After" in headers else None)
        await self._respond(writer, status, body, fwd)
        return

    # ---------------------------------------------------------- lifecycle
    async def _main(self) -> None:
        # Publish-once fields: written exactly once here on the router
        # loop, strictly BEFORE the _started barrier below — start()
        # blocks on that Event, so every other thread (stop(), tests
        # reading .port) observes the final values.
        # lockcheck: disable=unguarded-shared-write -- single
        # assignment sequenced before the _started.set() barrier;
        # readers only run after start() returns.
        self._stopping = asyncio.Event()
        server = await asyncio.start_server(self._handle, self.host,
                                            self.port)
        # lockcheck: disable=unguarded-shared-write -- same _started
        # barrier as _stopping above: bound-port readback is published
        # before any reader can exist.
        self.port = server.sockets[0].getsockname()[1]
        health = asyncio.create_task(self._health_loop())
        self._started.set()
        async with server:
            await self._stopping.wait()
        health.cancel()

    def start(self) -> "RouterFrontend":
        """Bind + serve on a daemon thread; returns self once the port
        is bound (tests pass port=0 and read .port)."""

        def run():
            self._loop = asyncio.new_event_loop()
            asyncio.set_event_loop(self._loop)
            try:
                self._loop.run_until_complete(self._main())
            finally:
                self._loop.close()

        self._thread = threading.Thread(target=run, daemon=True,
                                        name="serve-router")
        self._thread.start()
        if not self._started.wait(10):
            raise RuntimeError("router frontend failed to start")
        return self

    def stop(self) -> None:
        if self._loop is not None and self._stopping is not None:
            self._loop.call_soon_threadsafe(self._stopping.set)
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._proxy_pool.shutdown(wait=False)
