"""Draft-token proposers for speculative decoding (serve/spec.py).

A drafter's only job is to guess the next ``k`` tokens of a request
cheaply; the verify step (one batched target-model forward over the
k+1-token block) then accepts the longest correct prefix and samples
one more token, so a WRONG draft costs nothing but the wasted draft
work — outputs are provably distributed exactly as non-speculative
decoding (greedy drafts are point-mass proposals, for which the
Leviathan et al. rejection rule reduces to: accept token d with
probability p(d), else resample from p with d's mass removed).

Two backends, one protocol:

  * ``NGramDrafter`` (kind='host') — prompt-lookup drafting (the
    tokenizer-free scheme HF assisted generation popularized): the
    request's own context (prompt + generated tokens) is scanned for
    the most recent earlier occurrence of its trailing n-gram and the
    tokens that followed it are proposed. Zero extra weights, zero
    device programs, CPU-testable; shines on repetitive/extractive
    workloads (code, structured text, summarization-with-quoting).

  * ``ModelDrafter`` (kind='device') — a smaller GPT sharing the
    target's tokenizer, run greedily for k steps against its OWN
    slot-pool KV cache (same fixed-shape discipline as the engine:
    one compiled draft program, drafter prefills bounded by the same
    admit-ladder x bucket grid). The drafter's frontier needs no
    separate bookkeeping: it consumes the engine's device-resident
    (pos, tok, active) state, so verification rollback is simply the
    engine not advancing pos past the accepted prefix.

The host-side protocol is deliberately tiny (``kind``, ``k``, and
``propose(context, max_tokens)`` for host drafters) so tests can plug
in adversarial drafters (e.g. always-wrong proposals pin the
full-reject rollback path).
"""

from __future__ import annotations

from typing import List, Optional, Sequence


class NGramDrafter:
    """Prompt-lookup drafting: propose the continuation of the most
    recent earlier occurrence of the context's trailing n-gram.

    ``max_ngram`` is the longest suffix tried (longest first — a longer
    match is stronger evidence the continuation repeats); matching
    prefers the MOST RECENT earlier occurrence (locality: loops and
    boilerplate repeat at short range). A match at distance d from the
    context end supplies only d literal continuation tokens; the
    proposal is extended to the full budget by CYCLING those d tokens
    (exact for text of period d, e.g. a degenerate greedy loop — and a
    wrong guess costs nothing: the verify block is the same fixed shape
    whether a draft slot holds a hot guess or filler, acceptance just
    stops at the first miss). Always returns the full budget when any
    match exists; returns [] on no match — the engine then verifies
    that row with draft length 0, which degrades to exactly one
    ordinary decode step, so mixed hit/miss batches never stall.
    """

    kind = "host"

    def __init__(self, k: int = 4, max_ngram: int = 3):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        if max_ngram < 1:
            raise ValueError(f"max_ngram must be >= 1, got {max_ngram}")
        self.k = int(k)
        self.max_ngram = int(max_ngram)

    def propose(self, context: Sequence[int],
                max_tokens: Optional[int] = None) -> List[int]:
        cap = self.k if max_tokens is None else min(self.k, max_tokens)
        n_ctx = len(context)
        if cap <= 0 or n_ctx < 2:
            return []
        context = list(context)
        for n in range(min(self.max_ngram, n_ctx - 1), 0, -1):
            suffix = context[n_ctx - n:]
            # Most recent earlier occurrence: scan right-to-left.
            for start in range(n_ctx - n - 1, -1, -1):
                if context[start:start + n] == suffix:
                    m = start + n          # continuation begins here
                    d = n_ctx - m          # literal tokens before the end
                    return [context[m + i % d] for i in range(cap)]
        return []


class ModelDrafter:
    """A small GPT (same vocabulary) drafting k tokens greedily against
    its own slot-pool KV cache.

    Construction takes only (model, params, k); the engine calls
    ``build(...)`` with its slot geometry and trace registry, which
    allocates the drafter pool and compiles the two drafter programs:

      * ``draft``         — ONE program: a lax.scan of k+1 single-token
                            greedy steps over all slots at the engine's
                            per-row frontiers, proposing the first k
                            (the extra step only writes the k-th
                            draft's K/V — see _draft_fn; consumes the
                            engine's pos/tok/active state — see module
                            docstring).
      * ``draft_prefill`` — one program per (admit rung, bucket) pair,
                            the same closed grid as the engine's own
                            prefill: the drafter must ingest every
                            admitted prompt into its pool.
    """

    kind = "device"

    def __init__(self, model, params, k: int = 4):
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.model = model
        self.params = params
        self.k = int(k)
        self._pool = None
        self._draft = None
        self._prefill = None
        self.num_slots = None
        self.max_len = None
        self.paged = False

    # -- engine-driven lifecycle ------------------------------------------

    def build(self, *, target_cfg, num_slots: int, max_len: int,
              n_prefill_programs: int, registry, on_accel: bool,
              kv_dtype=None, decode_impl=None, paged: bool = False,
              kv_page_size: int = 0, kv_pool_blocks: int = 0) -> dict:
        """Allocate the drafter pool + compile draft/prefill under the
        engine's trace registry; returns the program budget entries to
        merge into Engine.max_programs(). kv_dtype mirrors the engine's
        pool mode onto the drafter's own pool ('int8' halves it too);
        decode_impl (the ENGINE's setting) overrides the drafter
        model's own ladder rung, so an operator pinning the engine off
        a broken kernel pins the drafter's draft steps with it.

        ``paged`` mirrors the engine's block-paged layout: the drafter
        pool becomes a parallel (kv_pool_blocks, H, page, D) heap
        indexed by the ENGINE's block table — block lifecycle (alloc,
        prefix sharing, eviction) is decided once, by the engine's
        BlockPool, and both pools follow the same ids, which is also
        why a prefix-cache hit skips the DRAFTER's prefill chunks for
        free (its blocks for those ids still hold that prefix's K/V)."""
        import jax

        if decode_impl is not None and decode_impl != self.model.cfg.decode_impl:
            self.model = type(self.model)(
                cfg=self.model.cfg.replace(decode_impl=decode_impl),
                mesh=getattr(self.model, "mesh", None))

        from nanosandbox_tpu.models.gpt import init_cache, init_paged_cache

        dcfg = self.model.cfg
        if dcfg.vocab_size != target_cfg.vocab_size:
            raise ValueError(
                f"drafter vocab_size {dcfg.vocab_size} != target "
                f"vocab_size {target_cfg.vocab_size}: speculative drafts "
                "are token ids, so the models must share one tokenizer")
        if dcfg.block_size < max_len:
            raise ValueError(
                f"drafter block_size {dcfg.block_size} < engine max_len "
                f"{max_len}: the drafter must hold every slot frontier "
                "the target can reach")
        self.num_slots = num_slots
        self.max_len = max_len
        self.paged = bool(paged)
        if self.paged:
            self._pool = init_paged_cache(dcfg, kv_pool_blocks,
                                          kv_page_size, kv_dtype=kv_dtype)
        else:
            self._pool = init_cache(dcfg, num_slots, max_len,
                                    kv_dtype=kv_dtype)
        budget = {"draft": 1, "draft_prefill": n_prefill_programs}
        draft_body = self._draft_paged_fn if self.paged else self._draft_fn
        prefill_body = (self._prefill_paged_fn if self.paged
                        else self._prefill_fn)
        self._draft = jax.jit(
            registry.guard("draft", budget["draft"])(draft_body),
            donate_argnums=(1,) if on_accel else ())
        self._prefill = jax.jit(
            registry.guard("draft_prefill",
                           budget["draft_prefill"])(prefill_body),
            donate_argnums=(1,) if on_accel else ())
        return budget

    def prefill_wave(self, prompts, meta) -> None:
        """Ingest an admission wave's (k_wave, L_bucket) prompts into the
        drafter pool at the wave's slot rows — called by the engine right
        after its own wave prefill, with the SAME staged device arrays
        (the engine's packed ``meta`` layout; ladder-padding rows carry
        the out-of-range slot id / sentinel table row and drop). Under
        the paged engine ``prompts`` is the SUFFIX block, written
        straight into the drafter pool through the shared block table —
        a prefix-cache hit skips the drafter's prefill chunks too."""
        self._pool = self._prefill(self.params, self._pool, prompts, meta)

    def draft(self, tok, pos, active, table=None):
        """(S, k) greedy draft tokens for every slot at the engine's
        frontiers; rewrites the drafter cache rows pos..pos+k-1 (via the
        engine's block table when paged)."""
        if self.paged:
            self._pool, drafts = self._draft(self.params, self._pool, tok,
                                             pos, active, table)
        else:
            self._pool, drafts = self._draft(self.params, self._pool, tok,
                                             pos, active)
        return drafts

    def shardcheck_programs(self, mesh, *, buckets=(), rungs=(),
                            suffix: str = "") -> list:
        """ProgramSpecs for the drafter's compiled set (draft scan +
        the draft_prefill grid) under the engine's replicated-on-mesh
        contract — see Engine.shardcheck_programs. Requires build()."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from nanosandbox_tpu.analysis.shardcheck import (Expectations,
                                                         ProgramSpec)
        from nanosandbox_tpu.parallel.mesh import replicated_abstract

        if self._pool is None:
            raise RuntimeError("shardcheck_programs requires build() — "
                               "construct the Engine with this drafter "
                               "first")
        rep = NamedSharding(mesh, PartitionSpec())
        aparams = replicated_abstract(mesh, self.params)
        apool = replicated_abstract(mesh, self._pool)
        expect = Expectations(comms_free=True)

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

        def jit_rep(fn):
            return jax.jit(fn, in_shardings=rep, out_shardings=rep)

        S = self.num_slots
        nb = (-(-self.max_len // self._pool[0][0].shape[2])
              if self.paged else 0)
        if self.paged:
            args = (aparams, apool, sds((S,), jnp.int32),
                    sds((S,), jnp.int32), sds((S,), jnp.bool_),
                    sds((S, nb), jnp.int32))
            draft_body = self._draft_paged_fn
        else:
            args = (aparams, apool, sds((S,), jnp.int32),
                    sds((S,), jnp.int32), sds((S,), jnp.bool_))
            draft_body = self._draft_fn
        specs = [ProgramSpec(
            name=f"drafter_draft{suffix}",
            lower=lambda: jit_rep(draft_body).lower(*args),
            abstract_args=args, expect=expect, tags=("serve", "drafter"))]
        meta_w = (nb + 5) if self.paged else 4
        for bucket in buckets:
            for k in rungs:
                prefill_body = (self._prefill_paged_fn if self.paged
                                else self._prefill_fn)
                pargs = (aparams, apool, sds((k, bucket), jnp.int32),
                         sds((k, meta_w), jnp.int32))
                specs.append(ProgramSpec(
                    name=f"drafter_prefill{suffix}_k{k}_L{bucket}",
                    lower=(lambda pargs=pargs, prefill_body=prefill_body:
                           jit_rep(prefill_body).lower(*pargs)),
                    abstract_args=pargs, expect=expect,
                    tags=("serve", "drafter")))
        return specs

    # -- compiled bodies ---------------------------------------------------

    def _prefill_fn(self, dparams, dpool, prompts, meta):
        """Same shape discipline as Engine._prefill_fn, minus sampling:
        the drafter only needs the prompt K/V in its pool (the first
        generated token reaches it through the engine's tok state).
        ``meta`` is the engine's packed dense staging row ([slot |
        true_len | top_k | seed]); only the slot column matters here."""
        from nanosandbox_tpu.models.gpt import init_cache, scatter_cache_rows

        kk, L = prompts.shape
        cache = init_cache(self.model.cfg, kk, L)
        _, cache = self.model.apply({"params": dparams}, prompts,
                                    deterministic=True, cache=cache,
                                    cache_index=0)
        return scatter_cache_rows(dpool, cache, meta[:, 0])

    def _prefill_paged_fn(self, dparams, dpool, suffix, meta):
        """Engine._prefill_paged_fn minus the sampling: forward the
        SUFFIX at per-row cache_index = hit length, its K/V written
        straight into the drafter pool through the shared block table
        (the resident prefix's drafter K/V rides the same refcounted
        blocks, so a hit skips the DRAFTER's prefill chunks too).
        Shared hit blocks stay read-only in the drafter pool as well —
        the write range starts at the block-aligned hit boundary. meta
        is the engine's packed paged staging row ([table (nb) | slot |
        true_len | top_k | seed | hit_len])."""
        nb = -(-self.max_len // self._pool[0][0].shape[2])
        _, dpool = self.model.apply({"params": dparams}, suffix,
                                    deterministic=True, cache=dpool,
                                    cache_index=meta[:, nb + 4],
                                    block_table=meta[:, :nb])
        return dpool

    def _draft_paged_fn(self, dparams, dpool, tok, pos, active, table):
        """The k+1-step draft scan over the block-paged drafter pool:
        identical control flow to _draft_fn, with every cached read and
        write paged through the engine's block table."""
        import jax.numpy as jnp
        from jax import lax

        def step(carry, _):
            tok, pos, pool = carry
            logits, pool = self.model.apply({"params": dparams},
                                            tok[:, None],
                                            deterministic=True, cache=pool,
                                            cache_index=pos,
                                            block_table=table)
            nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, tok)
            pos = pos + active.astype(jnp.int32)
            return (nxt, pos, pool), nxt

        (_, _, dpool), drafts = lax.scan(step, (tok, pos, dpool), None,
                                         length=self.k + 1)
        return dpool, drafts[:self.k].T  # (k+1, S) -> (S, k)

    def _draft_fn(self, dparams, dpool, tok, pos, active):
        """k+1 greedy single-token steps over all slots, proposing the
        first k predictions. Inactive rows are parked (pos frozen, token
        pinned) exactly like the engine's decode step, so a released
        slot's garbage stays in its own row. The extra step exists for
        the CACHE, not the proposal: it feeds the k-th draft so its K/V
        lands at column pos+k. When the verify accepts all k drafts the
        engine's frontier jumps to pos+k+1 and the next draft call
        queries across that column — without this write it would stay
        stale garbage for the rest of the request (never overwritten:
        later writes all land past it), silently degrading every
        subsequent draft for the slot. Partial accepts don't need it
        (the next call's writes cover the rejected tail before any
        query attends there), but the full accept is the drafter's
        TARGET regime."""
        import jax.numpy as jnp
        from jax import lax

        def step(carry, _):
            tok, pos, pool = carry
            logits, pool = self.model.apply({"params": dparams},
                                            tok[:, None],
                                            deterministic=True, cache=pool,
                                            cache_index=pos)
            nxt = jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32)
            nxt = jnp.where(active, nxt, tok)
            pos = pos + active.astype(jnp.int32)
            return (nxt, pos, pool), nxt

        (_, _, dpool), drafts = lax.scan(step, (tok, pos, dpool), None,
                                         length=self.k + 1)
        return dpool, drafts[:self.k].T  # (k+1, S) -> (S, k)


def drafter_from_flag(spec: str, *, k: int = 4, data_dir: str = "data"):
    """CLI plumbing shared by sample.py / serve __main__ / bench.py:
    'ngram' -> NGramDrafter, 'model:<out_dir>' -> ModelDrafter restored
    from that checkpoint directory (params cast to its serving dtype).
    'off'/'' -> None."""
    if spec in ("", "off", "none"):
        return None
    if spec == "ngram":
        return NGramDrafter(k=k)
    if spec.startswith("model:"):
        from nanosandbox_tpu.sample import cast_params_for_serving
        from nanosandbox_tpu.train import restore_for_inference

        out_dir = spec[len("model:"):]
        if not out_dir:
            raise ValueError("--spec=model:<out_dir> needs a checkpoint dir")
        trainer, state, _ = restore_for_inference(out_dir, data_dir=data_dir)
        dparams = cast_params_for_serving(state["params"],
                                          trainer.cfg.compute_dtype)
        return ModelDrafter(trainer.model, dparams, k=k)
    raise ValueError(
        f"unknown --spec value {spec!r} (expected off, ngram, or "
        "model:<out_dir>)")
