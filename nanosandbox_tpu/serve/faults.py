"""Deterministic fault injection for the serving engine (ISSUE 11).

Chaos testing only earns its keep when a failure is REPRODUCIBLE: a
fault that fires "sometimes, under load" produces flaky CI and
unfalsifiable incident reports.  A ``FaultPlan`` is therefore a pure,
seeded schedule over named *sites* in the engine hot path — the engine
asks ``plan.fire(site, step)`` at each site visit and the answer is a
deterministic function of (plan, step, visit count), so the same plan
against the same workload produces the same failure at the same place,
every run.

Sites (where the engine consults the plan — see Engine for the hooks):

  nan_logits      the dispatched decode/verify step's readback tokens
                  are poisoned with the out-of-vocab sentinel — the
                  observable effect of NaN/inf logits reaching the
                  sampler (the engine's in-program isfinite guard maps
                  real non-finite logits to the same sentinel, so the
                  detection path under test is the production one).
  slow_step       ``stall_s`` seconds of host stall injected at the
                  decode dispatch — a wedged device / runaway retry,
                  caught by the ``stalled_step`` watchdog.
  alloc_fail      BlockPool.admit is forced to report exhaustion (the
                  request stays queued; counted as a stall step) —
                  paged engines only.
  drafter_fault   the speculative drafter raises at propose/draft time
                  — exercises the degrade-don't-die path (spec auto-
                  disables after ``spec_fault_tolerance`` consecutive
                  faults).
  scatter_corrupt an admission wave's prefill-sampled first tokens are
                  poisoned — a corrupted slot scatter, detected at the
                  wave readback.
  prefill_exc     the prefill dispatch raises ``FaultInjected`` — a
                  mid-admission crash with blocks already committed,
                  the hardest recovery case (the wave is in limbo:
                  popped from the queue, not yet active).
  preempt_storm   the scheduler is forced to preempt its lowest-
                  priority active victim regardless of any deadline
                  pressure (ISSUE 13) — repeated firings keep evicting
                  the SAME victim as it re-admits, pinning that
                  preemption-resume (blocks donated, prompt' = prompt +
                  tokens so far) composes with recovery and still
                  yields token-identical outputs and exactly-once
                  terminals.
  replica_down    a FLEET-level site (ISSUE 15, consulted by
                  serve/fleet.py's step, never by an Engine): one live
                  replica is hard-killed (abort_all — permanent
                  failure, in-flight requests terminal 'failed') so the
                  router's failure path is exercised end to end:
                  health-out within one interval, victims re-routed to
                  surviving replicas with exactly-once fleet terminals
                  and token-identical greedy resumes.  Disaggregated
                  serving (ISSUE 16) consults the same site from
                  ``DisaggPair._pump`` — once per migration, INSIDE
                  the handoff window (destination blocks reserved via
                  begin_adopt, nothing committed), the hardest
                  exactly-once case: the adoption aborts (released
                  WITHOUT donation), the decode tier is marked failed,
                  and the export either requeues colocated on the
                  prefill engine (same rid, same first token) or
                  surfaces terminal 'failed' with fallback off.

Plans are enabled only by the explicit ``Engine(faults=...)`` /
``bench.py --faults=...`` hook: with no plan attached every site check
is one ``is None`` branch, production pays nothing, and the compile
set / host-sync ledger are untouched (pinned by test).  Everything
here is stdlib-only — no jax import (the scheduler.py contract).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional

SITES = ("nan_logits", "slow_step", "alloc_fail", "drafter_fault",
         "scatter_corrupt", "prefill_exc", "preempt_storm",
         "replica_down")

# Named plans for CI smoke jobs and drills: steps are RELATIVE to the
# last (re)arm, so `plan.rearm(engine.steps)` after warmup aims the
# whole schedule at the measured window.
CANNED = {
    # One poisoned decode step, a burst of allocation failures, a
    # mid-admission prefill crash, and a repeated-preemption storm —
    # the three recovery classes (poison rebuild, backpressure-no-
    # rebuild, exception rebuild-with-flush) plus preemption-resume,
    # early enough that short --quick runs hit all of them.
    "chaos-smoke": ("nan_logits@6,alloc_fail@10x6,prefill_exc@18,"
                    "preempt_storm@22x3"),
    # Every class incl. a drafter failure streak and a second poison —
    # for manual drills against a spec-enabled engine.
    "chaos-full": ("nan_logits@6,drafter_fault@10x4,prefill_exc@20,"
                   "alloc_fail@28x8,preempt_storm@34x3,nan_logits@40"),
}


class FaultInjected(RuntimeError):
    """Raised at exception-type fault sites (``prefill_exc``,
    ``drafter_fault``) so tests and the supervisor can tell an injected
    crash from an organic one."""

    def __init__(self, site: str, step: int):
        super().__init__(f"injected fault: {site} at step {step}")
        self.site = site
        self.step = step


@dataclass
class FaultSpec:
    """One scheduled fault: fire at site visits once the engine step
    counter reaches ``step`` (relative to the plan's arm point), up to
    ``count`` times; or, with ``prob`` set, fire each visit with that
    probability (deterministic in the plan seed and visit index)."""
    site: str
    step: int = 0
    count: int = 1
    stall_s: float = 0.05          # slow_step only
    prob: Optional[float] = None
    fired: int = field(default=0, compare=False)

    def __post_init__(self):
        if self.site not in SITES:
            raise ValueError(f"unknown fault site {self.site!r}; "
                             f"sites: {', '.join(SITES)}")
        if self.step < 0 or self.count < 1:
            raise ValueError(f"bad fault schedule {self.site}@"
                             f"{self.step}x{self.count}")
        if self.prob is not None and not 0.0 < self.prob <= 1.0:
            raise ValueError(f"prob must be in (0, 1], got {self.prob}")


class FaultPlan:
    """A seeded, deterministic schedule of :class:`FaultSpec`\\ s.

    ``fire(site, step)`` is the engine-side hook: returns the spec that
    fires at this visit, or None.  Visits are counted per site, so
    count-based specs drain even when the engine step counter is not
    advancing (e.g. allocation stalls with no decode dispatch).
    ``rearm(step0)`` resets firing state and re-bases relative steps —
    benchmarks arm the plan at the start of the measured window so
    warmup and capacity probes run clean."""

    def __init__(self, faults: List[FaultSpec], seed: int = 0):
        self.seed = int(seed)
        self.enabled = True
        self._by_site: Dict[str, List[FaultSpec]] = {}
        for f in faults:
            self._by_site.setdefault(f.site, []).append(f)
        self._step0 = 0
        self._visits: Dict[str, int] = {}
        self.fired_log: List[dict] = []

    # ------------------------------------------------------------ build
    @classmethod
    def parse(cls, text: str, seed: int = 0) -> "FaultPlan":
        """Build a plan from its compact flag syntax::

            site@STEP[xCOUNT][:PARAM]   fire at relative step >= STEP,
                                        COUNT times (default 1); PARAM
                                        is stall seconds for slow_step
            site@pPROB[:PARAM]          fire each visit with prob PROB
                                        (seeded, deterministic)

        entries comma-separated; a canned plan name (see ``CANNED``)
        expands first.  Examples: ``nan_logits@40``,
        ``slow_step@20:0.5``, ``alloc_fail@10x30``,
        ``drafter_fault@p0.05``, ``chaos-smoke``."""
        text = CANNED.get(text.strip(), text)
        specs: List[FaultSpec] = []
        for entry in text.split(","):
            entry = entry.strip()
            if not entry:
                continue
            if "@" not in entry:
                raise ValueError(
                    f"fault entry {entry!r}: expected site@step (or a "
                    f"canned plan: {', '.join(sorted(CANNED))})")
            site, sched = entry.split("@", 1)
            stall = 0.05
            if ":" in sched:
                sched, param = sched.split(":", 1)
                stall = float(param)
            prob: Optional[float] = None
            count = 1
            step = 0
            if sched.startswith("p"):
                prob = float(sched[1:])
                # "fire each visit with prob PROB" means EVERY visit
                # flips the coin — an uncapped count (count=1 would
                # silently stop after the first hit).
                count = 1 << 30
            else:
                if "x" in sched:
                    sched, n = sched.split("x", 1)
                    count = int(n)
                step = int(sched)
            specs.append(FaultSpec(site=site.strip(), step=step,
                                   count=count, stall_s=stall, prob=prob))
        if not specs:
            raise ValueError(f"empty fault plan: {text!r}")
        return cls(specs, seed=seed)

    # ---------------------------------------------------------- runtime
    def arm(self, step0: int) -> None:
        """Base relative steps at ``step0`` (idempotent; Engine calls
        this once at construction)."""
        self._step0 = int(step0)

    def rearm(self, step0: int) -> None:
        """Re-base AND reset all firing state — aim the schedule at a
        fresh window (bench points, post-warmup serving)."""
        self._step0 = int(step0)
        self._visits = {}
        self.fired_log = []
        for specs in self._by_site.values():
            for f in specs:
                f.fired = 0

    def fire(self, site: str, step: int) -> Optional[FaultSpec]:
        """The engine-side site check. Deterministic: a pure function
        of (plan state, step, per-site visit count)."""
        if not self.enabled:
            return None
        specs = self._by_site.get(site)
        if not specs:
            return None
        visit = self._visits.get(site, 0)
        self._visits[site] = visit + 1
        rel = step - self._step0
        for f in specs:
            if f.fired >= f.count:
                continue
            if f.prob is not None:
                # Seeded per-visit coin: same plan + same visit index
                # -> same outcome, run after run.
                coin = random.Random(f"{self.seed}:{site}:{visit}")
                if coin.random() >= f.prob:
                    continue
            elif rel < f.step:
                continue
            f.fired += 1
            self.fired_log.append({"site": site, "step": step,
                                   "visit": visit})
            return f
        return None

    # ------------------------------------------------------------ views
    def describe(self) -> List[dict]:
        return [{"site": f.site, "step": f.step, "count": f.count,
                 "prob": f.prob, "stall_s": f.stall_s}
                for specs in self._by_site.values() for f in specs]

    def stats(self) -> dict:
        return {"enabled": self.enabled, "seed": self.seed,
                "armed_at": self._step0,
                "specs": self.describe(),
                "fired": list(self.fired_log)}
