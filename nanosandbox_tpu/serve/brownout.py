"""Brownout ladder: graceful load degradation driven by the SLO ledger.

PR 10 built the *measurement* half of overload (deadlines, attainment,
goodput) and priority scheduling (ISSUE 13) the *ordering* half; this
module closes the control loop.  When the engine is burning its SLO —
a recent window of deadline-carrying terminals mostly missed or shed —
the :class:`BrownoutController` steps the engine DOWN a ladder of named
degradation levels, trading progressively more capability for tail
latency, and steps back up only after a sustained healthy stretch
(hysteresis — a single good window never un-sheds a class just to
re-shed it two windows later):

  level 0  ``normal``            full service.
  level 1  ``shrink_scan``       cap the multi-token decode scan chunk
                                 at scan_k/2: shorter chunks mean less
                                 finish-lag waste and finer admission
                                 interleaving when every slot matters.
                                 (No-op at scan_k == 1.)
  level 2  ``no_spec``           suspend speculative decoding: verify
                                 dispatches are the widest programs in
                                 the engine and a mispredicting drafter
                                 under loaded traffic is pure overhead.
                                 Reversible (unlike the drafter-fault
                                 auto-disable); outputs are unchanged
                                 by construction.
  level 3  ``shed_batch``        shed the batch class (priority < 1):
                                 queued batch requests get terminal
                                 'shed' Results and new batch
                                 submissions shed at submit (429 +
                                 Retry-After upstream) instead of
                                 rotting in the queue.
  level 4  ``interactive_only``  shed everything below interactive
                                 (priority < 2) — the last stop before
                                 involuntary collapse, entered only
                                 when shedding batch alone did not
                                 clear the burn.

Each transition leaves a ``brownout`` flight event and moves the
``serve_brownout_level`` gauge / ``serve_brownout_transitions_total``
counter, so a saturation incident reads as an explicit staircase in the
dashboard instead of an unexplained latency cliff.

The controller polls every ``check_interval_steps`` engine steps (a
handful of int compares between polls — the watchdog-panel cost
discipline) and judges each window by its SLO attainment delta:
escalate immediately when a window with enough terminal events attains
below ``escalate_below``; de-escalate one level after ``clear_checks``
consecutive windows at/above ``clear_above`` (idle windows — no
deadline-carrying terminals — count as healthy, so a drained engine
walks back to normal as traffic returns).  Deadline-less deployments
never produce SLO events, so the controller simply never escalates —
brownout costs nothing unless deadlines are in play.

No jax import; plain host arithmetic over the engine's ledgers (the
obs/ contract).
"""

from __future__ import annotations

from typing import Optional

LEVELS = ("normal", "shrink_scan", "no_spec", "shed_batch",
          "interactive_only")


class BrownoutController:
    """SLO-burn load controller over one Engine (metrics publish on the
    engine's registry, next to the attainment it reacts to).

    Parameters
    ----------
    engine : the Engine to degrade (reads ``engine.slo``, writes the
        ``scan_cap`` / ``spec_suspended`` / ``brownout_min_priority``
        knobs the hot loop consults).
    check_interval_steps : engine steps between window judgements.
    escalate_below / clear_above : window-attainment thresholds; the
        gap between them is the hysteresis band (windows inside it
        neither escalate nor count toward clearing).
    min_window_events : deadline-carrying terminals a window needs
        before its attainment is trusted (tiny windows are noise).
    clear_checks : consecutive healthy windows required per step DOWN
        the ladder (one burning window escalates immediately —
        overload is an emergency, recovery is not).
    shed_batch_floor / interactive_floor : the priority floors level 3
        and 4 apply (requests BELOW the floor shed).
    """

    def __init__(self, engine, *, check_interval_steps: int = 16,
                 escalate_below: float = 0.85,
                 clear_above: float = 0.95,
                 min_window_events: int = 4,
                 clear_checks: int = 3,
                 shed_batch_floor: int = 1,
                 interactive_floor: int = 2):
        self.engine = engine
        self.check_interval_steps = max(1, int(check_interval_steps))
        self.escalate_below = float(escalate_below)
        self.clear_above = float(clear_above)
        self.min_window_events = int(min_window_events)
        self.clear_checks = max(1, int(clear_checks))
        self.shed_batch_floor = int(shed_batch_floor)
        self.interactive_floor = int(interactive_floor)
        self.level = 0
        self.transitions = 0
        self._clear_streak = 0
        self._last_check_step = engine.steps
        self._mark = engine.slo.totals()
        self._bshed_mark = engine.brownout_sheds
        m = engine.metrics
        self._g_level = m.gauge(
            "serve_brownout_level",
            "Current brownout degradation level (0 = normal; see "
            "serve/brownout.py for the ladder).")
        self._c_trans = m.counter(
            "serve_brownout_transitions_total",
            "Brownout ladder transitions, by direction.",
            labelnames=("direction",))
        self._g_level.set(0.0)

    # ------------------------------------------------------------- poll
    def on_step(self) -> None:
        """Called by Engine.step(); self-throttles to one window
        judgement per ``check_interval_steps``."""
        eng = self.engine
        if eng.steps - self._last_check_step < self.check_interval_steps:
            return
        self._last_check_step = eng.steps
        met, missed, shed = eng.slo.totals()
        bshed = eng.brownout_sheds
        m0, x0, s0 = self._mark
        b0, self._bshed_mark = self._bshed_mark, bshed
        self._mark = (met, missed, shed)
        # Sheds caused by the controller's own floor are load REMOVED,
        # not ongoing burn: count them as burn and level >= 3 sustains
        # itself on below-floor traffic that keeps arriving after the
        # overload ends, never clearing.  Subtract them from the
        # window's shed delta (clamped — a ledger reset can skew the
        # two counters independently).
        d_shed = max(0, (shed - s0) - (bshed - b0))
        d_met, d_events = met - m0, (met - m0) + (missed - x0) + d_shed
        # Negative deltas mean the ledger was reset (bench warmup
        # hygiene) — treat as an idle window and let the mark resync.
        if d_events >= self.min_window_events and d_met >= 0:
            attainment = d_met / d_events
            if attainment < self.escalate_below:
                self._clear_streak = 0
                if self.level < len(LEVELS) - 1:
                    self._set(self.level + 1, attainment=attainment)
                return
            if attainment < self.clear_above:
                # Hysteresis band: neither burning nor provably healthy.
                self._clear_streak = 0
                return
        self._clear_streak += 1
        if self.level > 0 and self._clear_streak >= self.clear_checks:
            self._clear_streak = 0
            self._set(self.level - 1)

    # ------------------------------------------------------- transitions
    def _set(self, level: int, attainment: Optional[float] = None) -> None:
        """Move to ``level`` and (re)apply the CUMULATIVE effects of
        every level at or below it — de-escalation reverses by the same
        assignment, so the knobs can never drift from the level."""
        old, self.level = self.level, level
        eng = self.engine
        eng.scan_cap = max(1, eng.scan_k // 2) if level >= 1 else None
        eng.spec_suspended = level >= 2
        eng.brownout_min_priority = (
            self.interactive_floor if level >= 4
            else self.shed_batch_floor if level >= 3 else None)
        self.transitions += 1
        direction = "up" if level > old else "down"
        self._c_trans.labels(direction=direction).inc()
        self._g_level.set(float(level))
        info = {"level": level, "name": LEVELS[level],
                "from": LEVELS[old], "direction": direction}
        if attainment is not None:
            info["window_attainment"] = round(attainment, 4)
        eng.flight.record("brownout", step=eng.steps, **info)

    # ------------------------------------------------------------- views
    def stats(self) -> dict:
        return {"level": self.level,
                "name": LEVELS[self.level],
                "transitions": self.transitions,
                "clear_streak": self._clear_streak,
                "min_priority": self.engine.brownout_min_priority,
                "levels": list(LEVELS)}
