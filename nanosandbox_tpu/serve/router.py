"""Prefix-affinity request routing over N engine replicas (ISSUE 15).

PR 9's radix prefix cache made a hit's TTFT ~0.5x a miss — but the
cache is per-engine, so once serving goes multi-replica *where* a
request lands matters as much as how fast one engine runs: send two
requests sharing a system prompt to two different replicas and the
fleet pays the prefill twice AND caches the prefix twice (half the
fleet's effective cache capacity, for nothing). The router here closes
that gap from ABOVE the engines, with zero engine-side cost:

  * Every replica already fingerprints its resident prefix chains —
    ``paged.prefix_digests`` chained per-block hashes, reported on each
    Result / flight ``finish`` / HTTP ``/generate`` body
    (``prefix_digest``) and summarized by ``/debug/prefix_summary``.

  * The router keeps an APPROXIMATE per-replica index of those digests
    (bounded LRU membership set — see _PrefixIndex): updated
    opportunistically from per-request reports, replaced wholesale by
    the periodic authoritative summary (which is the index's staleness
    eviction: anything the replica LRU-evicted since the last refresh
    drops out).

  * ``route()`` scores the READY replicas by
        est_prefix_hit_tokens
          - load_weight   * (queued + active)
          - brownout_weight * brownout_level
    and picks the max — affinity wins when a warm replica exists and
    its queue is not disproportionately deep; otherwise the choice
    degrades to least-loaded (reason ``load``). A replica that is
    draining, quarantined, or failed is simply not a candidate; when
    the caller is re-routing around a failure (``failover=True``) or an
    exclusion changed the choice, the decision is tagged ``fallback``.

Everything here is stdlib-only host bookkeeping (the scheduler.py
contract): no jax import, no device state, nothing on any engine's hot
loop. The in-process harness (serve/fleet.py) and the asyncio HTTP
front tier (serve/http.py) both drive this one class, so the routing
policy tested on one host is the policy the k8s router Deployment runs.

Thread safety: the HTTP front tier calls into one router instance from
THREE contexts at once — route() and stats() on the asyncio loop
thread, update_replica()/refresh_summary() from health-poll executor
threads, add_replica()/remove_replica() from discovery resolution —
and ``self.replicas`` is a plain dict whose iteration (route's ready
scan) crashes outright when a poll mutates it mid-walk. Every public
method therefore serializes on ``self._lock`` (an RLock:
update_replica re-enters through add_replica). Nothing under the lock
blocks — pure dict/score work, microseconds — so the serialization is
invisible next to a single proxied request. The lock sits in the
``engine`` tier of budgets/lock_order.json.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence

# Decision reasons (the serve_router_decisions_total{reason=} label
# set): ``affinity`` — a prefix-warm replica won; ``load`` — no usable
# affinity signal, least-loaded pick; ``fallback`` — the preferred
# choice was unavailable (failover re-route, exclusion, or the best
# affinity candidate was not ready) and traffic was redirected.
REASONS = ("affinity", "load", "fallback")


class NoReadyReplicaError(RuntimeError):
    """Every replica is excluded, draining, quarantined, or failed —
    the fleet cannot take this request (503 upstream)."""


@dataclass
class RouteDecision:
    replica: str
    reason: str                  # one of REASONS
    est_hit_tokens: int          # prefix tokens the chosen replica skips
    candidates: int              # ready replicas considered


class _PrefixIndex:
    """Bounded LRU membership set of prefix-chain digests — the
    router's approximate picture of ONE replica's radix cache.

    Membership is all matching needs: a request's own digest chain
    (prefix_digests) is walked in order and the hit depth is the last
    contiguous member — the same longest-prefix semantics the replica's
    trie applies, without the router holding a single token id. The cap
    bounds router memory per replica; the authoritative summary refresh
    (replace()) clears any stale survivors the cap kept too long."""

    def __init__(self, cap: int = 8192):
        if cap < 1:
            raise ValueError(f"index cap must be >= 1, got {cap}")
        self.cap = cap
        self._set: "OrderedDict[str, None]" = OrderedDict()

    def __len__(self) -> int:
        return len(self._set)

    def add_chain(self, digests: Iterable[str]) -> None:
        for d in digests:
            if d in self._set:
                self._set.move_to_end(d)
            else:
                self._set[d] = None
                while len(self._set) > self.cap:
                    self._set.popitem(last=False)

    def replace(self, digests: Iterable[str]) -> None:
        """Authoritative refresh from /debug/prefix_summary: the
        replica's trie IS this set now (capped), nothing else."""
        fresh: "OrderedDict[str, None]" = OrderedDict()
        for d in digests:
            fresh[d] = None
            if len(fresh) > self.cap:
                fresh.popitem(last=False)
        self._set = fresh

    def clear(self) -> None:
        self._set = OrderedDict()

    def match_blocks(self, chain: Sequence[str]) -> int:
        """Contiguous leading blocks of ``chain`` present here — the
        estimated radix hit depth (digests chain parent-to-child, so a
        missing link means everything deeper is unreachable too)."""
        hit = 0
        for d in chain:
            if d not in self._set:
                break
            self._set.move_to_end(d)
            hit += 1
        return hit


@dataclass
class ReplicaView:
    """The router's picture of one replica — health + load refreshed
    every interval, the prefix index fed by result reports and summary
    refreshes."""
    name: str
    ready: bool = False
    reason: str = "unknown"
    queued: int = 0
    active: int = 0
    brownout: int = 0
    retry_after_s: Optional[float] = None
    last_update_t: float = 0.0
    index: _PrefixIndex = field(default_factory=_PrefixIndex)
    # Serving tier (ISSUE 16): "both" (colocated, the default),
    # "prefill", or "decode". route(phase=...) only considers replicas
    # whose role covers the request's phase — the phase dimension that
    # turns the router into a two-tier dispatcher. Sticky: set at
    # add_replica (the k8s tier annotation) and only changed by an
    # explicit update.
    role: str = "both"

    @property
    def load(self) -> int:
        return self.queued + self.active


class PrefixAffinityRouter:
    """Score-and-pick routing over named replicas (module docstring has
    the policy). ``affinity=False`` routes SEEDED-UNIFORM-RANDOM over
    the ready set: the honest affinity-blind baseline the bench twin is
    measured against. (Not least-loaded-with-rotation: that is
    quasi-deterministic, and on a grouped arrival pattern its rotation
    can alias into accidental affinity — or anti-affinity — flipping
    the comparison with the workload's phase instead of its policy.)

    ``metrics`` (an obs.MetricRegistry) hosts the router families:
    serve_router_decisions_total{reason=}, the
    serve_router_prefix_hit_est_tokens histogram, and per-replica
    serve_router_replica_ready / serve_router_replica_load gauges.
    All recording happens at route/update time on host ints — there is
    no hot loop here to stay off."""

    def __init__(self, replicas: Iterable[str], *, page: int = 16,
                 index_cap: int = 8192, load_weight: float = 8.0,
                 brownout_weight: float = 64.0, affinity: bool = True,
                 metrics=None, seed: int = 0,
                 roles: Optional[Dict[str, str]] = None):
        import random as _random

        self._lock = threading.RLock()
        self.page = int(page)
        self._rng = _random.Random(seed)
        self.load_weight = float(load_weight)
        self.brownout_weight = float(brownout_weight)
        self.affinity = bool(affinity)
        self.index_cap = int(index_cap)
        self.replicas: Dict[str, ReplicaView] = {}  # guarded-by: _lock
        roles = roles or {}
        for name in replicas:
            self.add_replica(name, role=roles.get(name, "both"))
        if not self.replicas:
            raise ValueError("router needs at least one replica")
        self.decisions: Dict[str, int] = {r: 0 for r in REASONS}  # guarded-by: _lock
        self._rr = int(seed)         # rotates load-tie picks
        self._m_decisions = None
        self._m_hit_est = None
        self._m_ready = None
        self._m_load = None
        if metrics is not None:
            self._m_decisions = metrics.counter(
                "serve_router_decisions_total",
                "Routing decisions by reason "
                "(affinity | load | fallback).", labelnames=("reason",))
            self._m_hit_est = metrics.histogram(
                "serve_router_prefix_hit_est_tokens",
                "Estimated prefix-hit tokens at the chosen replica.",
                unit="tokens",
                buckets=(0, 16, 32, 64, 128, 256, 512, 1024))
            self._m_ready = metrics.gauge(
                "serve_router_replica_ready",
                "1 while the replica is in rotation, else 0.",
                labelnames=("replica",))
            self._m_load = metrics.gauge(
                "serve_router_replica_load",
                "Queued + active requests at the replica, as of its "
                "last health refresh.", labelnames=("replica",))

    # ------------------------------------------------------------ updates
    def add_replica(self, name: str, *, role: str = "both") -> None:
        """Register a replica (headless-Service discovery may grow the
        set at runtime); idempotent. ``role`` is the tier annotation
        ("both" | "prefill" | "decode") — see ReplicaView.role."""
        if role not in ("both", "prefill", "decode"):
            raise ValueError(f"role must be 'both', 'prefill' or "
                             f"'decode', got {role!r}")
        with self._lock:
            if name not in self.replicas:
                self.replicas[name] = ReplicaView(
                    name=name, role=role,
                    index=_PrefixIndex(self.index_cap))

    def remove_replica(self, name: str) -> None:
        """Deregister (scale-down, DNS churn). The label children a
        registry already minted persist in the exposition, so zero the
        gauges on the way out — a pod that left must not keep
        exporting ready=1 to the dashboards forever."""
        with self._lock:
            if name in self.replicas and self._m_ready is not None:
                self._m_ready.labels(replica=name).set(0.0)
                self._m_load.labels(replica=name).set(0.0)
            self.replicas.pop(name, None)

    def update_replica(self, name: str, *, ready: bool,
                       reason: str = "", queued: int = 0, active: int = 0,
                       brownout: int = 0,
                       retry_after_s: Optional[float] = None,
                       role: Optional[str] = None) -> None:
        """One health-interval refresh: readiness (drain / quarantine /
        failure take the replica out of rotation HERE, which is why the
        rotation reacts within one interval), queue depth, brownout
        level, and the replica's own retry estimate. ``role`` is sticky
        (None leaves the tier annotation untouched)."""
        with self._lock:
            self.add_replica(name)
            r = self.replicas[name]
            if role is not None:
                if role not in ("both", "prefill", "decode"):
                    raise ValueError(f"role must be 'both', 'prefill' or "
                                     f"'decode', got {role!r}")
                r.role = role
            r.ready = bool(ready)
            r.reason = reason
            r.queued = int(queued)
            r.active = int(active)
            r.brownout = int(brownout)
            r.retry_after_s = retry_after_s
            r.last_update_t = time.monotonic()
            if self._m_ready is not None:
                self._m_ready.labels(replica=name).set(
                    1.0 if r.ready else 0.0)
                self._m_load.labels(replica=name).set(float(r.load))

    def observe_digests(self, name: str, digests: Sequence[str]) -> None:
        """Opportunistic index update from one finished request's
        prefix_digest report: replica ``name`` now caches this chain."""
        with self._lock:
            if digests and name in self.replicas:
                self.replicas[name].index.add_chain(digests)

    def refresh_summary(self, name: str, digests: Sequence[str]) -> None:
        """Authoritative replacement from the replica's
        /debug/prefix_summary — the staleness/eviction path: digests
        the replica LRU-evicted since the last refresh disappear from
        the router's index with it."""
        with self._lock:
            if name in self.replicas:
                self.replicas[name].index.replace(digests)

    def forget(self, name: str) -> None:
        """Drop a replica's index (it died, recovered with a flushed
        cache, or reset) without deregistering it."""
        with self._lock:
            if name in self.replicas:
                self.replicas[name].index.clear()

    # ------------------------------------------------------------ routing
    def match_tokens(self, name: str, chain: Sequence[str]) -> int:
        with self._lock:
            r = self.replicas.get(name)
            if r is None:
                return 0
            return r.index.match_blocks(chain) * self.page

    def route(self, chain: Sequence[str] = (), *,
              exclude: Iterable[str] = (),
              failover: bool = False,
              phase: Optional[str] = None) -> RouteDecision:
        """Pick a replica for a request whose prompt's digest chain is
        ``chain`` (empty = no affinity signal: dense engines, text-only
        HTTP requests). ``exclude`` removes replicas the caller already
        tried this request; ``failover=True`` marks the decision as a
        re-route (reason ``fallback``) regardless of what wins.
        ``phase`` (ISSUE 16) restricts candidates to the matching tier:
        "prefill" routes an arriving request into the prefill tier,
        "decode" picks the adoption target for a parked export —
        colocated ("both") replicas serve either phase, so a mixed
        fleet degrades gracefully to single-tier routing. Raises
        NoReadyReplicaError when no candidate remains."""
        if phase is not None and phase not in ("prefill", "decode"):
            raise ValueError(f"phase must be 'prefill' or 'decode', "
                             f"got {phase!r}")
        excluded = set(exclude)
        with self._lock:
            ready = [r for r in self.replicas.values()
                     if r.ready and r.name not in excluded
                     and (phase is None or r.role in ("both", phase))]
            if not ready:
                raise NoReadyReplicaError(
                    ("no ready replica" if phase is None
                     else f"no ready {phase}-tier replica") + " (of "
                    f"{len(self.replicas)}: "
                    + ", ".join(f"{r.name}[{r.role}]="
                                f"{r.reason or 'excluded'}"
                                for r in self.replicas.values()) + ")")
            ready.sort(key=lambda r: r.name)
            if not self.affinity:
                # The affinity-blind baseline: seeded uniform-random
                # over the ready set (class docstring explains why not
                # least-loaded-with-rotation).
                best = self._rng.choice(ready)
                reason = "fallback" if (failover or excluded) else "load"
                self.decisions[reason] += 1
                if self._m_decisions is not None:
                    self._m_decisions.labels(reason=reason).inc()
                    self._m_hit_est.observe(0)
                return RouteDecision(replica=best.name, reason=reason,
                                     est_hit_tokens=0,
                                     candidates=len(ready))
            # Stable candidate rotation: ties (fresh fleet, equal load)
            # spread round-robin instead of piling the whole warmup on
            # one replica; the rotation point advances per decision.
            self._rr += 1
            ready = (ready[self._rr % len(ready):]
                     + ready[:self._rr % len(ready)])
            hits = {r.name: (r.index.match_blocks(chain) * self.page
                             if chain else 0)
                    for r in ready}

            def score(r: ReplicaView) -> float:
                return (hits[r.name] - self.load_weight * r.load
                        - self.brownout_weight * r.brownout)

            best = max(ready, key=score)
            est = hits[best.name]
            if failover or excluded:
                reason = "fallback"
            elif est > 0:
                reason = "affinity"
            else:
                # No affinity among the READY set — if a non-ready/
                # excluded replica held the prefix, this is traffic
                # redirected off its warm home, which an operator reads
                # differently from plain cold load-balancing.
                warm_elsewhere = any(
                    self.affinity and chain
                    and r.index.match_blocks(chain) > 0
                    for r in self.replicas.values()
                    if not r.ready or r.name in excluded)
                reason = "fallback" if warm_elsewhere else "load"
            self.decisions[reason] += 1
            if self._m_decisions is not None:
                self._m_decisions.labels(reason=reason).inc()
                self._m_hit_est.observe(est)
            return RouteDecision(replica=best.name, reason=reason,
                                 est_hit_tokens=est,
                                 candidates=len(ready))

    # ------------------------------------------------------------- views
    def ready_replicas(self) -> List[str]:
        with self._lock:
            return sorted(r.name for r in self.replicas.values()
                          if r.ready)

    def stats(self) -> dict:
        with self._lock:
            return {
                "affinity": self.affinity,
                "page": self.page,
                "index_cap": self.index_cap,
                "load_weight": self.load_weight,
                "brownout_weight": self.brownout_weight,
                "decisions": dict(self.decisions),
                "replicas": {
                    r.name: {
                        "ready": r.ready,
                        "role": r.role,
                        "reason": r.reason,
                        "queued": r.queued,
                        "active": r.active,
                        "brownout": r.brownout,
                        "retry_after_s": r.retry_after_s,
                        "index_digests": len(r.index),
                        "age_s": (round(
                            time.monotonic() - r.last_update_t, 6)
                            if r.last_update_t else None),
                    } for r in self.replicas.values()
                },
            }
