"""Engine supervisor: crash-safe stepping with bounded-backoff recovery
and permanent-failure escalation (ISSUE 11).

The Engine owns the recovery MECHANISM (poison detection, device-state
rebuild, victim re-admission — engine.recover()); this module owns the
POLICY: *when* to recover, how hard to back off, and when to stop
trying.  ``EngineSupervisor.step()`` is a drop-in replacement for
``Engine.step()`` — http.EngineLoop, bench.py and the tests drive it
exactly like the engine — that turns three failure classes into
self-healing instead of a dead process:

  poisoned step     the engine's in-program isfinite sentinel (or an
                    injected fault) surfaced garbage tokens at the
                    readback: quarantine + rebuild, KEEPING the KV pool
                    and radix cache (a poisoned step only ever wrote
                    its rows' private frontier blocks — the PR 9
                    copy-on-write argument makes the cache provably
                    clean, so every victim's resume is a prefix hit).
                    Under the multi-token scan (scan_k > 1) the same
                    machinery unwinds a poisoned MID-SCAN chunk: the
                    retire keeps each row's clean pre-poison prefix
                    and discards everything sampled downstream of the
                    garbage (a poisoned token feeds the next scan step
                    by construction), so the requeued prompt' = prompt
                    + clean tokens and greedy resume stays token-
                    identical to a no-fault run — lag-k, same proof.
  step exception    a dispatch crashed (device OOM, compile error,
                    injected prefill_exc): donated buffers may be
                    invalid, so the rebuild additionally FLUSHES the
                    cache and re-materializes the pool arrays.
  watchdog trip     stuck_slot / stalled_step — a wedge with no
                    exception to catch: same quarantine + rebuild.

Preemption (ISSUE 13) composes with all three classes: a victim evicted
by the priority scheduler sits in the queue as prompt' = prompt +
tokens-so-far behind the same _Resume stitch recovery uses, so a fault
landing between its eviction and its re-admission just requeues it
again — one terminal, token-identical output, pinned by the
preempt_storm chaos tests.  Requests mid-CHUNKED-prefill are unwound
like mid-wave limbo: blocks freed WITHOUT donation (their prompt chain
is only partially written) and the request re-chunks from scratch.

Recovery attempts back off exponentially (base * 2^(n-1), capped), and
``max_consecutive`` failures inside ``settle_s`` escalate to PERMANENT
failure: the engine drains cleanly (every in-flight/queued request gets
a terminal ``failed`` Result with partial tokens salvaged, submissions
refuse with EngineFailedError -> HTTP 503) instead of crash-looping
through the same poison forever.  A clean stretch of ``settle_s``
resets the consecutive counter, so a fault tomorrow starts the ladder
from the bottom.

No jax import — policy is host-side arithmetic (the obs/ contract);
metrics publish on the engine's registry so /metrics carries
``serve_engine_recoveries_total`` next to the latency it explains.
"""

from __future__ import annotations

import time
from typing import Callable, List, Optional

# Watchdog kinds the supervisor treats as "the engine is wedged, a
# rebuild can help" — the observability-only kinds (ttft_spike,
# pool_thrash, admission_stall, post_freeze_retrace) page, they do not
# trigger recovery: tearing down device state cannot un-spike a TTFT.
RECOVERABLE_TRIPS = ("stuck_slot", "stalled_step")


class EngineSupervisor:
    """Crash-safe wrapper: ``step()`` like an Engine, plus quarantine /
    rebuild / backoff / permanent-failure policy.

    Parameters
    ----------
    engine : the Engine to supervise (metrics land on its registry).
    max_consecutive : recoveries tolerated without a ``settle_s`` clean
        stretch before escalating to permanent failure.
    backoff_base_s / backoff_max_s : exponential backoff between a
        detection and its rebuild (base * 2^(n-1), capped). Tests pass
        base 0 to run the ladder without sleeping.
    settle_s : a fault-free stretch this long resets the consecutive
        counter (transient storms escalate; isolated blips do not).
    sleep : injectable clock for tests.
    """

    def __init__(self, engine, *, max_consecutive: int = 4,
                 backoff_base_s: float = 0.05,
                 backoff_max_s: float = 5.0,
                 settle_s: float = 60.0,
                 sleep: Callable[[float], None] = time.sleep):
        self.engine = engine
        self.max_consecutive = int(max_consecutive)
        self.backoff_base_s = float(backoff_base_s)
        self.backoff_max_s = float(backoff_max_s)
        self.settle_s = float(settle_s)
        self._sleep = sleep
        self.state = "ok"                   # ok | failed
        self.recoveries = 0
        self.consecutive = 0
        self.last_cause: Optional[str] = None
        self.last_detail = ""
        self.last_backoff_s = 0.0
        self._last_fault_t: Optional[float] = None
        self._trip_mark = {k: engine.watchdog.trips.get(k, 0)
                           for k in RECOVERABLE_TRIPS}
        # Time-to-first-retired-token after a quarantine: the number an
        # operator actually feels (rebuild time is host bookkeeping;
        # TTFRT includes the re-prefill of every victim).
        self._await_tok_t: Optional[float] = None
        self._tok_mark = 0
        m = engine.metrics
        self._h_ttfrt = m.histogram(
            "serve_recovery_ttfrt_seconds",
            "Quarantine detection -> first post-recovery retired token.",
            unit="seconds",
            buckets=(0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0))
        self._g_state = m.gauge(
            "serve_supervisor_state",
            "Supervisor state one-hot (ok | failed).",
            labelnames=("state",))
        self._g_state.labels(state="ok").set(1.0)

    # ------------------------------------------------------------- step
    def step(self) -> List:
        """One supervised engine step. Returns the engine's finished
        Results; on a detected fault, recovery happens HERE (quarantine
        -> backoff -> rebuild -> requeue) and the re-admitted requests
        finish through later steps. After permanent failure this only
        flushes results the engine already owed."""
        eng = self.engine
        if self.state == "failed":
            return eng.step()       # flushes pending results only
        try:
            results = eng.step()
        except Exception as e:      # dispatch crash: buffers suspect
            return self._handle_fault(
                f"step_error:{type(e).__name__}", flush_cache=True,
                detail=str(e))
        cause = None
        poison = eng.take_poison()
        if poison is not None:
            cause = poison.get("kind", "poisoned_step")
        else:
            cause = self._watchdog_cause()
        if cause is not None:
            results = list(results)
            results.extend(self._handle_fault(cause, flush_cache=False))
            return results
        now = time.monotonic()
        if (self._await_tok_t is not None
                and eng.tokens_generated > self._tok_mark):
            self._h_ttfrt.observe(now - self._await_tok_t)
            self._await_tok_t = None
        if (self.consecutive and self._last_fault_t is not None
                and now - self._last_fault_t > self.settle_s):
            self.consecutive = 0
        return results

    def drain(self) -> List:
        """step() until idle — the supervised twin of Engine.drain()."""
        out: List = []
        while self.engine.has_work() and self.state != "failed":
            out.extend(self.step())
        out.extend(self.engine.step())      # flush any stragglers
        return out

    # ----------------------------------------------------------- policy
    def _watchdog_cause(self) -> Optional[str]:
        trips = self.engine.watchdog.trips
        for kind, seen in self._trip_mark.items():
            cur = trips.get(kind, 0)
            if cur > seen:
                self._trip_mark[kind] = cur
                return kind
        return None

    def _handle_fault(self, cause: str, *, flush_cache: bool,
                      detail: str = "") -> List:
        eng = self.engine
        now = time.monotonic()
        if (self._last_fault_t is not None
                and now - self._last_fault_t > self.settle_s):
            self.consecutive = 0
        self._last_fault_t = now
        self.consecutive += 1
        self.last_cause = cause
        self.last_detail = detail
        eng.quarantine(cause)
        if self.consecutive > self.max_consecutive:
            # Escalate: recovery is not converging — drain cleanly
            # (terminal 'failed' Results, submissions refused) instead
            # of burning the ladder forever on the same poison.
            self.state = "failed"
            self._g_state.labels(state="ok").set(0.0)
            self._g_state.labels(state="failed").set(1.0)
            return eng.abort_all(
                f"{cause} x{self.consecutive} (recovery exhausted)")
        backoff = min(self.backoff_base_s * (2 ** (self.consecutive - 1)),
                      self.backoff_max_s)
        self.last_backoff_s = backoff
        if backoff > 0:
            self._sleep(backoff)
        self._tok_mark = eng.tokens_generated
        self._await_tok_t = now
        eng.recover(cause, flush_cache=flush_cache)
        self.recoveries += 1
        return []

    # ------------------------------------------------------------ views
    def stats(self) -> dict:
        return {"state": self.state,
                "recoveries": self.recoveries,
                "consecutive": self.consecutive,
                "max_consecutive": self.max_consecutive,
                "last_cause": self.last_cause,
                "last_detail": self.last_detail,
                "last_backoff_s": self.last_backoff_s,
                "ttfrt_s": self._h_ttfrt.percentiles((50, 90, 99))}
