"""Continuous-batching inference: slot KV pool + fixed-shape scheduler.

The training half of the nanoGPT capability surface lives in train.py;
this package is the serving half the ROADMAP's "heavy traffic" north
star needs. sample.py jits one fixed-shape generate per invocation and
serves exactly one prompt shape at a time; batch-1 decode is
weight-read-bound (the whole parameter set streams from HBM per token),
so multiplexing many requests through ONE compiled decode step is the
single largest throughput lever on TPU.

Pieces:
  scheduler.py — SlotScheduler: FIFO queue, free-slot pool, prefill
                 bucket ladder + admission-wave ladder (the fixed-shape
                 admission policy).
  engine.py    — Engine: block-paged KV pool (dense per-slot rows as the
                 comparison baseline), batched wave prefill, pipelined
                 per-row decode over device-resident slot state,
                 submit()/step()/drain().
  paged.py     — BlockPool + RadixPrefixCache: host-side block-id
                 allocator with refcounted radix prefix reuse and LRU
                 eviction (the elastic-memory half of ROADMAP item 2).
  http.py      — EngineLoop (background stepping thread) + a stdlib
                 ThreadingHTTPServer frontend.
  drafters.py  — speculative draft proposers: NGramDrafter (host-side
                 prompt lookup, zero extra weights) and ModelDrafter (a
                 small same-tokenizer GPT with its own slot-pool cache).
  spec.py      — SpecRunner: the fixed-shape batched verification step
                 (k+1 positions per slot, one program) + rejection
                 sampling with per-row accepted lengths.
  faults.py    — FaultPlan: deterministic, seeded fault injection at
                 named hot-path sites (chaos testing; zero cost when
                 no plan is attached).
  recovery.py  — EngineSupervisor: crash-safe stepping — quarantine,
                 device-state rebuild, re-admission of in-flight
                 requests, bounded backoff, permanent-failure drain.
  brownout.py  — BrownoutController: SLO-ledger-driven graceful load
                 degradation (shrink scan -> suspend spec -> shed
                 batch -> interactive only) with hysteresis.
  router.py    — PrefixAffinityRouter: fleet-level routing over N
                 replicas by radix-prefix affinity (approximate
                 per-replica digest index, load/brownout/readiness
                 scoring, failover fallback).
  disagg.py    — DisaggPair: disaggregated prefill/decode serving —
                 a prefill tier exports {block chain, first token,
                 seed} into migration limbo and a decode tier adopts
                 it as a pure prefix hit (zero prefill dispatches),
                 with wire serialization for the cross-pod form.
  fleet.py     — Fleet: in-process N-replica harness behind the router
                 (namespaced flight ledgers, replica_down failover with
                 exactly-once terminals, aggregated retry hints) — the
                 test bench for the policy the HTTP front tier and the
                 k8s router Deployment run.
  __main__.py  — `python -m nanosandbox_tpu.serve` entrypoint: restore a
                 checkpoint and serve it.
"""

from nanosandbox_tpu.serve.brownout import LEVELS as BROWNOUT_LEVELS
from nanosandbox_tpu.serve.brownout import BrownoutController
from nanosandbox_tpu.serve.disagg import (DisaggPair, adopt_from_wire,
                                          export_to_wire)
from nanosandbox_tpu.serve.drafters import (ModelDrafter, NGramDrafter,
                                            drafter_from_flag)
from nanosandbox_tpu.serve.engine import (DEFAULT_PRIORITY,
                                          PRIORITY_BY_CLASS, Engine,
                                          EngineFailedError, Request,
                                          Result)
from nanosandbox_tpu.serve.faults import (CANNED, FaultInjected, FaultPlan,
                                          FaultSpec)
from nanosandbox_tpu.serve.fleet import Fleet
from nanosandbox_tpu.serve.paged import (Allocation, BlockPool,
                                         RadixPrefixCache, blocks_for,
                                         prefix_digests)
from nanosandbox_tpu.serve.recovery import EngineSupervisor
from nanosandbox_tpu.serve.router import (NoReadyReplicaError,
                                          PrefixAffinityRouter,
                                          RouteDecision)
from nanosandbox_tpu.serve.scheduler import (SlotScheduler, admit_ladder,
                                             default_buckets)

__all__ = ["Engine", "Request", "Result", "SlotScheduler",
           "admit_ladder", "default_buckets", "NGramDrafter",
           "ModelDrafter", "drafter_from_flag", "BlockPool",
           "RadixPrefixCache", "Allocation", "blocks_for",
           "prefix_digests", "FaultPlan", "FaultSpec", "FaultInjected",
           "CANNED", "EngineSupervisor", "EngineFailedError",
           "BrownoutController", "BROWNOUT_LEVELS",
           "PRIORITY_BY_CLASS", "DEFAULT_PRIORITY",
           "Fleet", "PrefixAffinityRouter", "RouteDecision",
           "NoReadyReplicaError", "DisaggPair", "export_to_wire",
           "adopt_from_wire"]
