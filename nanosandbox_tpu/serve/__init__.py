"""Continuous-batching inference: slot KV pool + fixed-shape scheduler.

The training half of the nanoGPT capability surface lives in train.py;
this package is the serving half the ROADMAP's "heavy traffic" north
star needs. sample.py jits one fixed-shape generate per invocation and
serves exactly one prompt shape at a time; batch-1 decode is
weight-read-bound (the whole parameter set streams from HBM per token),
so multiplexing many requests through ONE compiled decode step is the
single largest throughput lever on TPU.

Pieces:
  scheduler.py — SlotScheduler: FIFO queue, free-slot pool, prefill
                 bucket ladder + admission-wave ladder (the fixed-shape
                 admission policy).
  engine.py    — Engine: slot-based KV cache pool, batched wave prefill,
                 pipelined per-row decode over device-resident slot
                 state, submit()/step()/drain().
  http.py      — EngineLoop (background stepping thread) + a stdlib
                 ThreadingHTTPServer frontend.
  __main__.py  — `python -m nanosandbox_tpu.serve` entrypoint: restore a
                 checkpoint and serve it.
"""

from nanosandbox_tpu.serve.engine import Engine, Request, Result
from nanosandbox_tpu.serve.scheduler import (SlotScheduler, admit_ladder,
                                             default_buckets)

__all__ = ["Engine", "Request", "Result", "SlotScheduler",
           "admit_ladder", "default_buckets"]
