"""Continuous-batching decode engine over a slot-based KV cache pool.

Design (the TPU fixed-shape discipline, end to end):

  * One per-layer KV pool of shape (num_slots, H, max_len, D)
    (models/gpt.py init_cache with batch = num_slots). Each in-flight
    request OWNS one slot row for its lifetime; eviction is just
    returning the row to the free list — no copies, the next occupant's
    prefill overwrites it and the per-row causal mask hides any stale
    tail.

  * Batched prefill: an admission WAVE — the FIFO prefix of the queue
    sharing one prompt bucket, up to the free slots — runs the model
    once over a (k, L_bucket) prompt block, scatters the K/V rows into
    the wave's slot rows, and samples each request's first token from
    its TRUE last prompt position. k is padded up a power-of-two ladder
    (scheduler.admit_ladder) so the compile set stays bounded at
    len(admit_ladder) * len(buckets) programs.

  * Device-resident slot state: the per-slot decode operands
    (pos/tok/temp/top_k/top_p/seed/active) live in a donated on-device
    struct threaded through the decode step alongside the pool — the
    decode hot loop uploads NOTHING from the host. Admission and
    eviction mutate the struct through two small compiled programs
    (_admit_fn / _release_fn) instead of re-staging six host arrays
    every token.

  * Pipelined decode: step k+1 is dispatched from the device-resident
    token array of step k BEFORE step k's tokens are read back, so the
    per-token host round trip overlaps device compute instead of
    serializing with it (the same async-dispatch discipline
    train.estimate_loss applies to eval). Finish/eviction decisions
    therefore lag ONE step: a row that finished at step k still rides
    along in step k+1, and its ride-along token is dropped at readback
    via the dispatch-time (slot -> rid) snapshot — a backfilled slot's
    new occupant can never inherit it. On device the active mask parks
    finished/idle rows (pos frozen, token pinned) so their garbage
    stays inside their own slot row.

  * Sampling is per-row (_sample_token with (S,) parameter vectors) and
    per-row keyed: the token at position q of request r is sampled with
    fold_in(key(r.seed), q), so a request's output stream is a pure
    function of (params, prompt, settings, seed) — independent of which
    other requests happen to share its batch. That invariant is what
    makes continuous batching testable against single-request
    sample.generate token-for-token, and it survives pipelining because
    the device state the next step consumes is exactly the sampled
    token the host would have re-uploaded.

  * Speculative decoding (spec=...): a drafter (serve/drafters.py)
    guesses k tokens per slot and ONE fixed-shape verify program
    (serve/spec.py) scores all k+1 positions per row against the slot
    pool, accepting the longest target-agreed prefix plus one fresh
    token — up to k+1 tokens per forward instead of 1, outputs
    distributed exactly as non-spec decode (greedy: token-identical).
    Spec steps replace the decode dispatch and run SYNCHRONOUSLY: a
    host drafter needs the latest tokens to propose from, and the
    verify readback (accepted lengths) gates the next frontier, so
    the one-step pipeline lag has nothing to overlap.

The engine is single-threaded by design (one step() == at most one
decode dispatch + one lagged readback); http.py wraps it in a
background thread for concurrent clients.
"""

from __future__ import annotations

import itertools
import os
import tempfile
import threading
import time
from collections import deque
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from nanosandbox_tpu.obs import (FlightRecorder, MetricRegistry, SLOLedger,
                                 SpanTracer, WatchdogPanel,
                                 validate_slo_class)
from nanosandbox_tpu.serve.brownout import BrownoutController
from nanosandbox_tpu.serve.faults import FaultInjected, FaultPlan
from nanosandbox_tpu.serve.scheduler import SlotScheduler, default_buckets
from nanosandbox_tpu.utils import tracecheck as _tracecheck
from nanosandbox_tpu.utils.tracecheck import TraceBudgetRegistry

# Scheduling priority by SLO class (ISSUE 13): higher admits first.
# Interactive traffic outranks the default class, which outranks batch;
# an explicit Request.priority overrides the class mapping, and unknown
# classes land on the default. The brownout ladder's shed floors
# (serve/brownout.py) are expressed against these same numbers.
PRIORITY_BY_CLASS = {"batch": 0, "default": 1, "interactive": 2}
DEFAULT_PRIORITY = 1


# Consecutive poisoned readbacks a row survives before it terminates
# 'failed' — the UNSUPERVISED backstop: with an EngineSupervisor the
# first poison triggers a recovery (fresh row state, counter gone), so
# the limit is only ever reached when nobody is recovering and the
# poison is persistent (bad checkpoint, broken device). Pre-PR-11 such
# a row terminated with garbage tokens; wedging the slot forever would
# be strictly worse.
POISON_STRIKE_LIMIT = 3


class EngineFailedError(RuntimeError):
    """The engine escalated to permanent failure (recovery exhausted its
    attempts) and drained; submissions are refused until a restart. The
    HTTP layer maps this to 503 — clients should hit another replica."""


@dataclass(frozen=True)
class Request:
    """One generation request, in token-id space (the HTTP layer owns
    text <-> tokens). ``deadline_s`` is the submit-to-finish SLO budget
    (None = best-effort: never SLO-tracked, never shed); ``slo_class``
    labels the request's SLO accounting on /metrics; ``priority``
    orders the scheduler queue (higher first; defaulted from the class
    via PRIORITY_BY_CLASS) and decides who preempts whom."""
    rid: int
    prompt: tuple
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: Optional[int] = None
    deadline_s: Optional[float] = None
    slo_class: str = "default"
    priority: int = DEFAULT_PRIORITY


@dataclass
class Result:
    rid: int
    prompt: tuple
    tokens: List[int]          # generated ids (includes the eos hit, if any)
    finish_reason: str         # 'length' | 'eos' | 'shed' | 'failed'
    # Chained fingerprints of the prompt's full KV blocks DONATED to
    # this engine's radix cache at finish (paged.prefix_digests; empty
    # when the prefix cache is off or nothing was donated). The fleet
    # router's per-replica index ingests these — "replica r now holds
    # this chain" — which is what turns the radix cache into a
    # fleet-wide routing signal (ISSUE 15).
    prefix_digest: tuple = ()


@dataclass
class _Active:
    req: Request
    slot: int
    tokens: List[int] = field(default_factory=list)
    first_token_t: float = 0.0   # wall clock of the prefill-token readback
    submit_t: float = 0.0        # wall clock at submit (SLO end-to-end)
    last_t: float = 0.0          # wall clock of the last retired token
    spec_accepted: int = 0       # draft tokens this request accepted
    span: int = 0                # open "generate" span id (obs tracer)
    alloc: object = None         # paged.Allocation (block-paged engines)
    poison_strikes: int = 0      # consecutive poisoned readbacks (row
    #                              terminates 'failed' at the cap when
    #                              no supervisor recovers in between)


@dataclass
class _Resume:
    """Host-side stitch record for a request re-admitted after an
    engine recovery OR a priority preemption: the ORIGINAL prompt and
    the tokens generated before the interruption, so the terminal
    Result (and its flight/SLO accounting) reads as one uninterrupted
    request."""
    prompt: tuple
    tokens: List[int]
    submit_t: float


@dataclass
class _Chunking:
    """One request mid-chunked-prefill (ISSUE 13): popped from the
    queue with a slot claimed and ALL blocks reserved, its (suffix)
    prompt lands in the KV pool across several bucket-shaped prefill
    dispatches interleaved with decode steps. ``hit`` is the prefix-
    cache hit (the first chunk's cache_index); ``done`` counts suffix
    tokens already written. Intermediate chunks carry the sentinel slot
    id — no admit scatter, no readback — so only the FINAL chunk
    samples a first token and activates the row."""
    req: Request
    slot: int
    alloc: object
    hit: int
    done: int = 0


@dataclass
class _Export:
    """One request parked in MIGRATION LIMBO (ISSUE 16): its prefill
    completed on this engine — the whole prompt's K/V sits in
    ``alloc``'s block chain and ``first_tok`` was sampled with the
    fold_in(seed, true_len) key — but its decode belongs to another
    tier. The slot was released at export (the row must never decode
    here), so the record owns exactly {blocks, first token, request}:
    the migration wire format. Parked in the scheduler's limbo queue,
    where the deadline sweep sees it like any queued request; shed or
    aborted from limbo, its blocks free WITHOUT donation (the handoff
    never completed — the terminal says so, the cache must not claim
    otherwise... the chain IS fully written, but a shed request's
    blocks are freed not donated by the ISSUE 16 contract: nothing
    should warm a cache on traffic the engine refused to serve)."""
    req: Request
    alloc: object
    first_tok: int
    export_t: float              # wall clock at export (migration p50/p99)
    submit_t: float              # wall clock at submit (deadline budget)
    submit_step: int

    # drain_expired applies one predicate to queue items and limbo
    # records alike — forward the fields it reads.
    @property
    def rid(self) -> int:
        return self.req.rid

    @property
    def deadline_s(self) -> Optional[float]:
        return self.req.deadline_s

    @property
    def priority(self) -> int:
        return self.req.priority


@dataclass
class _Adoption:
    """The adopt-side handle between begin_adopt (slot + blocks
    reserved, nothing written) and commit_adopt (row activated) /
    abort_adopt (unwound). ``copy`` lists the chain positions whose
    blocks the caller must fill from the source pool before commit —
    ``dst_blocks`` are their block ids here."""
    req: Request
    slot: int
    alloc: object
    copy: List[int]

    @property
    def dst_blocks(self) -> List[int]:
        return [self.alloc.table[i] for i in self.copy]


class Engine:
    """submit() / step() / drain() continuous-batching engine.

    Parameters
    ----------
    model, params : the flax GPT and its (cast) params — exactly what
        sample.generate takes, so one checkpoint serves both paths.
    num_slots : concurrent request capacity (the decode batch).
    max_len : per-slot KV length; prompt + new tokens must fit. Capped
        at block_size (wpe defines no positions past it).
    prefill_buckets : padded prompt lengths to compile; default is the
        power-of-two ladder up to max_len.
    pipeline : keep one decode step in flight ahead of the host
        (default). False restores the synchronous PR-1 loop — dispatch,
        read back, repeat — which bench.py uses as the comparison
        baseline; results are identical either way, only the
        dispatch/readback overlap differs.
    spec : a drafter (serve/drafters.py NGramDrafter / ModelDrafter, or
        anything matching the host protocol) enabling speculative
        decoding: each "decode" step verifies k drafted tokens per slot
        in one fixed-shape forward instead of computing one. Forces the
        synchronous loop (see module docstring); greedy outputs are
        token-identical to spec=None, sampled outputs identically
        distributed.
    scan_k : decode steps fused into ONE compiled dispatch via lax.scan
        (default 1, the classic per-token loop). With scan_k = k the
        host dispatches once per k tokens — sample -> (paged) KV
        quantize-and-write through the block table -> frontier advance
        all stay in-program — so the per-dispatch host floor (~180 us
        per staging upload measured in PR 9) amortizes over k tokens.
        Finish detection lags up to k steps: a row hitting eos or its
        budget mid-chunk keeps riding the chunk on device, its overrun
        tokens truncate at readback, and its overrun KV writes land in
        its own private frontier positions (dense) or drop on the
        sentinel block-table entries past its reservation (paged) —
        the PR 2 lagged-retire argument stretched from lag-1 to lag-k.
        Composes with ``pipeline`` (one k-chunk in flight ahead of the
        host); forced to 1 under ``spec`` (the verify readback gates
        the next frontier — there is no chunk to fuse). Tradeoff:
        larger k = fewer dispatches, but more wasted lane work when
        rows finish mid-chunk and chunk-granular TTFT for backfilled
        requests (docs/playbook.md has the k-vs-lag table). Greedy
        outputs are token-identical to scan_k=1 (pinned by test).
    metrics : obs.MetricRegistry to publish on (default: a fresh
        per-engine registry — tests spin up many engines). Counters and
        gauges are mirrored from the engine's plain ints by a
        collection-time callback, so the hot loop never touches them;
        only the latency histograms observe per event.
    tracer : obs.SpanTracer recording the span timeline (prefill waves,
        decode steps with the pipelined one-step-lag retire, spec verify
        rounds, per-request queued/generate). Default: a fresh bounded
        tracer; records only already-host-resident ints/floats, so it
        adds no host sync.
    kv_dtype : KV-pool storage mode ('fp32' | 'bf16' | 'int8'; default
        None = the model's compute dtype, the pre-int8 behavior).
        'int8' stores per-(slot, head, position) scales alongside the
        values (models/gpt.py init_cache): ~2x less HBM per cached
        token than bf16 — 2x the slots at constant HBM — and
        proportionally less decode read traffic. Applies to the
        drafter's pool too (spec verify and drafts read the same mode).
    decode_impl : cached-decode attention impl for the T=1 hot path
        ('auto' | 'pallas' | 'pallas_interpret' | 'xla',
        ops/flash_decode.py ladder). Default None keeps the model
        config's own setting. The RESOLVED impl (auto settles on
        pallas or xla at construction, with a warn_once when a TPU
        lands on the fallback) is exported as the
        serve_decode_attention_impl gauge and in stats().
    paged : block-paged KV pool (default True, the ROADMAP-2 layout):
        the pool is a global heap of kv_pool_blocks fixed-size blocks
        of kv_page_size positions, a device-resident (num_slots,
        max_blocks) block table maps each slot's positions onto blocks,
        and admission reserves each request's ACTUAL need
        (ceil((prompt + max_new) / page) blocks) instead of a dense
        worst-case (max_len) row — elastic memory at constant pool
        bytes, plus prefix reuse (below). False restores the dense
        per-slot rows (the PR 8 layout), kept as the bench comparison
        baseline. Same compile set either way: the block table is
        DATA, not shape, so max_programs() is identical.
    kv_page_size : positions per KV block (paged only; must divide
        max_len). Small pages waste less memory on final-block
        fragmentation and shorten shareable-prefix granularity; large
        pages cut table overhead and DMA count. On real TPUs int8
        pools want >= 32 (the sublane tiling quantum — the compile
        probe rejects smaller and decode falls back to XLA).
    kv_pool_blocks : pool size in blocks (paged only; default
        num_slots * max_len / page — byte-identical to the dense
        pool, so paged-vs-dense comparisons hold pool HBM constant
        while capacity becomes elastic).
    prefix_cache : radix/trie prefix reuse over finished requests'
        prompt blocks (paged only, default True): a request whose
        prompt prefix is resident skips those prefill chunks entirely
        — admission prefills only the (bucketed) suffix — with
        refcounted copy-on-write block sharing and LRU eviction of
        refcount-zero blocks (serve/paged.py).
    flight : obs.FlightRecorder for the per-request lifecycle ledger
        (default: a fresh bounded recorder). Records submit -> queue ->
        block-reserve/stall -> admit -> prefill[hit|miss] -> retire* ->
        evict -> finish|reject|shed, from already-host-resident
        dispatch-time state only — no host sync, < 50 us/event (pinned).
        Serves GET /debug/requests and the watchdog dumps.
    watchdogs / watchdog_dir : anomaly watchdogs (obs.WatchdogPanel:
        TTFT spike, admission stall, pool thrash, post-steady retrace,
        stuck slot). A trip counts on watchdog_trips_total{kind=} and
        snapshots flight + span ring + stats() into watchdog_dir
        (default: a tempdir created on the first trip).
    default_deadline_s : deadline applied to requests that submit none
        (None = best-effort). A queued request whose deadline expires
        before admission is SHED — a terminal 'shed' Result instead of
        burning a slot on an answer its client stopped waiting for —
        and every deadline-carrying request lands in the SLO ledger
        (attainment, goodput tokens, deadline margin) on /metrics.
    faults : a serve.faults.FaultPlan injecting deterministic failures
        at named hot-path sites (nan_logits, slow_step, alloc_fail,
        drafter_fault, scatter_corrupt, prefill_exc) — chaos testing
        and the recovery subsystem's test bench. None (the default)
        reduces every site to one `is None` branch: production pays
        nothing, and the compile set / host-sync ledger are identical
        with and without the hook (pinned by test).
    spec_fault_tolerance : consecutive drafter faults absorbed (each
        degrades that step to plain decode) before speculative decoding
        auto-DISABLES for the engine's lifetime — degrade, don't die:
        a dead drafter costs throughput, never correctness or uptime.
    prefill_chunk : per-STEP prefill token budget (ISSUE 13; None = the
        classic admit-everything-now behavior). Must be one of the
        prefill buckets. Each engine step spends at most ~this many
        prefill tokens before dispatching its decode step, so a
        prefill storm interleaves with decode instead of stalling every
        active row's TPOT for the whole wave. Paged engines
        additionally SPLIT a single long (suffix) prompt into
        chunk-sized pieces across steps — each chunk is an ordinary
        (1, bucket) prefill dispatch writing at cache_index = tokens-
        already-prefilled, exactly the prefix-hit machinery, so the
        compile set does not widen (max_programs() identical, pinned).
        Dense engines cannot split one prompt (their prefill has no
        write offset) and fall back to pacing whole waves.
    preemption : allow deadline-driven preemption-by-eviction (default
        True): when the highest-priority queued request would miss its
        deadline waiting on slots or KV blocks, the lowest-priority
        active victim is evicted — its blocks (prompt AND generated)
        are donated to the radix cache and it requeues with prompt' =
        prompt + tokens-so-far through the recovery _Resume path, so
        its resume is a prefix hit and greedy output is token-identical
        to an unpreempted run (pinned). Equal-priority traffic never
        preempts, so single-class deployments behave exactly as before.
    brownout : attach a BrownoutController (serve/brownout.py): an
        SLO-ledger-driven ladder of named degradation levels (shrink
        scan chunk -> suspend spec -> shed batch class -> interactive
        only) with hysteresis, each transition a flight/metrics event.
        Default False; `python -m nanosandbox_tpu.serve` turns it on.
    tp : tensor-parallel degree (default 1 = today's single-chip
        engine, bit-for-bit unchanged). tp > 1 shards ONE engine over
        a (1, 1, 1, tp) mesh on the first tp devices: weights via the
        Megatron placements in parallel/sharding.py (column-parallel
        c_attn/c_fc, row-parallel c_proj), the KV pool — paged block
        heap or dense slot rows — and its per-position scale planes
        row-sharded along the HEADS dim over the ``model`` axis, and
        the per-slot frontier/slot state replicated (it is O(slots)
        ints; the bytes live in the pool). Decode/prefill/scan/verify
        all ride with_sharding_constraint anchors (models/gpt.py) so
        the only collectives are the bounded per-block activation
        exchanges — one model-axis all-reduce per block plus the qkv
        head resharding — never a full-pool all-gather; the committed
        budgets/serve_tp_cpu8.json pins exactly that contract in CI.
        Greedy outputs are token-identical to tp=1 (same keys, same
        per-row math; collectives are deterministic — pinned by test),
        the compile set does not widen, and recovery/preemption
        rebuild the SHARDED placements. Requires n_head % tp == 0.
        Flash kernels run per-shard over local heads via shard_map;
        the gather-free XLA paths partition under the same anchors.
    tp_mesh : an explicit mesh to shard over instead of the default
        (1, 1, 1, tp) slice — shardcheck's fleet lowers the tp=2
        engine under the full cpu8 mesh this way. Its ``model`` axis
        size must equal ``tp``.
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 max_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None,
                 pipeline: bool = True, spec=None, scan_k: int = 1,
                 metrics: Optional[MetricRegistry] = None,
                 tracer: Optional[SpanTracer] = None,
                 kv_dtype: Optional[str] = None,
                 decode_impl: Optional[str] = None,
                 paged: bool = True, kv_page_size: int = 16,
                 kv_pool_blocks: Optional[int] = None,
                 prefix_cache: bool = True,
                 flight: Optional[FlightRecorder] = None,
                 watchdogs: bool = True,
                 watchdog_dir: Optional[str] = None,
                 default_deadline_s: Optional[float] = None,
                 faults: Optional[FaultPlan] = None,
                 spec_fault_tolerance: int = 3,
                 prefill_chunk: Optional[int] = None,
                 preemption: bool = True,
                 brownout: bool = False,
                 tp: int = 1, tp_mesh=None,
                 role: str = "both"):
        import jax
        import jax.numpy as jnp

        from nanosandbox_tpu.models.gpt import (init_cache,
                                                init_paged_cache,
                                                normalize_kv_dtype)
        from nanosandbox_tpu.ops.flash_decode import resolve_decode_impl
        from nanosandbox_tpu.serve.paged import BlockPool

        if decode_impl is not None and decode_impl != model.cfg.decode_impl:
            # Rebind the module with the requested decode impl; params
            # are impl-independent, so the same tree serves the rebuilt
            # module (the same move sample.py relies on for dtype casts).
            model = type(model)(
                cfg=model.cfg.replace(decode_impl=decode_impl),
                mesh=getattr(model, "mesh", None))
        cfg = model.cfg
        # Tensor-parallel setup (tp > 1): build/validate the mesh, bind
        # it onto the model (the with_sharding_constraint anchors in
        # models/gpt.py key off it), and commit the weights to their
        # Megatron placements. Pool/state placement happens below where
        # those arrays are built; tp == 1 takes none of these branches.
        self.tp = int(tp)
        self._mesh = None
        self._rep = None
        if self.tp < 1:
            raise ValueError(f"tp must be >= 1, got {tp}")
        if self.tp > 1:
            from jax.sharding import NamedSharding, PartitionSpec
            from nanosandbox_tpu.parallel.mesh import (axis_sizes,
                                                       make_mesh)
            from nanosandbox_tpu.parallel.sharding import param_shardings

            if cfg.n_head % self.tp:
                raise ValueError(
                    f"tp={self.tp} must divide n_head={cfg.n_head}: the "
                    "KV pool shards along the heads dim")
            if tp_mesh is not None:
                mesh = tp_mesh
                if axis_sizes(mesh).get("model", 1) != self.tp:
                    raise ValueError(
                        f"tp_mesh model axis is {axis_sizes(mesh)} but "
                        f"tp={self.tp}")
            else:
                devs = jax.devices()
                if len(devs) < self.tp:
                    raise ValueError(
                        f"tp={self.tp} needs {self.tp} devices, have "
                        f"{len(devs)}")
                mesh = make_mesh(1, 1, self.tp, 1,
                                 devices=devs[:self.tp])
            self._mesh = mesh
            self._rep = NamedSharding(mesh, PartitionSpec())
            model = type(model)(cfg=cfg, mesh=mesh)
            params = jax.device_put(
                params,
                param_shardings(mesh, jax.eval_shape(lambda: params),
                                shard_params=False, tp=True))
        self.kv_dtype = normalize_kv_dtype(kv_dtype) or (
            "bf16" if cfg.compute_dtype == "bfloat16" else "fp32")
        # Resolve ONCE at construction (the probe caches per backend):
        # 'auto' degrading to xla on a TPU fires the warn_once here, at
        # startup, not silently inside the first traced decode step.
        self.decode_impl = resolve_decode_impl(cfg.decode_impl)
        self.model = model
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        # Spec steps must read accepted lengths back before the next
        # dispatch (and host drafters propose from the latest tokens),
        # so speculative mode runs the synchronous loop.
        self.pipeline = bool(pipeline) and spec is None
        if scan_k < 1:
            raise ValueError(f"scan_k must be >= 1, got {scan_k}")
        # scan_k composes with the pipeline, not with verify: a spec
        # step's readback gates the next frontier, so under spec the
        # chunk length collapses to 1 (the sync loop).
        self.scan_k = 1 if spec is not None else int(scan_k)
        # The scan-chunk rung ladder: power-of-two chunk lengths up to
        # scan_k (plus scan_k itself when off the ladder), one compiled
        # megaprogram per rung. Each dispatch picks the largest rung no
        # live row's remaining budget overruns, so a row one token from
        # its budget pulls the chunk down to what everyone can use
        # instead of riding 7 wasted lane-steps — budget overrun waste
        # is structurally zero (only eos still overruns, and eos is
        # host knowledge by design). The ladder is the compile-set
        # growth the budgets pin: len(scan_rungs) decode programs.
        self.scan_rungs = [1]
        r = 2
        while r < self.scan_k:
            self.scan_rungs.append(r)
            r *= 2
        if self.scan_k > 1:
            self.scan_rungs.append(self.scan_k)
        self.max_len = min(max_len or cfg.block_size, cfg.block_size)
        buckets = (sorted(b for b in prefill_buckets if b <= self.max_len)
                   if prefill_buckets else default_buckets(self.max_len))
        if not buckets:
            raise ValueError("no prefill bucket fits within max_len "
                             f"{self.max_len}: {prefill_buckets!r}")
        self.sched = SlotScheduler(num_slots, buckets)
        self.admit_buckets = self.sched.admit_buckets
        # Chunked prefill (ISSUE 13): the per-step prefill token budget.
        # The chunk must be a BUCKET so chunk dispatches reuse the
        # existing (rung, bucket) prefill grid — any other size would
        # either widen the compile set or pad every chunk.
        if prefill_chunk is not None:
            prefill_chunk = int(prefill_chunk)
            if prefill_chunk not in self.sched.buckets:
                raise ValueError(
                    f"prefill_chunk {prefill_chunk} must be one of the "
                    f"prefill buckets {self.sched.buckets} (chunk "
                    "dispatches reuse the bucket-shaped programs)")
        self.prefill_chunk = prefill_chunk
        self.preemption = bool(preemption)
        self.preemptions = 0
        self._prefill_spent = 0              # tokens this step (budgeted)
        self._chunking: List[_Chunking] = []  # mid-chunked-prefill lane
        # Brownout knobs (set by the controller; consulted on the hot
        # path as one attribute read each — see serve/brownout.py).
        self.scan_cap: Optional[int] = None
        self.spec_suspended = False
        self.brownout_min_priority: Optional[int] = None

        if self.decode_impl != "xla":
            from nanosandbox_tpu.ops.flash_decode import (decode_pad_copies,
                                                          paged_pad_copies)
            from nanosandbox_tpu.utils.metrics import warn_once

            pad = (paged_pad_copies(kv_page_size, cfg.n_embd // cfg.n_head)
                   if paged else
                   decode_pad_copies(self.max_len, cfg.n_embd // cfg.n_head))
            if pad:
                # The kernel would jnp.pad — copy — the whole pool
                # inside EVERY decode step, erasing the bytes the
                # kernel/int8 exist to save. Loud beats silent.
                warn_once(
                    f"flash-decode-pad-copy-{self.max_len}",
                    f"[serve] max_len={self.max_len} (head_dim "
                    f"{cfg.n_embd // cfg.n_head}) forces the flash-decode "
                    "kernel to pad-copy the KV pool on every step — use a "
                    "multiple of 32 (and head_dim 64 or a 128-multiple) "
                    "to keep the decode read zero-copy.")
        self.paged = bool(paged)
        self.kv_page_size = int(kv_page_size) if self.paged else 0
        self.block_pool = None
        if self.paged:
            if kv_page_size < 1:
                raise ValueError(
                    f"kv_page_size must be >= 1, got {kv_page_size}")
            # ceil: a max_len off the page quantum just leaves the last
            # block of a full-length request partially used.
            self.slot_blocks = -(-self.max_len // kv_page_size)
            self.kv_pool_blocks = int(kv_pool_blocks
                                      or num_slots * self.slot_blocks)
            self._pool = self._place_pool(
                init_paged_cache(cfg, self.kv_pool_blocks, kv_page_size,
                                 kv_dtype=kv_dtype))
            self.block_pool = BlockPool(self.kv_pool_blocks, kv_page_size,
                                        prefix_cache=prefix_cache)
        else:
            self.slot_blocks = 0
            self.kv_pool_blocks = 0
            self._pool = self._place_pool(
                init_cache(cfg, num_slots, self.max_len,
                           kv_dtype=kv_dtype))
        # The kv_dtype ARGUMENT (not the resolved mode): recover() must
        # rebuild the pool with exactly the constructor's layout.
        self._kv_dtype_arg = kv_dtype
        # Device-resident per-slot decode operands. Idle rows keep
        # harmless parked values (pos 0, temperature 0, active False):
        # their garbage decode writes stay inside their own slot row —
        # paged engines park the block-table row on the out-of-range
        # sentinel (kv_pool_blocks) instead, so an idle row's garbage
        # writes DROP rather than touch a block it no longer owns.
        self._state = self._fresh_slot_state()

        self._active: Dict[int, _Active] = {}        # slot -> state
        self._pending_results: List[Result] = []     # max_new_tokens == 0
        # The one decode step/chunk in flight ahead of the host:
        # (device token array — (S,) single-step or (k, S) chunk,
        # {slot: rid} snapshot at dispatch, open decode_step span id,
        # the dispatch's step number = the scan-chunk index the flight
        # retire events carry, and the chunk length the next rung
        # choice subtracts). The snapshot is the host half of the
        # eviction lag — a slot whose occupant changed between dispatch
        # and readback drops its ride-along tokens. The span closes at
        # RETIRE, so the exported timeline shows chunk k overlapping
        # chunk k+1's dispatch — the pipeline's true shape.
        self._inflight: Optional[
            Tuple[object, Dict[int, int], int, int, int]] = None
        self._rid = itertools.count()
        # rid -> (submit step, submit wall clock, open "queued" span id)
        self._submit_meta: Dict[int, Tuple[int, float, int]] = {}
        self.steps = 0
        self.admitted = 0
        self.completed = 0
        self.tokens_generated = 0
        # Host-dispatch ledger (ISSUE 12): every compiled-program launch
        # the engine performs, by program kind — the denominator of the
        # dispatch-floor story scan_k attacks. Plain ints on the hot
        # path, mirrored into labeled counters at collection time.
        self.host_dispatches: Dict[str, int] = {
            "decode": 0, "prefill": 0, "admit": 0, "release": 0,
            "verify": 0}
        self.shed = 0                                # deadline-expired drops
        # Deadline-carrying sheds CAUSED BY the brownout floor (subset
        # of the SLO ledger's shed count): the controller subtracts
        # these from its window signal — its own shedding must read as
        # load REMOVED, not as ongoing burn, or level 3 would sustain
        # itself on the traffic it sheds and never clear.
        self.brownout_sheds = 0
        self.rejected: Dict[str, int] = {}           # submit rejects, by kind
        # Fault-injection + crash-safe recovery state (ISSUE 11). The
        # hooks cost one `is None` branch each when no plan is attached;
        # recovery bookkeeping is cold-path only.
        self.faults = faults
        if faults is not None:
            faults.arm(0)
        self.spec_fault_tolerance = int(spec_fault_tolerance)
        self.quarantined = False
        self.quarantine_cause: Optional[str] = None
        self.failed = False
        self.recoveries = 0
        self.poisoned_steps = 0
        self.requeued = 0
        self.drafter_faults = 0
        self.spec_disabled_reason: Optional[str] = None
        self._drafter_fault_streak = 0
        self._poison: Optional[dict] = None
        # The wave currently mid-prefill: (req, slot, alloc) triples,
        # populated between the queue pop and the admission commit so a
        # prefill-dispatch crash leaves recover() enough to requeue.
        # _admitting_span is the wave's open tracer span, ended by
        # recover()/abort_all() when a crash skips the normal close.
        self._admitting: List[Tuple] = []
        self._admitting_span: Optional[int] = None
        self._resumed: Dict[int, _Resume] = {}
        # Disaggregated serving (ISSUE 16). ``role`` labels the tier
        # this engine plays ("prefill" runs chunked waves and exports,
        # "decode" adopts migrated chains, "both" is the classic
        # colocated engine — the role is telemetry + fleet routing
        # metadata, never a capability gate: a prefill engine that must
        # fall back to colocated decode, e.g. when its decode tier
        # died, still can). ``_migrate_rids`` marks requests submitted
        # with migrate=True: they allocate prompt-only block footprints
        # and EXPORT at the first-token readback instead of going
        # active. ``migrated``/``adopted`` count handoffs out of / into
        # this engine.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', got "
                f"{role!r}")
        self.role = role
        self._migrate_rids: set = set()
        self.migrated = 0
        self.adopted = 0
        if default_deadline_s is not None and default_deadline_s <= 0:
            raise ValueError(f"default_deadline_s must be > 0, got "
                             f"{default_deadline_s}")
        self.default_deadline_s = default_deadline_s
        # Telemetry spine (nanosandbox_tpu/obs): the latency signal
        # lives in registry histograms (RingStat window + Prometheus
        # buckets — /stats and /metrics read the SAME series), counters
        # and gauges mirror the engine's plain ints at collection time
        # (zero hot-loop cost), and the tracer records the span
        # timeline /trace exports.
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.tracer = tracer if tracer is not None else SpanTracer()
        m = self.metrics
        # One engine per registry: re-registration would hand BOTH
        # engines the same unlabeled families, and their collectors
        # would silently overwrite each other's mirrored counters at
        # every scrape. Loud beats last-writer-wins.
        if any(f.name == "serve_ttft_seconds" for f in m.families()):
            raise ValueError(
                "metrics registry already hosts an Engine's families; "
                "give each Engine its own MetricRegistry")
        self._ttft = m.histogram(
            "serve_ttft_seconds", "Submit -> first-token seconds.",
            unit="seconds")
        self._tpot = m.histogram(
            "serve_tpot_seconds", "Per-token seconds after the first.",
            unit="seconds")
        self._queue_wait = m.histogram(
            "serve_queue_wait_steps",
            "Decode steps a request spent queued before admission.",
            unit="steps", buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128))
        self._c_submitted = m.counter(
            "serve_requests_submitted_total", "Requests accepted by submit().")
        self._c_completed = m.counter(
            "serve_requests_completed_total",
            "Requests finished, by finish reason.", labelnames=("reason",))
        self._c_waves = m.counter(
            "serve_prefill_waves_total", "Batched prefill admission waves.")
        self._c_tokens = m.counter(
            "serve_tokens_generated_total", "Generated tokens read back.")
        self._c_steps = m.counter(
            "serve_decode_steps_total",
            "Batched decode/verify step dispatches.")
        # Dispatch-floor observability (ISSUE 12): how many compiled-
        # program launches the host performs per kind, and how many
        # tokens each decode dispatch amortizes (scan_k's win, live).
        self._c_dispatches = m.counter(
            "serve_host_dispatches_total",
            "Compiled-program dispatches from the engine loop, by "
            "program kind.", labelnames=("kind",))
        self._g_toks_per_dispatch = m.gauge(
            "serve_tokens_per_dispatch",
            "Generated tokens per decode dispatch over the engine "
            "lifetime (== scan_k when every chunk retires fully).")
        self._c_admitted = m.counter(
            "serve_requests_admitted_total", "Requests admitted to slots.")
        self._c_traces = m.counter(
            "serve_compile_traces_total",
            "Observed jit traces of this engine's programs, by kind.",
            labelnames=("program",))
        self._g_active = m.gauge("serve_slots_active",
                                 "Slots owned by in-flight requests.")
        self._g_free = m.gauge("serve_slots_free", "Free KV-pool slots.")
        self._g_queued = m.gauge("serve_queue_depth",
                                 "Requests queued awaiting admission.")
        self._g_rate = m.gauge(
            "serve_decode_tokens_per_sec",
            "Generated tokens/sec over the recent readback window.")
        # The RESOLVED decode-attention impl and KV storage mode, as
        # 1-hot labeled gauges: a scrape can tell whether this engine is
        # on the flash kernel or silently landed on the xla fallback
        # (the warn_once above fires once; the gauge persists).
        self._g_impl = m.gauge(
            "serve_decode_attention_impl",
            "Resolved cached-decode attention impl (1 = active).",
            labelnames=("impl",))
        self._g_kv = m.gauge(
            "serve_kv_dtype", "KV-pool storage mode (1 = active).",
            labelnames=("kv_dtype",))
        # Tensor-parallel posture (ISSUE 14): the model-axis shard
        # count this engine decodes across (1 = single chip).
        self._g_tp = m.gauge(
            "serve_tp_degree",
            "Tensor-parallel degree of the decode engine (model-axis "
            "shards; 1 = single chip).")
        # Disaggregated-serving posture (ISSUE 16): which tier this
        # engine serves (1-hot), plus its sides of the migration flow.
        self._g_role = m.gauge(
            "serve_engine_role",
            "Serving tier of this engine (1 = active role).",
            labelnames=("role",))
        self._g_limbo = m.gauge(
            "serve_migration_limbo_depth",
            "Exports parked awaiting adoption by the decode tier.")
        self._c_migrated = m.counter(
            "serve_migrated_out_total",
            "Requests this engine prefilled and handed to another "
            "tier (terminal accounting moves with them).")
        self._c_adopted = m.counter(
            "serve_adopted_in_total",
            "Migrated requests this engine re-admitted as pure prefix "
            "hits (zero prefill dispatches).")
        # Paged-pool + prefix-cache signal (ISSUE 9): block states
        # partition the pool, the hit/miss token counters are the
        # prefix_hit_rate numerator/denominator, and TTFT re-observes
        # into a by-prefix-outcome labeled histogram so the hit-vs-miss
        # latency cut is a first-class /metrics series, not a bench-only
        # artifact. All mirrored/observed host-side — zero hot-loop cost.
        self._g_pool_blocks = m.gauge(
            "serve_kv_pool_blocks",
            "Paged KV pool blocks by state (free | live | cached).",
            labelnames=("state",))
        self._c_prefix_hit = m.counter(
            "serve_prefix_hit_tokens_total",
            "Prompt tokens skipped via radix prefix-cache hits.")
        self._c_prefix_miss = m.counter(
            "serve_prefix_miss_tokens_total",
            "Prompt tokens prefilled from scratch.")
        self._c_block_stalls = m.counter(
            "serve_admission_block_stall_steps_total",
            "Admission attempts deferred on KV-block availability "
            "(the no-deadlock backpressure: the request stays queued).")
        self._ttft_prefix = m.histogram(
            "serve_prefix_ttft_seconds",
            "Submit -> first-token seconds by prefix-cache outcome.",
            unit="seconds", labelnames=("prefix",))
        # Overload/SLO observability (ISSUE 10): submit-time rejects and
        # deadline sheds as mirrored counters, the SLO ledger (per-class
        # attainment / goodput / deadline margins) on the same registry,
        # the per-request flight recorder, and the anomaly watchdogs.
        # Label children appear only when the events actually happen —
        # a deadline-less deployment scrapes no placeholder SLO series.
        self._c_rejected = m.counter(
            "serve_requests_rejected_total",
            "Requests rejected at submit, by reason.",
            labelnames=("reason",))
        self._c_shed = m.counter(
            "serve_requests_shed_total",
            "Queued requests shed after their deadline expired.")
        # Scheduling-endgame signal (ISSUE 13): preemption-by-eviction
        # events, mirrored from a plain int at collection time.
        self._c_preempted = m.counter(
            "serve_preemptions_total",
            "Active requests preempted (evicted + requeued) so a "
            "higher-priority deadline could admit.")
        # Crash-safe recovery signal (ISSUE 11): recovery cycles by
        # cause, rebuild latency, poisoned steps caught by the in-
        # program isfinite guard, re-admissions, drafter faults, and a
        # quarantine gauge readiness probes can alert on. Counters with
        # labels mint children only when the event happens (hygiene);
        # all are cold-path — a recovery is already an outage moment.
        self._c_recoveries = m.counter(
            "serve_engine_recoveries_total",
            "Engine quarantine -> rebuild -> re-admit cycles, by cause.",
            labelnames=("cause",))
        self._h_recovery = m.histogram(
            "serve_engine_recovery_seconds",
            "Quarantine -> device state rebuilt and victims requeued.",
            unit="seconds",
            buckets=(0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0))
        self._c_poisoned = m.counter(
            "serve_poisoned_steps_total",
            "Steps whose readback carried poisoned (non-finite-logit "
            "or out-of-vocab) tokens.")
        self._c_requeued = m.counter(
            "serve_requests_requeued_total",
            "In-flight requests re-admitted after an engine recovery.")
        self._c_drafter_faults = m.counter(
            "serve_spec_drafter_faults_total",
            "Drafter faults absorbed (the step degraded to plain "
            "decode).")
        self._g_quarantined = m.gauge(
            "serve_engine_quarantined",
            "1 while the engine is quarantined for recovery, else 0.")
        self.slo = SLOLedger(m)
        self.flight = flight if flight is not None else FlightRecorder()
        self.watchdog = WatchdogPanel(self, dump_dir=watchdog_dir,
                                      enabled=watchdogs)
        m.add_collector(self._collect_metrics)
        self._rate_ring: deque = deque(maxlen=256)   # (t, tokens read back)
        # On-demand jax.profiler window (POST /profile): requested from
        # an HTTP handler thread, opened/advanced/closed by the one
        # engine-stepping thread inside step().
        self._profile_lock = threading.Lock()
        self._profile: Optional[dict] = None
        self.last_profile: Optional[dict] = None
        # Retrace budgets (utils.tracecheck): jax calls each guarded
        # body once per TRACE, so a shape leak (e.g. a Python scalar
        # specializing a trace) raises CompileBudgetExceeded at the
        # retrace instead of becoming a silent 10x serving slowdown.
        # Per-engine registry — tests spin up many engines.
        self.tracecheck = TraceBudgetRegistry()

        # CPU jit ignores donation (and warns); only donate pool/state on
        # accelerators, where reusing the buffers in place matters.
        on_accel = jax.default_backend() != "cpu"

        # Speculative layer: built before max_programs() so the verify
        # (and any ModelDrafter draft/draft_prefill) budgets join the
        # published compile set the guards enforce.
        self._spec = None
        if spec is not None:
            from nanosandbox_tpu.serve.spec import SpecRunner

            if self.tp > 1 and getattr(spec, "kind", "host") == "device":
                # A device drafter owns its OWN model + KV pool; running
                # it under TP means sharding that second model too —
                # future work. Host drafters (NGram prompt lookup) ride
                # TP today: the verify program is the target model's and
                # shards like every other cached path.
                raise ValueError(
                    "tp > 1 supports host drafters only (e.g. "
                    "NGramDrafter); a tensor-parallel ModelDrafter "
                    "needs its own sharded pool")

            self._spec = SpecRunner(
                spec, model=model, num_slots=num_slots,
                max_len=self.max_len,
                n_prefill_programs=(len(self.sched.buckets)
                                    * len(self.admit_buckets)),
                registry=self.tracecheck, on_accel=on_accel,
                kv_dtype=kv_dtype, decode_impl=cfg.decode_impl,
                paged=self.paged, kv_page_size=kv_page_size,
                kv_pool_blocks=self.kv_pool_blocks)
        # Acceptance observability (windowed histograms, like the
        # latency signal): per-verify-row accepted lengths and
        # per-request accepted-token totals.
        self._spec_accept_len = m.histogram(
            "serve_spec_accept_len",
            "Accepted draft length per drafting verify row.",
            unit="tokens", buckets=(0, 1, 2, 3, 4, 6, 8, 12, 16),
            window=4096)
        self._spec_req_accepted = m.histogram(
            "serve_spec_req_accepted_tokens",
            "Draft tokens accepted over one request's lifetime.",
            unit="tokens", buckets=(0, 1, 2, 4, 8, 16, 32, 64, 128, 256))
        if self._spec is not None:
            self._spec.register_metrics(m)

        budget = self.max_programs()
        guard = self.tracecheck.guard
        # One prefill body per pool layout, published under ONE program
        # name and budget: the paged variant swaps the temp-cache
        # scatter for gather-prefix / suffix-forward / scatter-back, but
        # its shape key is the same (rung, bucket) grid — the bucketed
        # SUFFIX length, which without prefix hits IS the prompt bucket.
        prefill_body = (self._prefill_paged_fn if self.paged
                        else self._prefill_fn)
        self._prefill = jax.jit(
            guard("prefill", budget["prefill"])(prefill_body),
            donate_argnums=(1,) if on_accel else ())
        # The chunk length k is STATIC (the scan_rungs ladder): each
        # rung traces once under the one guarded name, so the decode
        # budget is exactly len(scan_rungs) and a rung outside the
        # ladder raises at the retrace, not as a silent program leak.
        self._decode = jax.jit(
            guard("decode", budget["decode"])(self._decode_fn),
            donate_argnums=(1, 2) if on_accel else (),
            static_argnums=(3,))
        self._admit = jax.jit(
            guard("admit", budget["admit"])(self._admit_fn),
            donate_argnums=(0,) if on_accel else ())
        self._release = jax.jit(
            guard("release", budget["release"])(self._release_fn),
            donate_argnums=(0,) if on_accel else ())

        # The brownout ladder (ISSUE 13): constructed last so the
        # controller sees the finished engine (slo ledger, metrics,
        # scan rungs all in place).
        self.brownout = BrownoutController(self) if brownout else None

    # ------------------------------------------------------------------
    # compiled step functions
    # ------------------------------------------------------------------
    # Wave-staging layout: the host packs a wave's per-row operands into
    # THREE uploads instead of nine — on the dispatch-bound CPU serving
    # floor each host->device staging array costs ~180us, so the packing
    # is a measurable slice of every admission wave (and it keeps the
    # paged and dense upload counts identical, which the paged-vs-dense
    # bench comparison relies on):
    #   prompts (k, L_bucket) int32 — the (suffix-)token block;
    #   meta    (k, meta_width) int32 — paged: [table row (slot_blocks)
    #           | slot | true_len | top_k | seed | hit_len]; dense:
    #           [slot | true_len | top_k | seed];
    #   fmeta   (k, 2) float32 — [temperature, top_p].
    # meta_width is a per-RUNG constant, so the admit program (which
    # consumes meta/fmeta plus the device-resident first tokens) keeps
    # its one-program-per-rung budget.
    @property
    def _meta_width(self) -> int:
        return (self.slot_blocks + 5) if self.paged else 4

    def _place_pool(self, pool: list) -> list:
        """Commit a freshly-built KV pool to its tensor-parallel
        placement — values AND scale planes row-sharded along the heads
        dim over the ``model`` axis (paged (N, H, page, D) and dense
        (S, H, L, D) both carry heads at dim 1). Identity at tp == 1.
        Construction and the recovery rebuild both come through here,
        so a recovered engine's placements match a fresh one's."""
        if self._mesh is None:
            return pool
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        val = NamedSharding(self._mesh, P(None, "model", None, None))
        sc = NamedSharding(self._mesh, P(None, "model", None))
        out = []
        for layer in pool:
            placed = (jax.device_put(layer[0], val),
                      jax.device_put(layer[1], val))
            if len(layer) == 4:
                placed += (jax.device_put(layer[2], sc),
                           jax.device_put(layer[3], sc))
            out.append(placed)
        return out

    def _stage(self, x):
        """Host->device staging for wave operands. Under TP the upload
        is an explicit replicated device_put (one copy per mesh device
        — these are O(wave) int32 rows, not pool bytes); tp == 1 keeps
        the plain single-device transfer."""
        import jax
        import jax.numpy as jnp

        if self._mesh is None:
            return jnp.asarray(x)
        return jax.device_put(x, self._rep)

    def _fresh_slot_state(self) -> dict:
        """A fully-parked device slot-state dict — construction AND the
        recovery rebuild use the same one, so a recovered engine starts
        from exactly the state a fresh one would. Under TP the struct
        is REPLICATED over the mesh (O(slots) ints — the sharded bytes
        are the pool's, and a replicated frontier is what lets every
        shard mask its local heads without an exchange)."""
        import jax.numpy as jnp

        state = {
            "pos": jnp.zeros(self.num_slots, jnp.int32),
            "tok": jnp.zeros(self.num_slots, jnp.int32),
            "temp": jnp.zeros(self.num_slots, jnp.float32),
            "topk": jnp.zeros(self.num_slots, jnp.int32),
            "topp": jnp.ones(self.num_slots, jnp.float32),
            "seed": jnp.zeros(self.num_slots, jnp.int32),
            "active": jnp.zeros(self.num_slots, jnp.bool_),
        }
        if self.paged:
            state["table"] = jnp.full(
                (self.num_slots, self.slot_blocks), self.kv_pool_blocks,
                jnp.int32)
        if self._mesh is not None:
            import jax

            state = jax.device_put(state, self._rep)
        return state

    def _split_meta(self, meta, fmeta):
        nb = self.slot_blocks if self.paged else 0
        tables = meta[:, :nb] if self.paged else None
        slots, true_lens, top_ks, seeds = (meta[:, nb], meta[:, nb + 1],
                                           meta[:, nb + 2], meta[:, nb + 3])
        hits = meta[:, nb + 4] if self.paged else None
        return tables, slots, hits, true_lens, top_ks, seeds, \
            fmeta[:, 0], fmeta[:, 1]

    def _prefill_fn(self, params, pool, prompts, meta, fmeta):
        """Admission wave (k, L_bucket) -> (new pool, first tokens (k,)).

        Runs the ordinary scalar-cache prefill on a batch-k temp cache of
        the bucket length, then scatters those rows into the wave's slot
        rows. Positions >= true_lens[i] hold garbage K/V — decode
        overwrites each position before attending to it and the per-row
        mask hides the rest, so padding never leaks into any output (the
        greedy parity test pins this). Ladder-padding rows carry slot id
        num_slots, which the scatter drops on the floor."""
        import jax.numpy as jnp

        from nanosandbox_tpu.models.gpt import init_cache, scatter_cache_rows
        from nanosandbox_tpu.sample import _sample_token, row_keys

        _, slots, _, true_lens, top_ks, seeds, temps, top_ps = \
            self._split_meta(meta, fmeta)
        k, L = prompts.shape
        cache = init_cache(self.cfg, k, L)
        logits, cache = self.model.apply({"params": params}, prompts,
                                         deterministic=True, cache=cache,
                                         cache_index=0)
        new_pool = scatter_cache_rows(pool, cache, slots)
        last = logits[jnp.arange(k), true_lens - 1, :]
        # Token destined for position true_len: fold_in(seed, true_len) —
        # the same stream the decode step continues at true_len + 1.
        keys = row_keys(seeds, true_lens)
        toks, _ = _sample_token(last, keys, temperature=temps,
                                top_k=top_ks, top_p=top_ps)
        return new_pool, self._poison_guard(toks, last)

    def _prefill_paged_fn(self, params, pool, suffix, meta, fmeta):
        """Paged admission wave: (k, L_suffix_bucket) SUFFIX tokens ->
        (new pool, first tokens (k,)).

        ONE model call straight against the pool — no temp cache, no
        scatter-back: the model's paged write path lands each row's
        suffix K/V at positions [hit, hit + Ls) through its block-table
        row (the same per-row vector-index scatter the spec verify
        uses), and its paged read path gathers the row's chain — the
        resident prefix INCLUDED — for the suffix's attention. The hit
        skips the prefix's forward FLOPs, which is where TTFT goes;
        shared hit blocks are never written (the write range starts at
        the block-aligned hit boundary, always a private block) and
        ladder-padding rows carry all-sentinel tables, so every one of
        their writes drops.

        The first token samples from position true_len - 1 with the
        SAME fold_in(seed, true_len) key a from-scratch prefill would
        use — prefix-hit outputs are token-identical to cold ones by
        construction (pinned by test)."""
        import jax.numpy as jnp

        from nanosandbox_tpu.sample import _sample_token, row_keys

        tables, _, hit_lens, true_lens, top_ks, seeds, temps, top_ps = \
            self._split_meta(meta, fmeta)
        k, _ = suffix.shape
        logits, pool = self.model.apply({"params": params}, suffix,
                                        deterministic=True, cache=pool,
                                        cache_index=hit_lens,
                                        block_table=tables)
        suf_lens = true_lens - hit_lens
        last = logits[jnp.arange(k), suf_lens - 1, :]
        keys = row_keys(seeds, true_lens)
        toks, _ = _sample_token(last, keys, temperature=temps,
                                top_k=top_ks, top_p=top_ps)
        return pool, self._poison_guard(toks, last)

    def _decode_step_fn(self, params, pool, state):
        """One batched token step over ALL slots at per-row frontiers —
        the scan body. pos advances and the sampled token becomes the
        next step's input ON DEVICE, so neither the host loop (scan_k
        == 1) nor the in-program scan (scan_k > 1) ever reads a token
        back before continuing. Inactive rows are parked by the mask —
        frozen pos, pinned token — so a released slot's garbage can't
        random-walk its own state. Paged pools ride the same program:
        the block table is one more state leaf, and the model's cached
        path pages reads/writes through it (with sentinel entries
        dropping any overrun row's writes)."""
        import jax.numpy as jnp

        from nanosandbox_tpu.sample import _sample_token, row_keys

        logits, pool = self.model.apply({"params": params},
                                        state["tok"][:, None],
                                        deterministic=True, cache=pool,
                                        cache_index=state["pos"],
                                        block_table=state.get("table"))
        keys = row_keys(state["seed"], state["pos"] + 1)
        nxt, _ = _sample_token(logits[:, 0, :], keys,
                               temperature=state["temp"],
                               top_k=state["topk"], top_p=state["topp"])
        nxt = self._poison_guard(nxt, logits[:, 0, :])
        active = state["active"]
        new_state = dict(state,
                         pos=state["pos"] + active.astype(jnp.int32),
                         tok=jnp.where(active, nxt, state["tok"]))
        return pool, new_state, nxt

    def _decode_fn(self, params, pool, state, k: int = 1):
        """The decode dispatch: one token step (k == 1, tokens (S,)) or
        the fused multi-step MEGAPROGRAM — a lax.scan of k token steps
        inside one compiled program, tokens (k, S). The scan carries
        (pool, state) through the same body the single-step path
        compiles, so the modes cannot drift: row r's token at position
        q is sampled from fold_in(key(seed_r), q) either way, and
        greedy outputs are token-identical across every k (pinned).
        ``k`` is a static jit arg drawn from the scan_rungs ladder —
        one compiled program per rung, the budget max_programs()
        publishes as {'decode': len(scan_rungs)}."""
        if k == 1:
            return self._decode_step_fn(params, pool, state)
        from jax import lax

        def body(carry, _):
            pool, state = carry
            pool, state, tok = self._decode_step_fn(params, pool, state)
            return (pool, state), tok

        (pool, state), toks = lax.scan(body, (pool, state), None,
                                       length=k)
        return pool, state, toks

    def _poison_guard(self, toks, logits):
        """In-program NaN/inf sentinel: a row whose logits went non-
        finite would otherwise sample an arbitrary-but-valid token
        (argmax over NaN is 0) and poison its KV history silently —
        instead the sampled token is replaced with the out-of-vocab
        sentinel, which the host retire loop detects for free from the
        readback it already performs (no extra sync, no extra program;
        the recovery supervisor turns the detection into a rebuild)."""
        import jax.numpy as jnp

        ok = jnp.isfinite(logits).all(axis=-1)
        return jnp.where(ok, toks, jnp.int32(self.cfg.vocab_size))

    def _admit_fn(self, state, toks, meta, fmeta):
        """Scatter an admission wave's operands into the slot-state rows.

        One per-rung program keyed by the packed (k, meta_width) staging
        shape; padding rows carry the out-of-range slot id num_slots,
        dropped by the scatter. Paged engines additionally scatter the
        wave's (k, max_blocks) block-table rows. ``toks`` is the prefill
        program's device-resident output — first tokens flow device-to-
        device into the slot state, never through the host."""
        tables, slots, _, pos0, top_ks, seeds, temps, top_ps = \
            self._split_meta(meta, fmeta)
        out = {
            "pos": state["pos"].at[slots].set(pos0, mode="drop"),
            "tok": state["tok"].at[slots].set(toks, mode="drop"),
            "temp": state["temp"].at[slots].set(temps, mode="drop"),
            "topk": state["topk"].at[slots].set(top_ks, mode="drop"),
            "topp": state["topp"].at[slots].set(top_ps, mode="drop"),
            "seed": state["seed"].at[slots].set(seeds, mode="drop"),
            "active": state["active"].at[slots].set(True, mode="drop"),
        }
        if tables is not None:
            out["table"] = state["table"].at[slots].set(tables, mode="drop")
        return out

    def _release_fn(self, state, slot):
        """Park one slot row back at the harmless idle values — for a
        paged engine that includes pointing the whole block-table row at
        the unallocated sentinel, so the parked row's garbage decode
        writes DROP instead of landing in a block the host may have
        already freed or donated to the prefix cache."""
        out = {
            "pos": state["pos"].at[slot].set(0),
            "tok": state["tok"].at[slot].set(0),
            "temp": state["temp"].at[slot].set(0.0),
            "topk": state["topk"].at[slot].set(0),
            "topp": state["topp"].at[slot].set(1.0),
            "seed": state["seed"].at[slot].set(0),
            "active": state["active"].at[slot].set(False),
        }
        if "table" in state:
            out["table"] = state["table"].at[slot].set(self.kv_pool_blocks)
        return out

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def _collect_metrics(self) -> None:
        """Collection-time mirror of the engine's plain-int state into
        the registry — runs per snapshot/scrape, NEVER in the decode
        loop, which is how telemetry stays off the hot path."""
        self._c_tokens._set_total(self.tokens_generated)
        self._c_steps._set_total(self.steps)
        for kind, n in list(self.host_dispatches.items()):
            if n:
                self._c_dispatches.labels(kind=kind)._set_total(n)
        dec = self.host_dispatches["decode"] + self.host_dispatches["verify"]
        self._g_toks_per_dispatch.set(
            self.tokens_generated / dec if dec else 0.0)
        self._c_admitted._set_total(self.admitted)
        self._c_shed._set_total(self.shed)
        self._c_preempted._set_total(self.preemptions)
        for reason, n in list(self.rejected.items()):
            self._c_rejected.labels(reason=reason)._set_total(n)
        self._c_poisoned._set_total(self.poisoned_steps)
        self._c_requeued._set_total(self.requeued)
        self._c_drafter_faults._set_total(self.drafter_faults)
        self._g_quarantined.set(1.0 if self.quarantined else 0.0)
        self._g_active.set(len(self._active))
        self._g_free.set(self.sched.free_slots)
        self._g_queued.set(self.sched.queued)
        rate = self._recent_rate()
        self._g_rate.set(0.0 if rate is None else rate)
        self._g_impl.labels(impl=self.decode_impl).set(1.0)
        self._g_kv.labels(kv_dtype=self.kv_dtype).set(1.0)
        self._g_tp.set(float(self.tp))
        self._g_role.labels(role=self.role).set(1.0)
        self._g_limbo.set(self.sched.limbo)
        self._c_migrated._set_total(self.migrated)
        self._c_adopted._set_total(self.adopted)
        if self.block_pool is not None:
            ps = self.block_pool.stats()
            for state in ("free", "live", "cached"):
                self._g_pool_blocks.labels(state=state).set(ps[state])
            self._c_prefix_hit._set_total(ps["prefix_hit_tokens"])
            self._c_prefix_miss._set_total(ps["prefix_miss_tokens"])
            self._c_block_stalls._set_total(ps["block_stall_steps"])
        for name, n in self.tracecheck.counts().items():
            self._c_traces.labels(program=name)._set_total(n)

    def _reject(self, reason: str, msg: str, **fields) -> None:
        """Reject a submission: count it, leave the terminal ``reject``
        event in the flight ledger (rid None — no id was ever assigned,
        matching the error the caller gets), raise the client error."""
        self.rejected[reason] = self.rejected.get(reason, 0) + 1
        self.flight.record("reject", step=self.steps, reason=reason,
                           **fields)
        raise ValueError(msg)

    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               seed: int = 0, eos_id: Optional[int] = None,
               deadline_s: Optional[float] = None,
               slo_class: str = "default",
               priority: Optional[int] = None,
               migrate: bool = False) -> int:
        """Queue one request; returns its id. Fixed-shape admission rules
        are enforced here so a bad request fails at submit, not as a
        mid-flight surprise — every reject leaves a terminal ``reject``
        event in the flight ledger. ``deadline_s`` (default: the
        engine's default_deadline_s) arms SLO accounting and queue-time
        shedding; ``slo_class`` labels it on /metrics; ``priority``
        (default: PRIORITY_BY_CLASS[slo_class]) orders the queue and
        the preemption policy. Under an active brownout shed floor a
        below-floor submission is accepted but immediately SHED (a
        terminal 'shed' Result — 429 + Retry-After upstream, never a
        silent queue-rot).

        ``migrate=True`` (ISSUE 16, paged engines only) marks the
        request for DISAGGREGATED handoff: this engine runs only its
        prefill (allocating the prompt's blocks, no generation
        budget), then parks the block chain + sampled first token in
        migration limbo for a decode tier to adopt — see pop_export()
        / Engine.begin_adopt(). The request's terminal Result comes
        from the ADOPTING engine (or from here, if the first token
        already finishes it or the export is shed/aborted)."""
        prompt = tuple(int(t) for t in prompt)
        plen = len(prompt)
        if self.failed:
            # Permanent failure drains, it does not crash-loop: refuse
            # loudly (503 upstream) instead of queueing into a void.
            self.rejected["engine_failed"] = \
                self.rejected.get("engine_failed", 0) + 1
            self.flight.record("reject", step=self.steps,
                               reason="engine_failed", prompt_len=plen)
            raise EngineFailedError(
                "engine permanently failed "
                f"({self.quarantine_cause or 'unknown cause'}); "
                "restart the process or route to another replica")
        if not prompt:
            self._reject("empty_prompt",
                         "empty prompt (encode at least one token)")
        bad = next((t for t in prompt
                    if not 0 <= t < self.cfg.vocab_size), None)
        if bad is not None:
            # An out-of-range id is not just garbage-in-garbage-out:
            # the embedding gather FILLS out-of-bounds rows (NaN under
            # jit), the poison sentinel fires on the non-finite logits,
            # and the recovery supervisor burns every attempt re-
            # admitting the same request until the engine PERMANENTLY
            # fails — one malformed request kills the replica (and a
            # failover-happy fleet would hand the same poison pill to
            # the next replica). Client errors reject at the boundary.
            self._reject(
                "token_out_of_range",
                f"prompt token {bad} outside [0, vocab_size="
                f"{self.cfg.vocab_size})", prompt_len=plen)
        if max_new_tokens < 0:
            self._reject(
                "bad_max_new",
                f"max_new_tokens must be >= 0, got {max_new_tokens}",
                prompt_len=plen)
        if deadline_s is None:
            deadline_s = self.default_deadline_s
        else:
            deadline_s = float(deadline_s)
            if deadline_s <= 0:
                self._reject("bad_deadline",
                             f"deadline_s must be > 0, got {deadline_s}",
                             prompt_len=plen)
        try:
            slo_class = validate_slo_class(str(slo_class))
        except ValueError as e:
            self._reject("bad_slo_class", str(e), prompt_len=plen)
        if plen > self.sched.buckets[-1]:
            self._reject(
                "prompt_exceeds_bucket",
                f"prompt length {plen} exceeds the largest prefill "
                f"bucket {self.sched.buckets[-1]}", prompt_len=plen)
        total = plen + max_new_tokens
        if total > self.max_len:
            self._reject(
                "exceeds_max_len",
                f"prompt ({plen}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds the per-slot KV "
                f"length {self.max_len}; long-context decode belongs to "
                "sample.py's windowed path", prompt_len=plen)
        if self.paged:
            # The no-deadlock split: a request the POOL could never hold
            # (even with every block free) is rejected HERE, loudly; one
            # that merely cannot fit RIGHT NOW queues and admission
            # defers it until running requests release blocks — full
            # reservation at admit means nothing mid-decode ever waits.
            need = self.block_pool.blocks_needed(plen, max_new_tokens)
            if need > self.kv_pool_blocks:
                self._reject(
                    "pool_too_small",
                    f"request needs {need} KV blocks but the pool holds "
                    f"{self.kv_pool_blocks}; raise kv_pool_blocks or "
                    "shorten the request", prompt_len=plen)
        if migrate and not self.paged:
            self._reject(
                "migrate_unpaged",
                "migrate=True needs a paged engine: the block chain IS "
                "the migration wire format (dense per-slot caches have "
                "nothing portable to hand off)", prompt_len=plen)
        if priority is None:
            priority = PRIORITY_BY_CLASS.get(slo_class, DEFAULT_PRIORITY)
        else:
            priority = int(priority)
        rid = next(self._rid)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), seed=int(seed), eos_id=eos_id,
                      deadline_s=deadline_s, slo_class=slo_class,
                      priority=priority)
        self._c_submitted.inc()
        sub_fields = {"prompt_len": plen, "max_new": max_new_tokens,
                      "slo_class": slo_class, "priority": priority}
        if deadline_s is not None:
            sub_fields["deadline_s"] = deadline_s
        self.flight.record("submit", rid=rid, step=self.steps,
                           **sub_fields)
        floor = self.brownout_min_priority
        if floor is not None and priority < floor:
            # Brownout shed-at-submit: the request is valid but the
            # engine is deliberately refusing its class right now —
            # terminal 'shed' (429 + Retry-After upstream), counted
            # against its class's attainment, zero resources spent.
            self.shed += 1
            self.flight.record("shed", rid=rid, step=self.steps,
                               reason="brownout", slo_class=slo_class,
                               priority=priority, floor=floor)
            if deadline_s is not None:
                self.slo.record_shed(slo_class)
                self.brownout_sheds += 1
            self._pending_results.append(
                Result(rid=rid, prompt=prompt, tokens=[],
                       finish_reason="shed"))
            return rid
        if max_new_tokens == 0:
            # Counts as completed too (never reaches _finish): the
            # natural submitted-minus-completed in-flight alert must
            # not drift on zero-token requests.
            self._c_completed.labels(reason="length").inc()
            self.flight.record("finish", rid=rid, step=self.steps,
                               reason="length", tokens=0, e2e_s=0.0)
            self.slo.record_finish(slo_class, tokens=0, elapsed_s=0.0,
                                   deadline_s=deadline_s)
            self._pending_results.append(
                Result(rid=rid, prompt=prompt, tokens=[],
                       finish_reason="length"))
            return rid
        if migrate and max_new_tokens > 1:
            # max_new <= 1 finishes at the prefill readback — nothing
            # left to migrate; those ride the colocated path untouched.
            self._migrate_rids.add(rid)
        sid = self.tracer.begin("queued", cat="request", rid=rid,
                                args={"prompt_len": plen,
                                      "max_new": max_new_tokens})
        self._submit_meta[rid] = (self.steps, time.monotonic(), sid)
        self.sched.enqueue(req)
        self.flight.record("queue", rid=rid, step=self.steps,
                           depth=self.sched.queued)
        return rid

    def has_work(self) -> bool:
        # Limbo counts: a parked export owes its client a terminal and
        # holds blocks — idle-with-limbo is not idle. Callers that
        # drain() a migrate-submitting engine must pump its exports
        # (DisaggPair.drain does) or carry deadlines that shed them.
        return bool(self._active or self.sched.queued or self._chunking
                    or self.sched.limbo
                    or self._pending_results or self._inflight is not None)

    def step(self) -> List[Result]:
        """Admit as many queued requests as slots allow (one batched
        prefill per wave), dispatch one batched decode step, then retire
        the PREVIOUS step's readback (pipelined; with pipeline=False the
        readback is the step just dispatched). Returns the requests that
        finished during this call."""
        if self.failed:
            # A permanently-failed engine only flushes already-terminal
            # results; abort_all() has drained everything else.
            finished, self._pending_results = self._pending_results, []
            return finished
        t0 = time.monotonic()
        self._prefill_spent = 0        # the per-step chunked-prefill budget
        traces0 = sum(self.tracecheck.counts().values())
        self._profile_window_start()
        finished = self._step_impl()
        self._profile_window_advance()
        # A single step stalling for tens of seconds is a wedged device,
        # not load — feed the stalled_step watchdog from the wall time
        # the step just took (one float compare when healthy). A step
        # that COMPILED something (--warmup=buckets lazy waves, tests)
        # is legitimately slow and must not read as a wedge: tearing
        # down a healthy replica for compiling would be recovery-
        # induced outage.
        if sum(self.tracecheck.counts().values()) == traces0:
            self.watchdog.on_step_time(time.monotonic() - t0)
        self.watchdog.check()
        if self.brownout is not None:
            self.brownout.on_step()
        return finished

    def _step_impl(self) -> List[Result]:
        # ``finished`` IS self._pending_results until the successful
        # detach at each return: an exception mid-step (device failure,
        # injected fault) must not strand already-terminal Results —
        # the supervisor's next step delivers them after recovery.
        finished = self._pending_results

        # Shed queued requests whose deadline already passed — BEFORE
        # admission, so an expired request never eats a slot, a prefill
        # program, or KV blocks on its way to a missed SLO.
        self._shed_expired(finished)
        # Preemption-by-eviction (ISSUE 13): if the highest-priority
        # queued deadline would expire waiting on slots/blocks, evict
        # the lowest-priority victim now so the admission below can
        # take its place. (Also hosts the preempt_storm fault site.)
        self._maybe_preempt()
        # Backfill free slots mid-flight; a wave finishing on its prefill
        # tokens immediately frees slots for the next wave in line.
        self._admit_waves(finished)

        if self._spec is not None and not self.spec_suspended:
            # Speculative step: draft -> one fixed-shape verify ->
            # retire, synchronously (any live row needs >= 1 more token
            # by construction — rows finish the moment they hit budget).
            if self._active:
                self._spec_step(finished)
                # Slots the retire just freed backfill NOW, same as the
                # pipelined loop's post-retire admission.
                self._admit_waves(finished)
            self._pending_results = []
            return finished

        retired = False
        chunk_len = self._next_chunk() if self._active else 0
        if chunk_len:
            if self.faults is not None:
                f = self.faults.fire("slow_step", self.steps)
                if f is not None:
                    self.flight.record("fault", step=self.steps,
                                       site="slow_step", stall_s=f.stall_s)
                    time.sleep(f.stall_s)
            self._pool, self._state, toks = self._decode(
                self.params, self._pool, self._state, chunk_len)
            self.steps += 1
            self.host_dispatches["decode"] += 1
            if (self.faults is not None
                    and self.faults.fire("nan_logits", self.steps)
                    is not None):
                # Injection happens at the host boundary: the readback
                # the retire will perform sees exactly what a real
                # non-finite step produces (the in-program sentinel),
                # so detection + recovery exercise the production path.
                # Under scan_k the whole chunk poisons — the worst
                # real case, a non-finite step mid-scan feeding every
                # later step garbage.
                self.flight.record("fault", step=self.steps,
                                   site="nan_logits")
                toks = np.full(np.shape(toks), self.cfg.vocab_size,
                               np.int32)
            snapshot = {slot: st.req.rid
                        for slot, st in self._active.items()}
            # decode_step span: opened at DISPATCH, closed at RETIRE —
            # under pipelining that close happens after the NEXT step's
            # open, so the exported timeline shows the true one-step
            # (one-CHUNK, under scan_k) overlap instead of a
            # synchronous fiction.
            sid = self.tracer.begin("decode_step", cat="decode",
                                    args={"step": self.steps,
                                          "rows": len(snapshot),
                                          "chunk_len": chunk_len})
            prev, self._inflight = self._inflight, (
                toks, snapshot, sid, self.steps, chunk_len)
            if not self.pipeline:
                inflight, self._inflight = self._inflight, None
                self._retire(inflight, finished)
                retired = True
            elif prev is not None:
                self._retire(prev, finished)
                retired = True
        elif self._inflight is not None:
            # Nothing left to dispatch (all rows' budgets covered by
            # computed tokens) — drain the lagging readback.
            inflight, self._inflight = self._inflight, None
            self._retire(inflight, finished)
            retired = True
        if retired:
            # Slots the retire just freed backfill NOW — their prefill
            # queues behind the in-flight step and the next dispatch
            # picks the new rows up, so eviction->readmission costs the
            # same one-step lag as the synchronous loop instead of two.
            self._admit_waves(finished)
        self._pending_results = []
        return finished

    def _shed_expired(self, finished: List[Result]) -> None:
        """Drop queued requests whose deadline expired while waiting —
        or whose priority sits below the active brownout shed floor:
        terminal ``shed`` Result (empty tokens), counted against SLO
        attainment. Requests without deadlines never deadline-shed
        (brownout can still shed them). Cheap when the queue carries no
        deadlines and no brownout is active — one attribute scan, no
        allocation (scheduler.drain_expired).

        The sweep also covers MIGRATION LIMBO (the ISSUE 16 fix):
        a request parked awaiting decode-tier adoption carries the same
        unserved deadline as a queued one — a stalled decode tier must
        shed it with a terminal ``shed``, its blocks released WITHOUT
        donation, not leak it forever. Limbo records shed on deadline
        only (never the brownout floor: their prefill is already paid —
        shedding it saves nothing)."""
        if not (self.sched.queued or self.sched.limbo):
            return
        now = time.monotonic()
        meta = self._submit_meta
        floor = self.brownout_min_priority

        def expired(item) -> bool:
            if isinstance(item, _Export):
                return (item.deadline_s is not None
                        and now - item.submit_t > item.deadline_s)
            return ((item.deadline_s is not None
                     and now - meta[item.rid][1] > item.deadline_s)
                    or (floor is not None and item.priority < floor))

        for item in self.sched.drain_expired(expired):
            if isinstance(item, _Export):
                self.shed += 1
                # Blocks freed, never donated (the ISSUE 16 contract):
                # a shed must not warm the cache on refused traffic.
                self.block_pool.release(item.alloc, donate=False)
                waited = now - item.submit_t
                self.flight.record(
                    "shed", rid=item.rid, step=self.steps,
                    reason="deadline", limbo=True,
                    waited_s=round(waited, 6),
                    deadline_s=item.deadline_s,
                    slo_class=item.req.slo_class)
                self.slo.record_shed(item.req.slo_class)
                finished.append(Result(rid=item.rid,
                                       prompt=item.req.prompt,
                                       tokens=[],
                                       finish_reason="shed"))
                continue
            req = item
            sub_step, sub_t, sid = meta.pop(req.rid)
            self.shed += 1
            self.tracer.end(sid, {"shed": True,
                                  "wait_steps": self.steps - sub_step})
            # A recovery-requeued victim can expire while waiting for
            # re-admission: unstitch it like every other terminal — the
            # Result carries the ORIGINAL prompt and the salvaged
            # pre-fault tokens, and the _Resume record must not leak.
            prompt_out, tokens_out, resumed = self._unstitch(
                req.rid, req, [])
            deadline_hit = (req.deadline_s is not None
                            and now - sub_t > req.deadline_s)
            shed_fields = {"waited_s": round(now - sub_t, 6),
                           "deadline_s": req.deadline_s,
                           "slo_class": req.slo_class,
                           "reason": ("deadline" if deadline_hit
                                      else "brownout")}
            if resumed:
                shed_fields["resumed"] = True
                shed_fields["tokens"] = len(tokens_out)
            self.flight.record("shed", rid=req.rid, step=self.steps,
                               **shed_fields)
            if req.deadline_s is not None:
                self.slo.record_shed(req.slo_class)
                if not deadline_hit:
                    self.brownout_sheds += 1
            finished.append(Result(rid=req.rid, prompt=prompt_out,
                                   tokens=tokens_out,
                                   finish_reason="shed"))

    def drain(self) -> List[Result]:
        """Run step() until queue, slots and pipeline are empty."""
        out: List[Result] = []
        while self.has_work():
            out.extend(self.step())
        return out

    # ------------------------------------------------------------------
    # on-demand profiling (POST /profile)
    # ------------------------------------------------------------------
    def request_profile(self, steps: int, out_dir: Optional[str] = None,
                        ) -> dict:
        """Arm a jax.profiler window over the next ``steps`` engine
        steps (train.py's --profile_steps machinery, serving-side).
        Thread-safe: HTTP handlers arm it, the one stepping thread
        opens/advances/closes it inside step(). Freeze-safe by
        construction — the window only wraps already-compiled programs,
        so a frozen tracecheck registry stays silent (pinned by test)."""
        steps = int(steps)
        if steps < 1:
            raise ValueError(f"profile steps must be >= 1, got {steps}")
        # Create/validate the dir BEFORE taking _profile_lock: the one
        # stepping thread takes this lock inside step() once a window is
        # armed, so filesystem I/O under it would let a slow /tmp stall
        # serving (lockcheck: blocking-under-lock). Validation stays on
        # the arming thread, where failure is a clean 400 — a bad path
        # surfacing later inside start_trace on the stepping thread
        # would kill the whole serving loop for one bad request.
        auto = out_dir is None
        d = out_dir or tempfile.mkdtemp(prefix="serve-profile-")
        try:
            os.makedirs(d, exist_ok=True)
        except OSError as e:
            raise ValueError(f"unusable profile dir {d!r}: {e}") from e
        with self._profile_lock:
            if self._profile is not None and self._profile["started"]:
                # Roll back the tempdir this losing arm just created.
                if auto:
                    try:
                        os.rmdir(d)
                    except OSError:
                        pass
                raise RuntimeError("a profile window is already in progress")
            # An armed-but-unstarted window (no traffic arrived yet) is
            # simply replaced — 409ing on it would wedge /profile
            # behind a window nothing is profiling, with no way out
            # until unrelated traffic drains it.
            self._reap_unstarted_dir()
            self._profile = {"dir": d, "auto_dir": auto, "steps": steps,
                             "remaining": steps, "started": False,
                             "span": 0, "sync_mark": None}
        return {"dir": d, "steps": steps}

    def _reap_unstarted_dir(self) -> None:
        """Remove the empty auto-created tempdir of a replaced/cancelled
        un-started window (call with _profile_lock held) — repeated arms
        from a flapping prober must not leak one /tmp dir per call.
        rmdir only: a dir a trace ever wrote into is never touched."""
        prof = self._profile
        if prof is not None and prof["auto_dir"] and not prof["started"]:
            try:
                os.rmdir(prof["dir"])
            except OSError:
                pass

    def cancel_profile(self) -> bool:
        """Disarm an armed-but-unstarted window (a started one belongs
        to the stepping thread and runs to its close). Returns whether
        anything was cancelled."""
        with self._profile_lock:
            if self._profile is not None and not self._profile["started"]:
                self._reap_unstarted_dir()
                self._profile = None
                return True
            return False

    def _profile_window_start(self) -> None:
        # Unlocked None fast path: this runs EVERY step, and the zero-
        # hot-loop-cost contract means no mutex traffic unless a window
        # is actually armed (arming publishes a non-None dict under the
        # lock; worst case the window starts one step late).
        if self._profile is None:
            return
        # The started flag flips under the lock so cancel/re-arm from
        # an HTTP thread can never swap the window out between this
        # check and the trace actually opening.
        with self._profile_lock:
            prof = self._profile
            if prof is None or prof["started"] or not self.has_work():
                return
            prof["started"] = True
        import jax

        try:
            jax.profiler.start_trace(prof["dir"])
        except Exception as e:  # dir went bad since arming, profiler busy
            # Fail the PROFILE, never the serving loop it rides in —
            # and reap the never-written auto dir, same as cancel.
            with self._profile_lock:
                if prof["auto_dir"]:
                    try:
                        os.rmdir(prof["dir"])
                    except OSError:
                        pass
                self._profile = None
            self.last_profile = {"dir": prof["dir"], "steps": prof["steps"],
                                 "error": f"{type(e).__name__}: {e}"}
            return
        prof["sync_mark"] = _tracecheck.sync_counts()
        prof["span"] = self.tracer.begin(
            "profile_window", cat="profile",
            args={"steps": prof["steps"], "dir": prof["dir"]})

    def _profile_window_advance(self) -> None:
        prof = self._profile
        if prof is None or not prof["started"]:
            return
        prof["remaining"] -= 1
        # Close early when the engine runs dry: the loop stops stepping
        # an idle engine, so an N-step window armed during a burst that
        # drains after k<N steps would otherwise stay open (trace
        # buffering, /profile 409ing) until traffic returns hours later.
        if prof["remaining"] > 0 and self.has_work():
            return
        import jax

        self.last_profile = {"dir": prof["dir"], "steps": prof["steps"],
                             "steps_profiled": prof["steps"]
                             - prof["remaining"]}
        try:
            jax.profiler.stop_trace()
        except Exception as e:  # trace dir reaped, disk full
            # Same contract as the start side: a stop failure loses the
            # PROFILE, never the serving loop — and must still clear
            # the window or /profile would 409 forever.
            self.last_profile["error"] = f"{type(e).__name__}: {e}"
            self.tracer.end(prof["span"], {"error": self.last_profile["error"]})
        else:
            by_kind = _tracecheck.sync_delta(prof["sync_mark"])
            self.tracer.end(prof["span"],
                            {"host_syncs": sum(by_kind.values())})
            self.last_profile["host_syncs_in_window"] = by_kind
        with self._profile_lock:
            self._profile = None

    def stats(self) -> dict:
        spec_stats = ({"enabled": False} if self._spec is None
                      else self._spec.stats())
        paged_stats: dict = {"enabled": self.paged}
        if self.block_pool is not None:
            paged_stats.update(self.block_pool.stats())
            # peek, never labels(): reading stats must not mint empty
            # {prefix=} series for the exposition to render (hygiene).
            hit = self._ttft_prefix.peek(prefix="hit")
            miss = self._ttft_prefix.peek(prefix="miss")
            paged_stats["ttft_hit_s"] = (
                hit.percentiles((50, 90, 99)) if hit is not None else None)
            paged_stats["ttft_miss_s"] = (
                miss.percentiles((50, 90, 99)) if miss is not None
                else None)
        return {
            "num_slots": self.num_slots,
            "max_len": self.max_len,
            "kv_dtype": self.kv_dtype,
            "tp": self.tp,
            "paged": self.paged,
            "kv_page_size": self.kv_page_size,
            "kv_pool_blocks": self.kv_pool_blocks,
            "kv_pool": paged_stats,
            "decode_attention_impl": self.decode_impl,
            "prefill_buckets": list(self.sched.buckets),
            "admit_buckets": list(self.admit_buckets),
            "pipeline": self.pipeline,
            "scan_k": self.scan_k,
            "host_dispatches": dict(self.host_dispatches),
            "tokens_per_dispatch": (
                self.tokens_generated
                / (self.host_dispatches["decode"]
                   + self.host_dispatches["verify"])
                if (self.host_dispatches["decode"]
                    + self.host_dispatches["verify"]) else None),
            "active": len(self._active),
            "queued": self.sched.queued,
            "free_slots": self.sched.free_slots,
            # The classless client-backoff estimate, scrapeable: the
            # fleet router's HTTP tier aggregates these across replicas
            # (min over ready) instead of forwarding whichever replica
            # happened to shed.
            "retry_after_s": self.retry_after_s(),
            "admitted": self.admitted,
            "completed": self.completed,
            "shed": self.shed,
            # Disaggregated posture (ISSUE 16): tier role plus both
            # sides of the migration flow this engine has seen.
            "role": self.role,
            "limbo": self.sched.limbo,
            "migrated": self.migrated,
            "adopted": self.adopted,
            "rejected": dict(self.rejected),
            "default_deadline_s": self.default_deadline_s,
            # Scheduling endgame (ISSUE 13): preemption/chunk/brownout
            # posture — what /debug/scheduler explains in detail.
            "preemptions": self.preemptions,
            "prefill_chunk": self.prefill_chunk,
            "chunking": len(self._chunking),
            "spec_suspended": self.spec_suspended,
            "brownout": (None if self.brownout is None
                         else self.brownout.stats()),
            # Fault/recovery posture (ISSUE 11): what readiness probes
            # and the /debug views key off, plus the armed fault plan
            # when chaos testing.
            "recovery": {
                "quarantined": self.quarantined,
                "failed": self.failed,
                "cause": self.quarantine_cause,
                "recoveries": self.recoveries,
                "recovery_s": self._h_recovery.percentiles((50, 90, 99)),
                "poisoned_steps": self.poisoned_steps,
                "requeued": self.requeued,
                "resumed_in_flight": len(self._resumed),
                "drafter_faults": self.drafter_faults,
                "spec_disabled": self.spec_disabled_reason,
            },
            "faults": (None if self.faults is None
                       else self.faults.stats()),
            "slo": self.slo.stats(),
            "flight": self.flight.stats(),
            "watchdog": self.watchdog.stats(),
            "decode_steps": self.steps,
            "tokens_generated": self.tokens_generated,
            "decode_tokens_per_sec": self._recent_rate(),
            "queue_wait_steps_mean": self._queue_wait.mean(),
            "ttft_s": self._ttft.percentiles((50, 90, 99)),
            "tpot_s": self._tpot.percentiles((50, 90, 99)),
            "trace_counts": dict(self.trace_counts),
            # Speculative signal: token-level acceptance rate, the mean
            # accepted draft length per verify row (ring window), and
            # per-request accepted-token totals (recorded at finish).
            "spec": spec_stats,
            "spec_acceptance_rate": spec_stats.get("acceptance_rate"),
            "spec_accepted_len_mean": self._spec_accept_len.mean(),
            "spec_req_accepted_tokens": self._spec_req_accepted.percentiles(
                (50, 90, 99)),
            "profile": {"active": self._profile is not None,
                        "last": self.last_profile},
        }

    def max_programs(self) -> dict:
        """The closed compile set by program kind — the budgets the
        tracecheck guards enforce at runtime (a retrace past these
        raises CompileBudgetExceeded) and tests/CI assert against.
        scan_k widens ONLY the decode entry, and exactly by its rung
        ladder: one megaprogram per scan_rungs chunk length (scan_k=1
        keeps the classic single program), pinned by test."""
        progs = {
            "prefill": len(self.sched.buckets) * len(self.admit_buckets),
            "decode": len(self.scan_rungs),
            "admit": len(self.admit_buckets),
            "release": 1,
        }
        if self._spec is not None:
            # ONE verify shape (fixed num_slots x (k+1); per-row draft
            # lengths are a mask, not a shape) — plus, for a
            # ModelDrafter, one draft scan and the drafter's own
            # (ladder x buckets) prefill grid.
            progs.update(self._spec.programs)
        return progs

    @property
    def mesh(self):
        """The tensor-parallel mesh this engine shards over (None at
        tp == 1 — the single-chip engine owns no mesh)."""
        return self._mesh

    def shardcheck_programs(self, mesh) -> list:
        """ProgramSpecs for the comms analyzer (analysis/shardcheck):
        the engine's full compiled set — decode, the prefill
        ladder x bucket grid, and (with spec=...) the verify/drafter
        programs — AOT-lowered under ``mesh``.

        tp == 1 lowers with every operand REPLICATED: the single-chip
        contract stated on the mesh, so the committed serve budget pins
        ZERO collectives. tp > 1 lowers under the engine's OWN mesh
        with the LIVE placements (Megatron weights, heads-sharded pool,
        replicated slot state): the partitioner runs for real and the
        committed TP budget (budgets/serve_tp_cpu8.json) pins the
        bounded model-axis collectives — while the accidental-all-gather
        rule stays armed (gather_ok_axes empty), so a dropped
        with_sharding_constraint that rebuilds the full pool on every
        chip is a CI finding with exact bytes, not a silent 2x HBM
        regression. Fresh jits: an analysis lower must not consume the
        live tracecheck budgets."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec

        from nanosandbox_tpu.analysis.shardcheck import (Expectations,
                                                         ProgramSpec)
        from nanosandbox_tpu.parallel.mesh import replicated_abstract

        rep = NamedSharding(mesh, PartitionSpec())
        if self.tp > 1:
            if mesh is not self._mesh:
                raise ValueError(
                    "a tensor-parallel engine lowers under its own mesh "
                    "— pass engine.mesh (or build the engine with "
                    "tp_mesh=<the fleet mesh>)")

            def live_abstract(tree):
                return jax.tree.map(
                    lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype,
                                                   sharding=x.sharding),
                    tree)

            aparams = live_abstract(self.params)
            apool = live_abstract(self._pool)
            astate = live_abstract(self._state)
            # Comms expected — the budget pins how much and where; the
            # empty gather_ok_axes keeps accidental-all-gather armed
            # against any full materialization of the sharded pool.
            expect = Expectations(comms_free=False)
            jit_kwargs = {}
        else:
            aparams = replicated_abstract(mesh, self.params)
            apool = replicated_abstract(mesh, self._pool)
            astate = replicated_abstract(mesh, self._state)
            expect = Expectations(comms_free=True)
            jit_kwargs = {"in_shardings": rep, "out_shardings": rep}

        def jit_fleet(fn):
            return jax.jit(fn, **jit_kwargs)

        def sds(shape, dtype):
            return jax.ShapeDtypeStruct(shape, dtype, sharding=rep)

        # Quantized-KV engines publish under distinct names so one
        # budget file can pin every pool mode's comms (the fleet
        # commits int8 and int4 twins); likewise the dense (pre-paged)
        # layout keeps a _dense suffix — the unsuffixed names ARE the
        # paged programs, the default engine contract the budgets pin.
        # A scan_k > 1 engine's decode is the fused megaprogram LADDER,
        # a materially different compile surface per rung, so each rung
        # above 1 owns a decode_scan<r> name the budget must list
        # explicitly (rung 1 is the classic single-step program).
        # Tensor-parallel engines append _tp<N>: a different comms
        # contract is a different program identity.
        sfx = {"int8": "_kv8", "int4": "_kv4"}.get(self.kv_dtype, "")
        if not self.paged:
            sfx += "_dense"
        if self.tp > 1:
            sfx += f"_tp{self.tp}"

        def decode_spec(r):
            name = f"decode_scan{r}{sfx}" if r > 1 else f"decode{sfx}"

            def lower(r=r):
                return jax.jit(self._decode_fn, static_argnums=(3,),
                               **jit_kwargs).lower(
                                   aparams, apool, astate, r)

            return ProgramSpec(name=name, lower=lower,
                               abstract_args=(aparams, apool, astate),
                               expect=expect, tags=("serve",))

        specs = [decode_spec(r) for r in self.scan_rungs]
        prefill_body = (self._prefill_paged_fn if self.paged
                        else self._prefill_fn)
        for bucket in self.sched.buckets:
            for k in self.admit_buckets:
                args = (aparams, apool, sds((k, bucket), jnp.int32),
                        sds((k, self._meta_width), jnp.int32),
                        sds((k, 2), jnp.float32))
                specs.append(ProgramSpec(
                    name=f"prefill{sfx}_k{k}_L{bucket}",
                    lower=(lambda args=args:
                           jit_fleet(prefill_body).lower(*args)),
                    abstract_args=args, expect=expect, tags=("serve",)))
        if self._spec is not None:
            specs.extend(self._spec.shardcheck_programs(
                mesh, aparams=aparams, apool=apool, astate=astate,
                buckets=self.sched.buckets, rungs=self.admit_buckets,
                suffix=sfx, expect=expect,
                replicated_io=self.tp == 1))
        return specs

    @property
    def trace_counts(self) -> Dict[str, int]:
        """Observed traces per program kind, read from the tracecheck
        registry (the engine no longer hand-counts; /stats, warmup
        logging and the bench report all read this view)."""
        return self.tracecheck.counts()

    # ------------------------------------------------------------------
    # live introspection (GET /debug/slots | /debug/kvpool |
    # /debug/scheduler). Best-effort reads from an HTTP handler thread
    # while the loop thread mutates — same discipline as /stats: every
    # shared structure is snapshotted (list()/get()) before iteration,
    # and a torn read across two fields yields a stale view, never a
    # crash. No device state is touched (host dicts and plain ints).
    # ------------------------------------------------------------------
    def debug_slots(self) -> dict:
        """Per-slot occupancy: who owns each row, how far along it is,
        and how stale its last token is (the stuck-slot watchdog's view,
        on demand)."""
        now = time.monotonic()
        inflight = dict(self._inflight[1]) if self._inflight is not None \
            else {}
        active = dict(self._active)
        chunking = {e.slot: e for e in list(self._chunking)}
        slots = []
        for slot in range(self.num_slots):
            st = active.get(slot)
            if st is None:
                e = chunking.get(slot)
                if e is not None:
                    slots.append({"slot": slot, "state": "prefilling",
                                  "rid": e.req.rid,
                                  "prompt_len": len(e.req.prompt),
                                  "prefilled": e.hit + e.done})
                else:
                    slots.append({"slot": slot, "state": "free"})
                continue
            req = st.req
            slots.append({
                "slot": slot, "state": "active", "rid": req.rid,
                "slo_class": req.slo_class, "deadline_s": req.deadline_s,
                "prompt_len": len(req.prompt),
                "max_new": req.max_new_tokens,
                "tokens": len(st.tokens),
                "age_s": round(now - st.submit_t, 6),
                "since_last_token_s": round(now - st.last_t, 6),
                "prefix_hit": bool(st.alloc.n_hit)
                if st.alloc is not None else False,
                "in_flight_step": inflight.get(slot) == req.rid,
                "spec_accepted": st.spec_accepted,
            })
        return {"num_slots": self.num_slots, "active": len(active),
                "free_slots": self.sched.free_slots, "slots": slots}

    def debug_kvpool(self) -> dict:
        """Paged-pool block states, fragmentation and radix-trie
        occupancy (serve/paged.py debug view); {"paged": False} on a
        dense engine."""
        if self.block_pool is None:
            return {"paged": False}
        live = [(st.req.rid, len(st.req.prompt) + len(st.tokens), st.alloc)
                for st in list(self._active.values())
                if st.alloc is not None]
        return {"paged": True, "kv_page_size": self.kv_page_size,
                **self.block_pool.debug(live)}

    def prefix_summary(self) -> dict:
        """The authoritative radix-cache residency summary a fleet
        router refreshes its approximate per-replica index from
        (GET /debug/prefix_summary): one chained fingerprint per
        resident trie node (paged.prefix_digests' chain, so membership
        answers "would block i of this prompt hit here"). Pure host
        bookkeeping over block ids — no device read, no sync. Routers
        should treat the digest SET as a full replacement: anything
        absent was LRU-evicted since the last refresh."""
        if self.block_pool is None or self.block_pool.cache is None:
            return {"enabled": False, "page": 0, "blocks": 0,
                    "digests": []}
        digests = self.block_pool.cache.digests()
        return {"enabled": True, "page": self.kv_page_size,
                "blocks": len(digests), "digests": digests}

    def debug_scheduler(self) -> dict:
        """Queue composition head-first — per-request wait, deadline
        state (the shed forecast), bucket, priority — plus per-class
        queue depths, the brownout posture, the chunked-prefill lane,
        the admission ladders and, under spec, the drafter's live
        acceptance."""
        now = time.monotonic()
        queued = []
        by_class: Dict[str, dict] = {}
        for item in self.sched.queued_items():
            meta = self._submit_meta.get(item.rid)
            waited = None if meta is None else round(now - meta[1], 6)
            queued.append({
                "rid": item.rid, "prompt_len": len(item.prompt),
                "max_new": item.max_new_tokens,
                # The no-hit bucket (bucket_for, not _suffix_bucket): a
                # debug read must not walk the radix trie the loop
                # thread owns, nor touch its LRU clocks.
                "bucket": self.sched.bucket_for(len(item.prompt)),
                "slo_class": item.slo_class,
                "priority": item.priority,
                "deadline_s": item.deadline_s,
                "waited_s": waited,
                "expired": bool(item.deadline_s is not None
                                and waited is not None
                                and waited > item.deadline_s),
            })
            # Per-priority counts, not one representative priority: a
            # class can mix explicit overrides with its default, and an
            # operator judging a brownout floor needs to see how much
            # of the class sits on each side of it.
            cls = by_class.setdefault(
                item.slo_class, {"queued": 0, "priorities": {}})
            cls["queued"] += 1
            pr = cls["priorities"]
            pr[item.priority] = pr.get(item.priority, 0) + 1
        # The migration limbo queue (ISSUE 16): exports prefilled here,
        # awaiting adoption by the decode tier. Same deadline fields as
        # the admission queue — limbo is swept by the same shed pass.
        limbo = []
        for exp in self.sched.limbo_items():
            waited = round(now - exp.submit_t, 6)
            limbo.append({
                "rid": exp.rid, "prompt_len": len(exp.req.prompt),
                "chain_blocks": len(exp.alloc.table),
                "hit_blocks": exp.alloc.n_hit,
                "slo_class": exp.req.slo_class,
                "priority": exp.priority,
                "deadline_s": exp.deadline_s,
                "waited_s": waited,
                "limbo_s": round(now - exp.export_t, 6),
                "expired": bool(exp.deadline_s is not None
                                and waited > exp.deadline_s),
            })
        out = {"queued": len(queued), "queue": queued,
               "queue_by_class": by_class,
               "role": self.role,
               "limbo": len(limbo), "limbo_queue": limbo,
               "migrated": self.migrated, "adopted": self.adopted,
               "free_slots": self.sched.free_slots,
               "active": len(self._active),
               "prefill_buckets": list(self.sched.buckets),
               "admit_buckets": list(self.admit_buckets),
               "pipeline": self.pipeline,
               "inflight_step": self._inflight is not None,
               "steps": self.steps, "shed": self.shed,
               "default_deadline_s": self.default_deadline_s,
               "preemptions": self.preemptions,
               "prefill_chunk": self.prefill_chunk,
               "chunking": [{"rid": e.req.rid, "slot": e.slot,
                             "prefilled": e.hit + e.done,
                             "prompt_len": len(e.req.prompt)}
                            for e in list(self._chunking)],
               "brownout": (None if self.brownout is None
                            else self.brownout.stats())}
        if self._spec is not None:
            out["spec"] = self._spec.debug()
        return out

    # ------------------------------------------------------------------
    # internals
    # ------------------------------------------------------------------
    def _suffix_bucket(self, req) -> int:
        """The paged wave key: the prefill bucket of the prompt MINUS
        its resident prefix (a pure probe — blocks commit in the admit
        callback). Requests sharing a hot system prompt therefore land
        together in small-suffix waves; with a cold cache this is
        exactly bucket_for(len(prompt))."""
        hit = self.block_pool.match_len(req.prompt)
        return self.sched.bucket_for(len(req.prompt) - hit)

    def _try_alloc(self, req):
        """Reserve one request's KV blocks (paged engines): the
        alloc_fail fault hook, the block_stall/block_reserve flight
        events and the no-deadlock backpressure in one place — shared
        by wave admission and the chunked-prefill lane. Returns the
        Allocation, or None (request stays queued)."""
        if (self.faults is not None
                and self.faults.fire("alloc_fail", self.steps)
                is not None):
            # Forced exhaustion: the request stays queued (the normal
            # no-deadlock backpressure), the stall is counted so the
            # admission_stall watchdog sees the same signal a real one
            # produces.
            self.block_pool.stall_steps += 1
            self.flight.record("fault", rid=req.rid, step=self.steps,
                               site="alloc_fail")
            return None
        # A migrate-flagged request reserves its PROMPT chain only: the
        # generation budget belongs to the adopting decode tier, and
        # double-reserving it here is exactly the pool pressure
        # disaggregation exists to remove from the prefill tier.
        max_new = (0 if req.rid in self._migrate_rids
                   else req.max_new_tokens)
        a = self.block_pool.admit(req.prompt, max_new)
        if a is None:
            self.flight.record(
                "block_stall", rid=req.rid, step=self.steps,
                need=self.block_pool.blocks_needed(
                    len(req.prompt), max_new),
                free=self.block_pool.free_blocks)
            return None
        self.flight.record("block_reserve", rid=req.rid,
                           step=self.steps, blocks=len(a.table),
                           hit_blocks=a.n_hit)
        return a

    def _admit_waves(self, finished: List[Result]) -> None:
        import jax.numpy as jnp

        budget = self.prefill_chunk
        # Chunked-prefill lane first (ISSUE 13): requests mid-chunking
        # are AHEAD of the queue in admission order — they were popped
        # from its head — so they get first claim on this step's budget.
        if budget is not None and self._chunking:
            self._advance_chunked(finished)
        while True:
            max_items: Optional[int] = None
            if budget is not None:
                if self._prefill_spent >= budget:
                    break
                head = self.sched.peek_head()
                if head is None:
                    break
                if self.sched.free_slots:
                    hb = (self._suffix_bucket(head) if self.paged
                          else self.sched.bucket_for(len(head.prompt)))
                    if self.paged and hb > budget:
                        # Too long for one budgeted wave: route into the
                        # chunked lane (paged prefill writes at
                        # cache_index offsets, so the split costs no new
                        # program). A block-starved head fences — FIFO.
                        if not self._start_chunked(head):
                            break
                        self._advance_chunked(finished)
                        continue
                    # Cap the wave so rung * bucket fits the remaining
                    # budget. A dense over-budget bucket (no way to
                    # split) admits alone on a fresh step.
                    remaining = budget - self._prefill_spent
                    max_items = 0
                    for r in self.admit_buckets:
                        if r * hb <= remaining:
                            max_items = r
                    if max_items == 0:
                        if self._prefill_spent:
                            break       # resume on the next step
                        max_items = 1
            allocs: List = []
            if self.paged:

                def try_alloc(req):
                    a = self._try_alloc(req)
                    if a is None:
                        return False
                    allocs.append(a)
                    return True

                wave = self.sched.next_admission_wave(
                    max_items=max_items,
                    bucket_of=self._suffix_bucket, admit=try_alloc)
            else:
                wave = self.sched.next_admission_wave(
                    max_items=max_items)
            if wave is None:
                break
            reqs, slots, bucket = wave
            if budget is not None:
                self._prefill_spent += (
                    self.sched.rung_for(len(reqs)) * bucket)
            # From here until the admission commits, the wave is in
            # limbo: popped from the queue, blocks reserved, slots
            # claimed, but not yet active. Track it so a prefill crash
            # leaves recover() enough to unwind and requeue.
            self._admitting = [
                (req, slot, allocs[i] if self.paged else None)
                for i, (req, slot) in enumerate(zip(reqs, slots))]
            k = self.sched.rung_for(len(reqs))
            self._c_waves.inc()
            wave_sid = self.tracer.begin(
                "prefill_wave", cat="prefill",
                args={"bucket": bucket, "rung": k, "wave": len(reqs),
                      "rids": [r.rid for r in reqs]})
            self._admitting_span = wave_sid
            # Host staging for the wave — the ONLY host->device uploads
            # the engine performs (three arrays, the packed layout above
            # _meta_width); the per-token loop stages nothing.
            nb = self.slot_blocks if self.paged else 0
            prompts = np.zeros((k, bucket), np.int32)
            meta = np.zeros((k, self._meta_width), np.int32)
            # Padding rows point at slot id num_slots (and, paged, an
            # all-sentinel table row): out of range, so the pool writes
            # and the state scatter all drop them.
            meta[:, nb] = self.num_slots
            meta[:, nb + 1] = 1                     # true_len floor
            if self.paged:
                meta[:, :nb] = self.kv_pool_blocks
            fmeta = np.zeros((k, 2), np.float32)
            fmeta[:, 1] = 1.0                       # top_p
            for i, (req, slot) in enumerate(zip(reqs, slots)):
                meta[i, nb] = slot
                meta[i, nb + 1] = len(req.prompt)
                meta[i, nb + 2] = req.top_k
                meta[i, nb + 3] = req.seed
                fmeta[i] = (req.temperature, req.top_p)
                if self.paged:
                    a = allocs[i]
                    hit = a.n_hit * self.kv_page_size
                    sfx = req.prompt[hit:]
                    prompts[i, :len(sfx)] = sfx
                    meta[i, :len(a.table)] = a.table
                    meta[i, nb + 4] = hit
                else:
                    prompts[i, :len(req.prompt)] = req.prompt
            prompts_dev = self._stage(prompts)
            meta_dev = self._stage(meta)
            fmeta_dev = self._stage(fmeta)
            if (self.faults is not None
                    and self.faults.fire("prefill_exc", self.steps)
                    is not None):
                self.flight.record("fault", step=self.steps,
                                   site="prefill_exc",
                                   rids=[r.rid for r in reqs])
                raise FaultInjected("prefill_exc", self.steps)
            self._pool, toks = self._prefill(self.params, self._pool,
                                             prompts_dev, meta_dev,
                                             fmeta_dev)
            self.host_dispatches["prefill"] += 1
            # First tokens flow device-to-device into the slot state;
            # the host copy below is for result lists and finish checks
            # only.
            self._state = self._admit(self._state, toks, meta_dev,
                                      fmeta_dev)
            self.host_dispatches["admit"] += 1
            if self._spec is not None and self._spec.drafter.kind == "device":
                # The drafter ingests the SAME staged wave into its own
                # pool (its frontier state is the engine's pos/tok, so
                # prompt K/V is all it needs). Paged drafters share the
                # engine's block ids: one table, two parallel pools.
                self._spec.drafter.prefill_wave(prompts_dev, meta_dev)
            # jaxlint: disable=host-sync -- first-token readback feeds results/eos checks
            toks_host = np.asarray(toks)
            if (self.faults is not None
                    and self.faults.fire("scatter_corrupt", self.steps)
                    is not None):
                # A corrupted slot scatter surfaces as garbage first
                # tokens at the wave readback — same detection boundary
                # as a poisoned decode step.
                self.flight.record("fault", step=self.steps,
                                   site="scatter_corrupt")
                toks_host = np.full(k, self.cfg.vocab_size, np.int32)
            now = time.monotonic()
            self._rate_ring.append((now, len(reqs)))
            poisoned_wave = False
            for i, (req, slot) in enumerate(zip(reqs, slots)):
                poisoned_wave |= self._commit_admission(
                    req, slot, allocs[i] if self.paged else None,
                    int(toks_host[i]), bucket=bucket, rung=k, now=now,
                    finished=finished)
            # Wave committed: nothing is in limbo anymore.
            self._admitting = []
            self._admitting_span = None
            if poisoned_wave:
                self._mark_poison("poisoned_prefill",
                                  rids=[r.rid for r in reqs])
            self.tracer.end(wave_sid)

    def _commit_admission(self, req: Request, slot: int, alloc,
                          first_tok: int, *, bucket: int, rung: int,
                          now: float, finished: List[Result]) -> bool:
        """Per-request admission commit — the bookkeeping between the
        first-token readback and the row going active, shared by wave
        admission and the chunked-prefill lane's final chunk. Returns
        whether the first token was poisoned (the caller aggregates
        into one _mark_poison)."""
        self.admitted += 1
        poisoned = not 0 <= first_tok < self.cfg.vocab_size
        resumed = req.rid in self._resumed
        if not poisoned:
            self.tokens_generated += 1
        sub_step, sub_t, queued_sid = self._submit_meta.pop(req.rid)
        self._queue_wait.observe(self.steps - sub_step)
        if not resumed and not poisoned:
            # A resumed request's first token predates the
            # recovery/preemption — re-observing submit->now as "TTFT"
            # would poison the spike watchdog's baseline; the recovery
            # histograms carry that latency instead. A POISONED first
            # token was discarded: its latency describes nothing the
            # client ever received.
            self._ttft.observe(now - sub_t)
            self.watchdog.on_ttft(now - sub_t)
        hit_toks = (alloc.n_hit * self.kv_page_size
                    if alloc is not None else 0)
        if (self.paged and self.block_pool.cache is not None
                and not resumed and not poisoned):
            # The by-prefix-outcome TTFT split exists only when the
            # prefix cache does — a cache-less engine must not mint
            # placeholder {prefix=} series (the /metrics label-hygiene
            # rule).
            self._ttft_prefix.labels(
                prefix="hit" if hit_toks else "miss").observe(
                    now - sub_t)
        self.tracer.end(queued_sid,
                        {"wait_steps": self.steps - sub_step})
        self.flight.record("admit", rid=req.rid, step=self.steps,
                           slot=slot, bucket=bucket, rung=rung,
                           wait_steps=self.steps - sub_step)
        self.flight.record(
            "prefill", rid=req.rid, step=self.steps,
            prefix="hit" if hit_toks else "miss",
            hit_tokens=hit_toks,
            suffix_tokens=len(req.prompt) - hit_toks)
        if req.rid in self._migrate_rids:
            self._migrate_rids.discard(req.rid)
            finishes_here = (
                poisoned
                or (req.eos_id is not None and first_tok == req.eos_id)
                or req.max_new_tokens <= 1)
            if not finishes_here and alloc is not None:
                import jax.numpy as jnp

                # EXPORT (ISSUE 16): the prompt's K/V is fully written —
                # including the partial tail block — and the first token
                # is in hand; everything a decode tier needs. Release
                # the slot NOW, host and device (commit runs before this
                # step's decode dispatch, so the row never decodes here
                # and the chain is never written again — the bit-
                # identity the adoption copy depends on), and park the
                # chain + token in migration limbo. The request's
                # terminal belongs to whoever adopts (or to the deadline
                # sweep / abort path if nobody does). A poisoned,
                # instantly-finished, or alloc-less first token falls
                # through to the colocated path below instead: migration
                # is an optimization, never a correctness fork.
                self.sched.release(slot)
                self._state = self._release(self._state,
                                            jnp.asarray(slot, jnp.int32))
                self.host_dispatches["release"] += 1
                exp = _Export(req=req, alloc=alloc, first_tok=first_tok,
                              export_t=now, submit_t=sub_t,
                              submit_step=sub_step)
                self.sched.park_limbo(exp)
                self.flight.record(
                    "export", rid=req.rid, step=self.steps,
                    chain_blocks=len(alloc.table),
                    hit_blocks=alloc.n_hit,
                    prompt_len=len(req.prompt))
                return False
        gen_sid = self.tracer.begin(
            "generate", cat="request", rid=req.rid,
            args={"slot": slot, "bucket": bucket})
        st = _Active(req=req, slot=slot,
                     tokens=[] if poisoned else [first_tok],
                     first_token_t=now,
                     submit_t=sub_t, last_t=now,
                     span=gen_sid, alloc=alloc)
        self._active[slot] = st
        if not poisoned:
            done = self._maybe_finish(st)
            if done is not None:
                finished.append(done)
        return poisoned

    # ------------------------------------------------------------------
    # chunked prefill (ISSUE 13): a long (suffix) prompt lands in the
    # pool across several bucket-shaped dispatches, interleaved with
    # decode steps — the admission pacing that keeps a prefill storm
    # from stalling every active row's TPOT.
    # ------------------------------------------------------------------
    def _start_chunked(self, head: Request) -> bool:
        """Claim the queue head into the chunked-prefill lane: blocks
        reserved (full reservation — the no-deadlock contract), a slot
        claimed (so traffic behind it cannot starve it out of one), no
        device work yet. False when block-starved: the head stays
        queued and fences admission, exactly like a starved wave."""
        alloc = self._try_alloc(head)
        if alloc is None:
            return False
        popped = self.sched.pop_head()
        assert popped is head
        slot = self.sched.take_slot()
        self._chunking.append(_Chunking(
            req=head, slot=slot, alloc=alloc,
            hit=alloc.n_hit * self.kv_page_size))
        return True

    def _advance_chunked(self, finished: List[Result]) -> None:
        """Dispatch prefill chunks for the in-progress chunked
        admissions, oldest first, until this step's budget is spent.
        Every chunk is an ordinary (1, bucket) prefill program — the
        suffix-bucket grid already compiled — writing at cache_index =
        hit + done (the prefix-hit machinery with a moving hit), so
        intermediate chunks need no admit scatter and no readback: the
        sampled token of an incomplete prompt is discarded unread. The
        FINAL chunk passes the real slot and true_len, samples the
        first token from fold_in(seed, true_len) — token-identical to a
        monolithic prefill (pinned) — and commits the admission."""
        import jax.numpy as jnp

        budget = self.prefill_chunk
        for entry in list(self._chunking):
            while self._prefill_spent < budget:
                req = entry.req
                remaining = len(req.prompt) - entry.hit - entry.done
                # Size the chunk to the REMAINING step budget, not the
                # full one — waves admitted earlier this step already
                # spent part of it, and a full-size chunk on top would
                # run the step up to ~2x the TPOT-protection budget.
                c = min(remaining, budget - self._prefill_spent)
                bucket = self.sched.bucket_for(c)
                if bucket > budget - self._prefill_spent:
                    # Bucket padding would overshoot: resume on the
                    # next step's fresh budget (which always fits one
                    # full chunk — prefill_chunk is itself a bucket).
                    # Same rule as the wave path's over-budget fence.
                    return
                final = c == remaining
                start = entry.hit + entry.done
                nb = self.slot_blocks
                prompts = np.zeros((1, bucket), np.int32)
                prompts[0, :c] = req.prompt[start:start + c]
                meta = np.zeros((1, self._meta_width), np.int32)
                meta[0, :nb] = self.kv_pool_blocks
                meta[0, :len(entry.alloc.table)] = entry.alloc.table
                # Intermediate chunks carry the sentinel slot id: only
                # the final chunk's sampled token may reach the slot
                # state (and only the final chunk is ever read back).
                meta[0, nb] = entry.slot if final else self.num_slots
                meta[0, nb + 1] = len(req.prompt) if final else start + c
                meta[0, nb + 2] = req.top_k
                meta[0, nb + 3] = req.seed
                meta[0, nb + 4] = start
                fmeta = np.zeros((1, 2), np.float32)
                fmeta[0] = (req.temperature, req.top_p)
                prompts_dev = self._stage(prompts)
                meta_dev = self._stage(meta)
                fmeta_dev = self._stage(fmeta)
                if (self.faults is not None
                        and self.faults.fire("prefill_exc", self.steps)
                        is not None):
                    self.flight.record("fault", rid=req.rid,
                                       step=self.steps,
                                       site="prefill_exc")
                    raise FaultInjected("prefill_exc", self.steps)
                self._pool, toks = self._prefill(
                    self.params, self._pool, prompts_dev, meta_dev,
                    fmeta_dev)
                self.host_dispatches["prefill"] += 1
                self._prefill_spent += bucket
                if (self._spec is not None
                        and self._spec.drafter.kind == "device"):
                    # The drafter's pool tracks the engine's chunk for
                    # chunk (same staged arrays, same table) so a later
                    # draft reads complete prompt K/V.
                    self._spec.drafter.prefill_wave(prompts_dev, meta_dev)
                entry.done += c
                self.flight.record(
                    "prefill_chunk", rid=req.rid, step=self.steps,
                    n=c, prefilled=entry.hit + entry.done,
                    of=len(req.prompt))
                if not final:
                    continue
                # Final chunk: admit into the slot and commit.
                self._state = self._admit(self._state, toks, meta_dev,
                                          fmeta_dev)
                self.host_dispatches["admit"] += 1
                # jaxlint: disable=host-sync -- the admission first-token readback (same contract as the wave path)
                first = int(np.asarray(toks)[0])
                if (self.faults is not None
                        and self.faults.fire("scatter_corrupt",
                                             self.steps) is not None):
                    self.flight.record("fault", step=self.steps,
                                       site="scatter_corrupt")
                    first = self.cfg.vocab_size
                now = time.monotonic()
                self._rate_ring.append((now, 1))
                self._chunking.remove(entry)
                if self._commit_admission(req, entry.slot, entry.alloc,
                                          first, bucket=bucket, rung=1,
                                          now=now, finished=finished):
                    self._mark_poison("poisoned_prefill",
                                      rids=[req.rid])
                break
            else:
                return    # budget spent; later entries wait their turn

    # ------------------------------------------------------------------
    # priority preemption (ISSUE 13): when the head of the queue would
    # miss its deadline waiting on slots or blocks, evict the lowest-
    # priority active victim. The victim's blocks — prompt AND
    # generated — are donated to the radix cache and it requeues with
    # prompt' = prompt + tokens-so-far through the recovery _Resume
    # path, so its resume is a prefix hit and greedy output is
    # token-identical to an unpreempted run (pinned by test).
    # ------------------------------------------------------------------
    def _head_blocks_available(self, head: Request) -> bool:
        """Could the head's reservation be covered right now (free +
        evictable blocks, minus its prefix hit)? A pure probe — nothing
        commits."""
        need = self.block_pool.blocks_needed(len(head.prompt),
                                             head.max_new_tokens)
        hit_blocks = self.block_pool.match_len(head.prompt) \
            // self.kv_page_size
        avail = self.block_pool.free_blocks
        if self.block_pool.cache is not None:
            avail += self.block_pool.cache.evictable()
        return need - hit_blocks <= avail

    def _steps_per_s(self) -> Optional[float]:
        """Recent dispatch rate in rate-ring entries/sec — the unit the
        queue-wait histogram counts in, shared by the preemption policy
        and the Retry-After hint so the two can never drift. None while
        the signal is cold. list(deque): single C-level copy — callers
        may run on an HTTP handler thread while the loop appends."""
        ring = list(self._rate_ring)
        if len(ring) < 2:
            return None
        dt = ring[-1][0] - ring[0][0]
        if dt <= 0:
            return None
        return (len(ring) - 1) / dt

    def _projected_slot_free_s(self) -> Optional[float]:
        """Seconds until a slot frees NATURALLY: the smallest remaining
        budget over active rows, converted through the recent PER-ROW
        token rate. Token rate, not dispatch rate: under scan_k (or
        spec) one retire lands several tokens per row, so dividing
        remaining TOKENS by the dispatch rate would overestimate the
        wait by the chunk length and preempt over-eagerly on exactly
        the engines PR 12 sped up. None while the signal is cold."""
        rate = self._recent_rate()
        if rate is None or rate <= 0 or not self._active:
            return None
        per_row = rate / len(self._active)
        min_rem = min(st.req.max_new_tokens - len(st.tokens)
                      for st in self._active.values())
        return max(0, min_rem) / per_row

    def _maybe_preempt(self) -> None:
        """One preemption per step, at most: evict the lowest-priority
        active victim when the queue head (highest priority queued) is
        blocked on slots/blocks AND its deadline slack no longer covers
        the projected natural wait. Deadline-less heads never preempt
        (there is no miss to prevent); equal-or-higher-priority victims
        never exist by definition. Also hosts the ``preempt_storm``
        fault site, which skips the policy and forces an eviction."""
        if self.faults is not None and self._active:
            f = self.faults.fire("preempt_storm", self.steps)
            if f is not None:
                victim = min(self._active.values(),
                             key=lambda s: (s.req.priority, s.req.rid))
                self.flight.record("fault", rid=victim.req.rid,
                                   step=self.steps, site="preempt_storm")
                self._preempt(victim, cause="preempt_storm")
        if not self.preemption or not self._active:
            return
        head = self.sched.peek_head()
        if head is None or head.deadline_s is None:
            return
        if self.sched.free_slots and (
                self.block_pool is None
                or self._head_blocks_available(head)):
            return          # admissible this step without violence
        meta = self._submit_meta.get(head.rid)
        if meta is None:
            return
        now = time.monotonic()
        waited = now - meta[1]
        slack = head.deadline_s - waited
        if slack <= 0:
            return          # already doomed: it sheds — never waste a
        #                     victim's work on a request past saving
        proj = self._projected_slot_free_s()
        if proj is not None:
            if slack > proj:
                return      # the natural wait still makes the deadline
        elif waited < 0.5 * head.deadline_s:
            # Cold rate signal: only preempt once the head has burned
            # half its budget waiting — conservative, but deterministic.
            return
        victims = [st for st in self._active.values()
                   if st.req.priority < head.priority]
        if not victims:
            return
        # Lowest priority first; among equals the LATEST admission (the
        # least sunk work — and its resume re-prefills the least).
        victim = min(victims, key=lambda st: (st.req.priority,
                                              -st.req.rid))
        self._preempt(victim, cause="deadline")

    def _preempt(self, st: _Active, cause: str) -> None:
        """Evict one active request in favor of the queue: park its
        slot, donate its prompt+generated blocks to the radix cache,
        and requeue it at the HEAD of its priority class (requeue_front
        — seniority preserved, same as a crash-recovery victim; it can
        never bounce back and evict its evictor, whose priority is
        strictly higher by the victim-selection rule) with prompt' =
        prompt + tokens-so-far via the _Resume stitch, so the terminal
        Result reads as one uninterrupted request. Not a terminal: no
        ``evict``/``finish`` event — the fuzz pin holds."""
        import jax.numpy as jnp

        req = st.req
        del self._active[st.slot]
        self.sched.release(st.slot)
        if self._inflight is not None:
            # Drop the in-flight snapshot's claim on this slot: the
            # victim's rid is about to re-enter the queue, and if it
            # re-admits into the SAME slot before the lagged readback,
            # the ride-along tokens would double-count. They are
            # recomputed identically on resume (position-keyed
            # sampling), so dropping them costs only the lane work.
            self._inflight[1].pop(st.slot, None)
        self._state = self._release(self._state,
                                    jnp.asarray(st.slot, jnp.int32))
        self.host_dispatches["release"] += 1
        donated = 0
        if st.alloc is not None:
            donated = self.block_pool.release(st.alloc,
                                              generated=st.tokens)
        base = self._resumed.get(req.rid)
        orig_prompt = base.prompt if base is not None else req.prompt
        pre = (base.tokens if base is not None else []) + st.tokens
        new_req = replace(req, prompt=req.prompt + tuple(st.tokens),
                          max_new_tokens=req.max_new_tokens
                          - len(st.tokens))
        self._resumed[req.rid] = _Resume(prompt=orig_prompt, tokens=pre,
                                         submit_t=st.submit_t)
        self.tracer.end(st.span, {"preempted": True, "cause": cause})
        sid = self.tracer.begin("queued", cat="request", rid=req.rid,
                                args={"preempted": True})
        self._submit_meta[req.rid] = (self.steps, st.submit_t, sid)
        self.preemptions += 1
        self.flight.record("preempt", rid=req.rid, step=self.steps,
                           cause=cause, slot=st.slot,
                           salvaged_tokens=len(pre),
                           donated_blocks=donated,
                           priority=req.priority)
        self.sched.requeue_front([new_req])

    def _spec_step(self, finished: List[Result]) -> None:
        """One speculative round: collect per-row drafts (host prompt
        lookup, or the compiled ModelDrafter scan), run the fixed-shape
        verify, and retire the accepted prefix + one fresh token per
        row — with per-token eos/length checks so a mid-chunk eos
        truncates exactly where the non-spec loop would have stopped.

        Per-row draft lengths are capped at remaining_budget - 1: the
        verify always emits accepted+1 tokens, so the cap guarantees a
        row can never overshoot max_new_tokens (greedy parity then
        needs no trimming) nor write an accepted token past max_len
        (submit already bounds prompt + max_new there)."""
        import jax

        # Local handle: _disable_spec (drafter-fault streak) nulls
        # self._spec mid-call; the already-dispatched verify still
        # retires through this runner.
        runner = self._spec
        k = runner.k
        drafter = runner.drafter
        verify_sid = self.tracer.begin(
            "spec_verify", cat="spec",
            args={"k": k, "rows": len(self._active)})
        caps = {slot: min(k, st.req.max_new_tokens - len(st.tokens) - 1)
                for slot, st in self._active.items()}
        dl = np.zeros(self.num_slots, np.int32)
        drafts = np.zeros((self.num_slots, k), np.int32)
        try:
            if (self.faults is not None
                    and self.faults.fire("drafter_fault", self.steps)
                    is not None):
                raise FaultInjected("drafter_fault", self.steps)
            if drafter.kind == "host":
                # The ONLY per-step host->device transfer spec mode adds:
                # the (num_slots, k) + (num_slots,) int32 blocks ride the
                # verify dispatch itself (numpy args into jit measure
                # ~25% cheaper per CPU verify than a separate device_put
                # round).
                for slot, st in self._active.items():
                    if caps[slot] <= 0:
                        continue
                    prop = drafter.propose(list(st.req.prompt) + st.tokens,
                                           caps[slot])
                    dl[slot] = len(prop)
                    drafts[slot, :len(prop)] = prop
            else:
                drafts = drafter.draft(self._state["tok"],
                                       self._state["pos"],
                                       self._state["active"],
                                       table=self._state.get("table"))
                for slot, cap in caps.items():
                    dl[slot] = max(cap, 0)
        except Exception as e:
            # Degrade, don't die: a drafter failure turns THIS step into
            # plain decode (zero drafts -> the verify's always-emitted
            # fresh token is the only output), and a streak of them
            # disables speculation for good — correctness and uptime
            # never depend on the drafter.
            self.drafter_faults += 1
            self._drafter_fault_streak += 1
            dl[:] = 0
            drafts = np.zeros((self.num_slots, k), np.int32)
            self.flight.record("drafter_fault", step=self.steps,
                               error=f"{type(e).__name__}: {e}",
                               streak=self._drafter_fault_streak)
            if self._drafter_fault_streak >= self.spec_fault_tolerance:
                self._disable_spec(
                    f"{self._drafter_fault_streak} consecutive drafter "
                    f"faults (last: {type(e).__name__}: {e})")
        else:
            self._drafter_fault_streak = 0
        # Under TP the draft block replicates over the mesh explicitly;
        # tp == 1 keeps the bare-numpy dispatch (measurably cheaper on
        # the CPU floor, PR 4). dl/drafts stay host-resident numpy for
        # the per-slot accounting below either way.
        drafts_in = drafts if self._mesh is None else self._stage(drafts)
        dl_in = dl if self._mesh is None else self._stage(dl)
        self._pool, self._state, emitted, counts, accepted = \
            runner.verify(self.params, self._pool, self._state,
                          drafts_in, dl_in)
        self.steps += 1
        self.host_dispatches["verify"] += 1
        runner.steps += 1
        # ONE batched readback for the whole retire (synchronous by
        # design — docstring; three separate np.asarray blocks cost a
        # measurable slice of the verify step on CPU).
        # jaxlint: disable=host-sync -- the spec retire: synchronous by design (docstring)
        emit_host, counts_host, acc_host = jax.device_get(
            (emitted, counts, accepted))
        if (self.faults is not None
                and self.faults.fire("nan_logits", self.steps) is not None):
            # The spec twin of the decode-branch injection: the verify's
            # emitted tokens are what the retire reads back (emit_host
            # is already host-resident — the device_get above).
            self.flight.record("fault", step=self.steps, site="nan_logits")
            emit_host = np.full(np.shape(emit_host), self.cfg.vocab_size,
                                np.int32)
        now = time.monotonic()
        n_kept = 0
        poisoned_slots: List[int] = []
        for slot, st in list(self._active.items()):
            c = int(counts_host[slot])
            if c <= 0:
                continue
            acc = int(acc_host[slot])
            toks = emit_host[slot, :c].tolist()
            if any(not 0 <= t < self.cfg.vocab_size for t in toks):
                # Poisoned verify output: keep the row's clean tokens,
                # let the supervisor rebuild (same contract as _retire,
                # including the unsupervised strike backstop).
                poisoned_slots.append(slot)
                st.poison_strikes += 1
                if st.poison_strikes >= POISON_STRIKE_LIMIT:
                    self._fail_row(st, "persistent_poison", finished)
                continue
            if dl[slot] > 0:
                runner.drafted += int(dl[slot])
                runner.accepted += acc
                self._spec_accept_len.observe(acc)
                st.spec_accepted += acc
            if st.req.eos_id is not None and st.req.eos_id in toks:
                # eos mid-chunk: the verify's tokens after it belong past
                # the finish and are dropped — the spec twin of the
                # pipelined ride-along drop.
                toks = toks[:toks.index(st.req.eos_id) + 1]
            st.tokens.extend(toks)
            st.poison_strikes = 0      # consecutive means consecutive
            st.last_t = now
            self.flight.record("retire", rid=st.req.rid, step=self.steps,
                               n=len(toks), accepted=acc)
            n_kept += len(toks)
            done = self._maybe_finish(st)
            if done is not None:
                finished.append(done)
        if poisoned_slots:
            self._mark_poison("poisoned_step", slots=poisoned_slots)
        self.tokens_generated += n_kept
        self._rate_ring.append((now, n_kept))
        self.tracer.end(verify_sid,
                        {"emitted": n_kept,
                         "drafted": int(dl.sum()),
                         "accepted": int(acc_host.sum())})

    # A host dispatch's fixed overhead, in units of one fused scan
    # step's device time — the rung policy's exchange rate between
    # "fewer dispatches" and "wasted lane-steps past a row's budget".
    # PR 9 measured ~180us per staging upload against sub-100us fused
    # steps on the CPU floor; 2.0 is a deliberately conservative
    # middle that also behaves on TPUs (where the fixed cost dominates
    # tiny-step compute even harder). Exposed as an attribute so
    # operators can re-pin it from a measured profile.
    scan_dispatch_cost_steps = 2.0

    def _next_chunk(self) -> int:
        """The next dispatch's scan-chunk length, from the scan_rungs
        ladder — 0 when every live row's budget is already covered by
        computed tokens (read back + the chunk in flight), meaning a
        dispatch could only produce ride-along garbage.

        The rung maximizes USEFUL lane-steps per unit wall time:
        sum_rows min(remaining, r) / (dispatch_cost + r). When every
        row has budget to burn this saturates at the top rung (the
        fewest dispatches); when most rows are a token or two from
        done it shrinks toward 1 instead of spending k lane-steps to
        harvest one token per row. A row the chunk overruns just
        truncates at readback (the same machinery eos overruns use —
        eos is host knowledge by design and the one overrun no policy
        here can see). The choice never changes the token stream:
        chunks are dispatch boundaries, not sampling state, so greedy
        outputs are identical across every scan_k (pinned by test).
        eos can finish a row EARLIER than its budget, never later, so
        the length-only remaining test never skips a needed step."""
        inflight = self._inflight
        inflight_slots = inflight[1] if inflight is not None else {}
        inflight_len = inflight[4] if inflight is not None else 0
        rems = []
        for slot, st in self._active.items():
            rem = st.req.max_new_tokens - len(st.tokens)
            if inflight_slots.get(slot) == st.req.rid:
                rem -= inflight_len
            if rem > 0:
                rems.append(rem)
        if not rems:
            return 0
        # Brownout level >= 1 caps the rung (serve/brownout.py): shorter
        # chunks, finer admission interleaving, less finish-lag waste —
        # a policy input only, never a new shape (the cap selects from
        # the compiled ladder).
        rungs = self.scan_rungs
        if self.scan_cap is not None:
            rungs = [r for r in rungs if r <= self.scan_cap] or rungs[:1]
        cost = self.scan_dispatch_cost_steps
        best, best_score = 1, -1.0
        for r in rungs:
            score = sum(min(rem, r) for rem in rems) / (cost + r)
            if score >= best_score:      # ties go to the larger rung
                best, best_score = r, score
        return best

    def _retire(self, inflight: Tuple[object, Dict[int, int], int, int,
                                      int],
                finished: List[Result]) -> None:
        """Read one dispatched step's (or scan chunk's) tokens back and
        apply the lagged finish/eviction decisions. A slot whose
        occupant is no longer the snapshot's rid was evicted after
        dispatch — its ride-along tokens belong to nobody and are
        dropped (the host half of the lag-k finish machinery; the
        device active mask is the other half). Within a live row's
        chunk, tokens walk in order and truncate at the first of:
        budget reached (the row overran mid-chunk — surplus dropped),
        eos (everything after belongs past the finish), or the poison
        sentinel (everything after was computed FROM garbage — the
        clean prefix is kept, the strike/recovery machinery takes the
        rest, and the supervisor unwinds the mid-scan chunk through
        the ordinary requeue path)."""
        toks, snapshot, sid, chunk, _ = inflight
        # jaxlint: disable=host-sync -- the pipelined readback: one step/chunk behind dispatch
        nxt = np.asarray(toks)
        if nxt.ndim == 1:
            nxt = nxt[None, :]           # (1, S): the scan_k == 1 shape
        now = time.monotonic()
        n_live = 0
        poisoned_slots: List[int] = []
        for slot, rid in snapshot.items():
            st = self._active.get(slot)
            if st is None or st.req.rid != rid:
                continue
            kept = 0
            poisoned = False
            for j in range(nxt.shape[0]):
                if len(st.tokens) >= st.req.max_new_tokens:
                    break                # mid-chunk budget overrun
                tok = int(nxt[j, slot])
                if not 0 <= tok < self.cfg.vocab_size:
                    # The in-program isfinite sentinel (or an injected
                    # poison): this token — and every later one in the
                    # chunk, each sampled from state downstream of the
                    # garbage — must never reach the request's output.
                    poisoned = True
                    break
                st.tokens.append(tok)
                kept += 1
                if (st.req.eos_id is not None
                        and tok == st.req.eos_id):
                    break                # mid-chunk eos: exact truncate
            if kept:
                st.last_t = now
                n_live += kept
                # One flight event per retired (row, chunk) — n tokens
                # at once under scan_k, with the chunk index, so
                # per-token TPOT stays derivable from the JSONL.
                ev = {"rid": rid, "step": self.steps, "n": kept}
                if self.scan_k > 1:
                    ev["chunk"] = chunk
                self.flight.record("retire", **ev)
            if poisoned:
                poisoned_slots.append(slot)
                st.poison_strikes += 1
                if st.poison_strikes >= POISON_STRIKE_LIMIT:
                    self._fail_row(st, "persistent_poison", finished)
                continue
            if kept:
                st.poison_strikes = 0    # consecutive means consecutive
                done = self._maybe_finish(st)
                if done is not None:
                    finished.append(done)
        if poisoned_slots:
            self._mark_poison("poisoned_step", slots=poisoned_slots)
        self.tokens_generated += n_live
        self._rate_ring.append((now, n_live))
        self.tracer.end(sid, {"live_tokens": n_live})

    def _recent_rate(self) -> Optional[float]:
        # list(deque): single C-level copy — stats() may run on an HTTP
        # handler thread while the engine loop appends, and Python-level
        # deque iteration would raise "mutated during iteration".
        ring = list(self._rate_ring)
        if len(ring) < 2:
            return None
        t0, t1 = ring[0][0], ring[-1][0]
        if t1 <= t0:
            return None
        # Tokens attributed to the window AFTER its first timestamp.
        toks = sum(n for _, n in ring[1:])
        return toks / (t1 - t0)

    def reset_latency_stats(self) -> None:
        """Clear the TTFT/TPOT/queue-wait/rate windows (and the span
        ring) — benchmarks call this between warmup and the timed
        workload so the reported percentiles describe the measured
        traffic, not compile-time."""
        self._ttft.reset()
        self._ttft_prefix.reset()
        self._tpot.reset()
        self._queue_wait.reset()
        self._rate_ring.clear()
        self._spec_accept_len.reset()
        self._spec_req_accepted.reset()
        self.tracer.clear()
        # The SLO ledger, flight ring and the watchdog's TTFT baseline
        # describe the measured traffic too — warmup requests are
        # synthetic, deadline-less, and compile-time slow.
        self.slo.reset()
        self.flight.clear()
        self.watchdog.reset()
        if self.block_pool is not None:
            # Hit rates and capacity means should describe the measured
            # workload too — warmup prompts are synthetic and all-miss.
            self.block_pool.reset_ledger()
        if self._spec is not None:
            # Acceptance rate should describe the measured workload too —
            # warmup prompts are degenerate (all-zero) and would skew it.
            self._spec.steps = 0
            self._spec.drafted = 0
            self._spec.accepted = 0

    def warm_scan_rungs(self) -> None:
        """Compile EVERY scan-rung megaprogram by dispatching each rung
        once over the parked slot state — no synthetic requests, no
        reasoning about which remaining-budget mixes the chunk policy
        can reach (ties and mixed-row scores make that set subtle).
        Parked rows are harmless to dispatch: their writes land at
        their own row's position 0 (dense — overwritten by the next
        occupant's prefill before any read, the stale-tail argument) or
        drop on the sentinel block-table entries (paged), pos stays
        frozen, and the garbage tokens are never read back. serve
        __main__ --warmup=full and the bench warmups call this; a rung
        left uncompiled would be a post-freeze retrace outage the first
        time live traffic's budget mix makes the policy pick it.
        Idle-only (enforced): on a busy engine the rung dispatches
        would advance live rows' device frontiers with no readback,
        silently dropping tokens from their outputs."""
        if self._active or self._inflight is not None:
            raise RuntimeError(
                "warm_scan_rungs on a busy engine: active rows' "
                "frontiers would advance without a readback")
        for r in self.scan_rungs:
            self._pool, self._state, _ = self._decode(
                self.params, self._pool, self._state, r)

    def reset_prefix_cache(self) -> None:
        """Drop every cached prefix block back to the free list. Only
        legal on an idle engine (no active requests hold cache refs) —
        warmup calls this so its synthetic prompts can never serve a
        hit to live traffic, and tests use it to force cold-cache
        baselines. The hit/miss token ledger resets with it (the rate
        should describe the traffic after the reset)."""
        if not self.paged:
            return
        if self._active:
            raise RuntimeError(
                "reset_prefix_cache on a busy engine: active requests "
                "hold references into the radix cache")
        self.block_pool.reset_cache()

    def _maybe_finish(self, state: _Active) -> Optional[Result]:
        import jax.numpy as jnp

        req = state.req
        reason = None
        if (req.eos_id is not None and state.tokens
                and state.tokens[-1] == req.eos_id):
            reason = "eos"
        elif len(state.tokens) >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return None
        now = time.monotonic()
        del self._active[state.slot]
        self.sched.release(state.slot)
        self.flight.record("evict", rid=req.rid, step=self.steps,
                           slot=state.slot)
        # Park the idle row on device; queued after any in-flight step,
        # so the ride-along step (if one is in flight) still reads the
        # pre-release state it was dispatched with.
        self._state = self._release(self._state,
                                    jnp.asarray(state.slot, jnp.int32))
        self.host_dispatches["release"] += 1
        prefix_digest: tuple = ()
        if state.alloc is not None:
            # Host block release: deref the hit chain, DONATE the full
            # prompt blocks to the radix cache, free the rest. Safe even
            # with a ride-along decode step in flight: that step was
            # dispatched with the old table and only ever writes the
            # row's generated-region frontier block — never a donated
            # (prompt-only) block — and any reallocation's prefill
            # queues behind it, overwriting its garbage block-for-block.
            self.block_pool.release(state.alloc)
            if self.block_pool.cache is not None:
                # What this replica now caches, as chained block
                # fingerprints (paged.prefix_digests — host-side hashing
                # of the already-host-resident prompt tuple, no sync):
                # the fleet router's affinity signal, reported on the
                # Result, the flight terminal, and the /generate body.
                from nanosandbox_tpu.serve.paged import prefix_digests
                prefix_digest = tuple(
                    prefix_digests(req.prompt, self.kv_page_size))
        self.completed += 1
        self._c_completed.labels(reason=reason).inc()
        # Stitch a recovered request back together: the Result (and its
        # SLO/flight accounting) must read as ONE uninterrupted request
        # — original prompt, pre-fault tokens + post-recovery tokens,
        # end-to-end latency from the original submit.
        prompt_out, tokens_out, resumed = self._unstitch(
            req.rid, req, state.tokens)
        self.tracer.end(state.span, {"tokens": len(tokens_out),
                                     "finish_reason": reason})
        # SLO + flight terminal: end-to-end latency vs deadline, tokens
        # into the goodput ledger, the exactly-once `finish` event.
        elapsed = now - state.submit_t
        prefix = ("hit" if state.alloc is not None and state.alloc.n_hit
                  else "miss")
        met = self.slo.record_finish(req.slo_class,
                                     tokens=len(tokens_out),
                                     elapsed_s=elapsed,
                                     deadline_s=req.deadline_s,
                                     prefix=prefix)
        fin = {"reason": reason, "tokens": len(tokens_out),
               "e2e_s": round(elapsed, 6)}
        if resumed:
            fin["resumed"] = True
        if met is not None:
            fin["deadline_met"] = met
        if prefix_digest:
            fin["prefix_digest"] = list(prefix_digest)
        self.flight.record("finish", rid=req.rid, step=self.steps, **fin)
        if self._spec is not None:
            self._spec_req_accepted.observe(state.spec_accepted)
        if len(state.tokens) > 1:
            self._tpot.observe((now - state.first_token_t)
                               / (len(state.tokens) - 1))
        return Result(rid=req.rid, prompt=prompt_out, tokens=tokens_out,
                      finish_reason=reason, prefix_digest=prefix_digest)

    # ------------------------------------------------------------------
    # fault detection, quarantine & crash-safe recovery (ISSUE 11).
    # The engine owns the MECHANISM (detect poison, rebuild device
    # state, re-admit victims); serve/recovery.py's EngineSupervisor
    # owns the POLICY (when to recover, backoff, permanent-failure
    # escalation).
    # ------------------------------------------------------------------
    def _mark_poison(self, kind: str, **info) -> None:
        """Record a detected poisoned step (latched until take_poison):
        the step's outputs were discarded, the device state is suspect,
        and the supervisor should rebuild before the next dispatch."""
        self.poisoned_steps += 1
        if self._poison is None:
            self._poison = {"kind": kind, "step": self.steps, **info}
        self.flight.record("poison", step=self.steps, kind=kind, **info)

    def take_poison(self) -> Optional[dict]:
        """The supervisor's post-step check: returns and clears the
        latched poison detection, if any."""
        poison, self._poison = self._poison, None
        return poison

    def _unstitch(self, rid: int, req: Request,
                  tokens: Sequence[int]) -> Tuple[tuple, List[int], bool]:
        """Resolve a terminal's (prompt, tokens, was_resumed) through
        the _Resume record: EVERY terminal path (finish, shed, failed,
        abort) must report the ORIGINAL prompt and the pre-fault tokens
        ahead of whatever this incarnation generated — and must pop the
        record, or a long-lived server leaks one per recovered rid."""
        res = self._resumed.pop(rid, None)
        if res is None:
            return req.prompt, list(tokens), False
        return res.prompt, res.tokens + list(tokens), True

    def _fail_row(self, st: _Active, cause: str,
                  finished: List[Result]) -> None:
        """Terminate ONE wedged row with a 'failed' Result — the
        unsupervised-poison backstop (POISON_STRIKE_LIMIT). A
        supervisor-driven engine recovers after the first poison, so
        this path means nobody is recovering and the poison is
        persistent: free the slot, salvage the clean tokens, leave
        exactly one terminal. No ``evict`` event — like abort_all, the
        row never finished (evict is reserved for the finish path)."""
        import jax.numpy as jnp

        req = st.req
        del self._active[st.slot]
        self.sched.release(st.slot)
        self._state = self._release(self._state,
                                    jnp.asarray(st.slot, jnp.int32))
        self.host_dispatches["release"] += 1
        if st.alloc is not None:
            # Prompt blocks are prefill-written (clean) — donation is
            # safe under the same argument recover() relies on.
            self.block_pool.release(st.alloc)
        prompt_out, tokens_out, _ = self._unstitch(req.rid, req,
                                                   st.tokens)
        if req.deadline_s is not None:
            self.slo.record_shed(req.slo_class)
        self._c_completed.labels(reason="failed").inc()
        self.tracer.end(st.span, {"failed": True, "cause": cause})
        self.flight.record("failed", rid=req.rid, step=self.steps,
                           cause=cause, tokens=len(tokens_out))
        finished.append(Result(rid=req.rid, prompt=prompt_out,
                               tokens=tokens_out, finish_reason="failed"))

    def _disable_spec(self, reason: str) -> None:
        """Graceful spec degradation: drop to plain synchronous decode
        for the engine's lifetime. Outputs stay correct (greedy spec ==
        greedy non-spec by construction); only throughput is lost."""
        from nanosandbox_tpu.utils.metrics import warn_once

        self.spec_disabled_reason = reason
        self._spec = None
        self.flight.record("spec_disabled", step=self.steps, reason=reason)
        warn_once("serve-spec-disabled",
                  f"[serve] speculative decoding DISABLED: {reason}; "
                  "continuing with plain decode")

    def quarantine(self, cause: str) -> None:
        """Flip the engine into quarantine: readiness probes go red and
        the supervisor rebuilds before anything else is dispatched."""
        self.quarantined = True
        self.quarantine_cause = cause
        self.flight.record("quarantine", step=self.steps, cause=cause)

    def _close_dangling_spans(self) -> None:
        """End the spans a crash left open — the in-flight decode_step
        (never retired) and a mid-prefill wave — so the tracer's open
        table cannot grow across repeated recoveries (open_count()'s
        zero-after-drain contract survives faults)."""
        if self._inflight is not None:
            self.tracer.end(self._inflight[2], {"aborted": True})
        if self._admitting_span is not None:
            self.tracer.end(self._admitting_span, {"aborted": True})
            self._admitting_span = None

    def recover(self, cause: str = "unknown", *,
                flush_cache: bool = False) -> dict:
        """Rebuild device slot state + block table from scratch and
        re-admit every in-flight request through the normal admission
        path.

        The flight recorder and the host request journal (_active /
        _admitting / scheduler queue) are the source of truth: each
        victim is re-queued AT THE HEAD with prompt' = prompt +
        tokens-generated-so-far and the remaining token budget. Row
        keys derive from fold_in(seed, absolute_position), so the
        resumed stream continues EXACTLY where the fault cut it —
        greedy outputs are token-identical to a no-fault run (pinned by
        test) and sampled outputs are identically distributed. With the
        prefix cache on, a victim's full prompt blocks are donated at
        release and its re-prefill is a prefix HIT: resume costs one
        suffix prefill, not a full re-prefill.

        ``flush_cache=True`` (the exception path: a dispatch crashed
        with donated buffers possibly invalidated) additionally drops
        the radix cache and re-materializes the KV pool arrays; the
        poison path keeps both — a poisoned step only ever wrote its
        rows' private frontier blocks, which are freed here and fully
        overwritten by re-prefill before any read (the PR 9 argument).
        """
        t0 = time.monotonic()
        self._close_dangling_spans()
        self._inflight = None
        self._poison = None
        actives = sorted(self._active.values(), key=lambda s: s.req.rid)
        # A crash INSIDE the wave-commit loop leaves the committed part
        # of the wave in BOTH _active and _admitting — releasing such a
        # slot/alloc twice would crash the recovery itself, so _active
        # wins and the overlap is dropped from the limbo list.
        active_rids = {st.req.rid for st in actives}
        admitting = [entry for entry in self._admitting
                     if entry[0].rid not in active_rids]
        self._active = {}
        self._admitting = []
        requeue: List[Tuple[Request, int, Optional[float]]] = []
        for st in actives:
            self.sched.release(st.slot)
            if st.alloc is not None:
                self.block_pool.release(st.alloc)
            self.tracer.end(st.span, {"recovered": True})
            base = self._resumed.get(st.req.rid)
            orig_prompt = base.prompt if base is not None else st.req.prompt
            pre = (base.tokens if base is not None else []) + st.tokens
            remaining = st.req.max_new_tokens - len(st.tokens)
            req = replace(st.req,
                          prompt=st.req.prompt + tuple(st.tokens),
                          max_new_tokens=remaining)
            self._resumed[req.rid] = _Resume(prompt=orig_prompt,
                                             tokens=pre,
                                             submit_t=st.submit_t)
            requeue.append((req, len(pre), st.submit_t))
        for req, slot, alloc in admitting:
            # A wave caught mid-prefill: blocks committed, slots
            # claimed, nothing active yet. Its submit meta (and queued
            # span) are still open — requeue as-is.
            self.sched.release(slot)
            if alloc is not None:
                self.block_pool.release(alloc)
            base = self._resumed.get(req.rid)
            requeue.append((req, len(base.tokens) if base else 0, None))
        for entry in self._chunking:
            # A chunked prefill caught mid-pipeline: its blocks hold a
            # PARTIALLY-written prompt, so they free without donation
            # (a half-written chain must never serve a prefix hit);
            # the request requeues as-is and re-chunks from scratch.
            self.sched.release(entry.slot)
            if entry.alloc is not None:
                self.block_pool.release(entry.alloc, donate=False)
            base = self._resumed.get(entry.req.rid)
            requeue.append((entry.req,
                            len(base.tokens) if base else 0, None))
        self._chunking = []
        while True:
            # Migration limbo: the export's chain is fully written and
            # its row already released — donate it back (clean by the
            # same copy-on-write argument as actives; under flush_cache
            # the reset below evicts it anyway), restore the migrate
            # intent, and requeue. Re-prefill is a prefix hit over the
            # just-donated chain and resamples the SAME first token
            # (fold_in(seed, true_len)), so the re-export is token-
            # identical to the one this recovery discarded.
            exp = self.sched.pop_limbo()
            if exp is None:
                break
            self.block_pool.release(exp.alloc)
            self._migrate_rids.add(exp.req.rid)
            base = self._resumed.get(exp.req.rid)
            requeue.append((exp.req,
                            len(base.tokens) if base else 0,
                            exp.submit_t))
        if flush_cache:
            from nanosandbox_tpu.models.gpt import (init_cache,
                                                    init_paged_cache)
            if self.paged:
                self.block_pool.reset_cache()
                # _place_pool: a TP engine's rebuilt pool must land on
                # the SAME heads-sharded placement the anchors expect —
                # a replicated rebuild would reshard (or worse, gather)
                # on the first post-recovery dispatch.
                self._pool = self._place_pool(
                    init_paged_cache(self.cfg, self.kv_pool_blocks,
                                     self.kv_page_size,
                                     kv_dtype=self._kv_dtype_arg))
            else:
                self._pool = self._place_pool(
                    init_cache(self.cfg, self.num_slots, self.max_len,
                               kv_dtype=self._kv_dtype_arg))
        self._state = self._fresh_slot_state()
        # FIFO restoration: victims re-enter at the head of their
        # PRIORITY CLASS in rid (= original admission) order, ahead of
        # same-class traffic that arrived after them but never jumping
        # higher-priority queued requests.
        requeue.sort(key=lambda item: item[0].rid)
        now = time.monotonic()
        for req, done, sub_t in requeue:
            if req.rid not in self._submit_meta:
                sid = self.tracer.begin("queued", cat="request",
                                        rid=req.rid,
                                        args={"resumed": True})
                self._submit_meta[req.rid] = (
                    self.steps, sub_t if sub_t is not None else now, sid)
            self.requeued += 1
            self.flight.record("requeue", rid=req.rid, step=self.steps,
                               cause=cause, tokens_done=done)
        self.sched.requeue_front([item[0] for item in requeue])
        self.recoveries += 1
        self._c_recoveries.labels(cause=cause).inc()
        dt = time.monotonic() - t0
        self._h_recovery.observe(dt)
        self.quarantined = False
        self.quarantine_cause = None
        self.flight.record("recover", step=self.steps, cause=cause,
                           requeued=len(requeue), flushed=flush_cache,
                           rebuild_s=round(dt, 6))
        return {"cause": cause, "requeued": len(requeue),
                "flush_cache": flush_cache, "rebuild_s": dt}

    def abort_all(self, cause: str) -> List[Result]:
        """Permanent-failure drain: terminal-fail every in-flight and
        queued request (partial tokens are salvaged into the Result),
        park the device state, and refuse future submissions — the
        clean alternative to a crash loop. Each victim gets exactly one
        terminal ``failed`` flight event."""
        self.failed = True
        self.quarantined = False
        self.quarantine_cause = cause
        self._close_dangling_spans()
        self._inflight = None
        self._poison = None
        results, self._pending_results = self._pending_results, []
        victims: List[Tuple[Request, Optional[int], object, List[int],
                            bool]] = []
        active_rids = set()
        for st in sorted(self._active.values(), key=lambda s: s.req.rid):
            self.sched.release(st.slot)
            if st.alloc is not None:
                self.block_pool.release(st.alloc)
            self.tracer.end(st.span, {"failed": True})
            active_rids.add(st.req.rid)
            victims.append((st.req, st.slot, st.alloc, st.tokens, False))
        for req, slot, alloc in self._admitting:
            if req.rid in active_rids:
                continue    # committed mid-wave: _active already owns it
            self.sched.release(slot)
            if alloc is not None:
                self.block_pool.release(alloc)
            victims.append((req, slot, alloc, [], True))
        for entry in self._chunking:
            self.sched.release(entry.slot)
            if entry.alloc is not None:
                # Partially-written chain: free, never donate.
                self.block_pool.release(entry.alloc, donate=False)
            victims.append((entry.req, entry.slot, entry.alloc, [], True))
        self._active = {}
        self._admitting = []
        self._chunking = []
        self._migrate_rids.clear()
        for item in self.sched.drain_expired(lambda item: True):
            if isinstance(item, _Export):
                # Migration limbo: blocks held, no slot. The handoff
                # never completed — free without donation and salvage
                # the sampled first token into the terminal, like any
                # in-flight victim's partial tokens.
                self.block_pool.release(item.alloc, donate=False)
                victims.append((item.req, None, item.alloc,
                                [item.first_tok], True))
                continue
            victims.append((item, None, None, [], True))
        self._state = self._fresh_slot_state()
        for req, slot, alloc, toks, queued in victims:
            meta = self._submit_meta.pop(req.rid, None)
            if meta is not None:
                self.tracer.end(meta[2], {"failed": True})
            prompt_out, tokens_out, _ = self._unstitch(req.rid, req, toks)
            if req.deadline_s is not None:
                self.slo.record_shed(req.slo_class)
            self._c_completed.labels(reason="failed").inc()
            self.flight.record("failed", rid=req.rid, step=self.steps,
                               cause=cause, tokens=len(tokens_out))
            results.append(Result(rid=req.rid, prompt=prompt_out,
                                  tokens=tokens_out,
                                  finish_reason="failed"))
        self.flight.record("engine_failed", step=self.steps, cause=cause,
                           aborted=len(victims))
        return results

    # ------------------------------------------------------------------
    # disaggregated prefill/decode (ISSUE 16). Export side: a migrate-
    # flagged request parks (block chain + first token) in limbo at its
    # first-token readback; the pump pops it, moves the blocks, and
    # either completes the export (adopted elsewhere) or requeues it
    # (colocated fallback — the exactly-once failure path). Adopt side:
    # begin/commit/abort adopt re-admits a migrated chain as a pure
    # prefix hit through the rung-1 admit program — ZERO prefill
    # dispatches, which is the whole point: the decode tier's compile
    # set stays {decode rungs, admit, release}, a strict subset of the
    # colocated engine's (jits are lazy; a program never dispatched is
    # never compiled), and its TPOT never pays for anyone's prompt.
    # ------------------------------------------------------------------
    def pop_export(self) -> Optional[_Export]:
        """Claim the oldest limbo-parked export for transfer (None when
        empty). The caller now owns the record: it must end in exactly
        one of complete_export (handoff succeeded), requeue_export
        (fallback to colocated here), or repark_export (transient
        backpressure — try again next pump)."""
        return self.sched.pop_limbo()

    def repark_export(self, exp: _Export) -> None:
        """Return an un-transferred export to the HEAD of limbo (the
        adopting tier had no slot/blocks this pump); the deadline sweep
        keeps watching it."""
        self.sched.park_limbo_front(exp)

    def complete_export(self, exp: _Export, *, dst: str = "",
                        blocks_copied: int = 0, bytes_moved: int = 0,
                        migrate_s: float = 0.0) -> None:
        """The handoff landed: the adopting engine committed the row.
        Release the chain WITH donation — it is fully written and
        clean, and keeping it warm in this tier's radix trie is what
        makes a later failover restitch (prompt + salvaged tokens) a
        prefix HIT here instead of a full re-prefill — and leave the
        terminal accounting to the adopter. Records the exactly-once
        ``migrate`` flight event (chain length, transferred bytes,
        src/dst) on THIS engine: the source owns the handoff story."""
        self.migrated += 1
        self.completed += 1
        self._c_completed.labels(reason="migrated").inc()
        self.block_pool.release(exp.alloc)
        self.flight.record(
            "migrate", rid=exp.req.rid, step=self.steps,
            dst=dst, chain_blocks=len(exp.alloc.table),
            hit_blocks=exp.alloc.n_hit, copied_blocks=blocks_copied,
            bytes=bytes_moved, migrate_s=round(migrate_s, 6),
            limbo_s=round(time.monotonic() - exp.export_t, 6))

    def requeue_export(self, exp: _Export, *, migrate: bool = False) -> None:
        """Fallback: no decode tier can adopt (tier death, permanent
        backpressure) — put the request back through THIS engine's
        admission, colocated by default. Blocks release WITH donation
        (the chain is clean and fully written), so the re-prefill is a
        pure prefix hit that resamples the SAME first token
        (fold_in(seed, true_len)) — the terminal Result is token-
        identical to the migration that never happened, under the
        request's ORIGINAL rid and deadline budget: exactly-once by
        construction, no pair-level dedup needed."""
        self.block_pool.release(exp.alloc)
        if migrate:
            self._migrate_rids.add(exp.req.rid)
        sid = self.tracer.begin("queued", cat="request", rid=exp.req.rid,
                                args={"requeued_export": True})
        self._submit_meta[exp.req.rid] = (exp.submit_step,
                                          exp.submit_t, sid)
        self.sched.requeue_front([exp.req])
        self.flight.record("requeue", rid=exp.req.rid, step=self.steps,
                           cause="export_fallback", tokens_done=0)

    def begin_adopt(self, req: Request, *,
                    max_new_tokens: Optional[int] = None
                    ) -> Optional[_Adoption]:
        """Phase 1 of adopting a migrated request: claim a slot and the
        FULL block footprint (prompt chain + generation budget —
        paged.adopt_chain). Returns None when this engine cannot take
        it right now (no free slot, pool shortfall, quarantine) — the
        adoption-backpressure signal; the caller re-parks the export
        and retries next pump. On success the handle's ``copy``/
        ``dst_blocks`` name the blocks to fill via write_pool_blocks
        before commit_adopt; abort_adopt unwinds a transfer that died
        mid-flight. The request is re-keyed into THIS engine's rid
        space (the pair/frontend owns the cross-engine mapping)."""
        if self.failed:
            raise EngineFailedError(
                "engine permanently failed; cannot adopt")
        if not self.paged:
            raise ValueError("adoption needs a paged engine: the block "
                             "chain is the migration wire format")
        max_new = (req.max_new_tokens if max_new_tokens is None
                   else int(max_new_tokens))
        if len(req.prompt) + max_new > self.max_len:
            raise ValueError(
                f"adopted prompt ({len(req.prompt)}) + max_new "
                f"({max_new}) exceeds max_len {self.max_len}")
        if self.quarantined or not self.sched.free_slots:
            return None
        got = self.block_pool.adopt_chain(req.prompt, max_new)
        if got is None:
            self.flight.record(
                "block_stall", rid=-1, step=self.steps,
                need=self.block_pool.blocks_needed(len(req.prompt),
                                                   max_new),
                free=self.block_pool.free_blocks, adopt=True)
            return None
        alloc, copy = got
        req = replace(req, rid=next(self._rid), max_new_tokens=max_new)
        return _Adoption(req=req, slot=self.sched.take_slot(),
                         alloc=alloc, copy=copy)

    def abort_adopt(self, ad: _Adoption) -> None:
        """Unwind a begun adoption whose transfer failed (source died
        mid-copy, injected fault): slot back, blocks freed WITHOUT
        donation — the chain may be partially copied and a half-written
        chain must never serve a prefix hit. No terminal here: the
        request still lives on the SOURCE side (its export record),
        which resolves it exactly once via requeue/complete/shed."""
        self.sched.release(ad.slot)
        self.block_pool.release(ad.alloc, donate=False)

    def commit_adopt(self, ad: _Adoption, first_tok: int, *,
                     submit_t: Optional[float] = None,
                     src: str = "") -> Tuple[int, Optional[Result]]:
        """Phase 2: activate the adopted row. One rung-1 ``admit``
        scatter stages the block table, pos = true_len = len(prompt),
        the migrated first token, and the ORIGINAL seed — decode
        continues with fold_in(seed, pos + 1) keys, exactly the stream
        the source's colocated decode would have produced, so greedy
        outputs are token-identical to never having migrated (pinned by
        test). NO prefill dispatch, no readback: admission here is a
        pure prefix hit by construction. Returns (rid-on-this-engine,
        immediately-finished Result or None)."""
        req = ad.req
        if not 0 <= int(first_tok) < self.cfg.vocab_size:
            # A poisoned/corrupt wire token must not be scattered: the
            # caller unwinds (abort_adopt) and the source falls back.
            raise ValueError(
                f"migrated first token {first_tok} outside [0, "
                f"vocab_size={self.cfg.vocab_size})")
        now = time.monotonic()
        nb = self.slot_blocks
        meta = np.zeros((1, self._meta_width), np.int32)
        meta[0, :nb] = self.kv_pool_blocks
        meta[0, :len(ad.alloc.table)] = ad.alloc.table
        meta[0, nb] = ad.slot
        meta[0, nb + 1] = len(req.prompt)
        meta[0, nb + 2] = req.top_k
        meta[0, nb + 3] = req.seed
        meta[0, nb + 4] = ad.alloc.n_hit * self.kv_page_size
        fmeta = np.array([[req.temperature, req.top_p]], np.float32)
        toks = np.array([int(first_tok)], np.int32)
        self._state = self._admit(self._state, self._stage(toks),
                                  self._stage(meta), self._stage(fmeta))
        self.host_dispatches["admit"] += 1
        self.admitted += 1
        self.adopted += 1
        self._c_submitted.inc()
        gen_sid = self.tracer.begin(
            "generate", cat="request", rid=req.rid,
            args={"slot": ad.slot, "adopted": True})
        st = _Active(req=req, slot=ad.slot, tokens=[int(first_tok)],
                     first_token_t=now,
                     submit_t=submit_t if submit_t is not None else now,
                     last_t=now, span=gen_sid, alloc=ad.alloc)
        self._active[ad.slot] = st
        self.flight.record(
            "adopt", rid=req.rid, step=self.steps, slot=ad.slot,
            src=src, chain_blocks=len(ad.alloc.table),
            hit_blocks=ad.alloc.n_hit, copied_blocks=len(ad.copy),
            prompt_len=len(req.prompt))
        return req.rid, self._maybe_finish(st)

    def read_pool_blocks(self, block_ids: Sequence[int]) -> List:
        """Gather whole KV-pool blocks by id, one host array per pool
        leaf in jax.tree flatten order — the migration wire payload
        (quantized pools ride as-is: int8/int4 codes + their scales are
        just more leaves, so a migration never dequantizes). A host
        sync by design: migration is a cold-path transfer the pump runs
        BETWEEN steps, never a per-token cost — it lives outside the
        engine's guarded compile set and its host-sync ledger."""
        import jax
        idx = np.asarray(list(block_ids), np.int32)
        return [np.asarray(leaf)[idx]
                for leaf in jax.tree_util.tree_leaves(self._pool)]

    def write_pool_blocks(self, block_ids: Sequence[int],
                          values: Sequence) -> int:
        """Scatter whole blocks into this pool by id — the adopt-side
        twin of read_pool_blocks. Updates are padded to the fixed
        slot_blocks rung with the out-of-range drop sentinel, so every
        chain length rides ONE implicit program per leaf instead of
        minting a shape per migration (the fixed-shape discipline,
        applied to the cold path too). Returns payload bytes written
        (real rows only — padding is free)."""
        import jax
        n = len(block_ids)
        if n == 0:
            return 0
        if n > self.slot_blocks:
            raise ValueError(
                f"{n} blocks exceed the per-request maximum "
                f"{self.slot_blocks}")
        idx = np.full((self.slot_blocks,), self.kv_pool_blocks, np.int32)
        idx[:n] = np.asarray(list(block_ids), np.int32)
        idx_dev = self._stage(idx)
        leaves, treedef = jax.tree_util.tree_flatten(self._pool)
        if len(values) != len(leaves):
            raise ValueError(
                f"payload has {len(values)} leaves, pool has "
                f"{len(leaves)}")
        out = []
        nbytes = 0
        for leaf, vals in zip(leaves, values):
            v = np.asarray(vals)
            if v.shape[0] < n or v.shape[1:] != leaf.shape[1:] \
                    or v.dtype != leaf.dtype:
                raise ValueError(
                    f"payload leaf {v.shape}/{v.dtype} does not match "
                    f"pool leaf {leaf.shape}/{leaf.dtype}")
            nbytes += v[:n].nbytes
            padded = np.zeros((self.slot_blocks,) + tuple(leaf.shape[1:]),
                              v.dtype)
            padded[:n] = v[:n]
            out.append(leaf.at[idx_dev].set(self._stage(padded),
                                            mode="drop"))
        self._pool = jax.tree_util.tree_unflatten(treedef, out)
        return nbytes

    def retry_after_s(self, slo_class: Optional[str] = None,
                      priority: Optional[int] = None) -> float:
        """Client backoff hint for 429/503 responses: the scheduler's
        queue-wait p50 converted to wall seconds through the recent
        step rate (fallback 1s when either signal is cold) — a shed
        client that waits this long lands where today's admitted
        traffic is actually clearing the queue.

        Priority-aware (ISSUE 13): under the priority queue a batch
        request waits behind EVERYTHING at or above its class, so its
        hint scales with the queue mass ahead of it — a batch client
        behind a deep interactive queue no longer gets an interactive
        client's optimistic number. ``slo_class`` maps through
        PRIORITY_BY_CLASS when ``priority`` is not given; with neither,
        the classless base estimate is returned (the pre-priority
        behavior)."""
        base = 1.0
        p = self._queue_wait.percentiles((50,))
        steps_per_s = self._steps_per_s()
        if p and p.get("p50") is not None and steps_per_s is not None:
            base = max(0.5, p["p50"] / steps_per_s)
        if priority is None:
            if slo_class is None:
                return base
            priority = PRIORITY_BY_CLASS.get(slo_class, DEFAULT_PRIORITY)
        # Everything at-or-above the class waits ahead of it; STRICTLY
        # higher backlog counts double — its depth is the best available
        # proxy for the arrival pressure that will keep jumping this
        # class after it requeues (and, with deadlines, preempting it).
        ahead, jumps = self.sched.queue_mass(priority)
        return base * (1.0 + (ahead + jumps) / max(1, self.num_slots))
