"""Continuous-batching decode engine over a slot-based KV cache pool.

Design (the TPU fixed-shape discipline, end to end):

  * One per-layer KV pool of shape (num_slots, H, max_len, D)
    (models/gpt.py init_cache with batch = num_slots). Each in-flight
    request OWNS one slot row for its lifetime; eviction is just
    returning the row to the free list — no copies, the next occupant's
    prefill overwrites it and the per-row causal mask hides any stale
    tail.

  * Prefill: a request admitted into a slot runs the model once over
    its prompt padded to a bucket length (scheduler ladder), writing
    the bucket's K/V columns into the slot row and sampling the first
    token from the TRUE last prompt position. One compiled program per
    bucket, ever.

  * Decode: every step runs the model on (num_slots, 1) tokens with a
    PER-ROW cache_index vector (models/gpt.py per-row frontier path) —
    active rows each at their own position, idle rows riding along as
    padding whose outputs are ignored. Exactly one compiled decode
    program regardless of the request mix.

  * Sampling is per-row (_sample_token with (S,) parameter vectors) and
    per-row keyed: the token at position q of request r is sampled with
    fold_in(key(r.seed), q), so a request's output stream is a pure
    function of (params, prompt, settings, seed) — independent of which
    other requests happen to share its batch. That invariant is what
    makes continuous batching testable against single-request
    sample.generate token-for-token.

The engine is synchronous and single-threaded by design (one step() ==
one decode dispatch + one host sync for the sampled tokens); http.py
wraps it in a background thread for concurrent clients.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from nanosandbox_tpu.serve.scheduler import SlotScheduler, default_buckets


@dataclass(frozen=True)
class Request:
    """One generation request, in token-id space (the HTTP layer owns
    text <-> tokens)."""
    rid: int
    prompt: tuple
    max_new_tokens: int
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    seed: int = 0
    eos_id: Optional[int] = None


@dataclass
class Result:
    rid: int
    prompt: tuple
    tokens: List[int]          # generated ids (includes the eos hit, if any)
    finish_reason: str         # 'length' | 'eos'


@dataclass
class _Active:
    req: Request
    slot: int
    tokens: List[int] = field(default_factory=list)


class Engine:
    """submit() / step() / drain() continuous-batching engine.

    Parameters
    ----------
    model, params : the flax GPT and its (cast) params — exactly what
        sample.generate takes, so one checkpoint serves both paths.
    num_slots : concurrent request capacity (the decode batch).
    max_len : per-slot KV length; prompt + new tokens must fit. Capped
        at block_size (wpe defines no positions past it).
    prefill_buckets : padded prompt lengths to compile; default is the
        power-of-two ladder up to max_len.
    """

    def __init__(self, model, params, *, num_slots: int = 8,
                 max_len: Optional[int] = None,
                 prefill_buckets: Optional[Sequence[int]] = None):
        import jax

        from nanosandbox_tpu.models.gpt import init_cache

        cfg = model.cfg
        self.model = model
        self.params = params
        self.cfg = cfg
        self.num_slots = num_slots
        self.max_len = min(max_len or cfg.block_size, cfg.block_size)
        buckets = (sorted(b for b in prefill_buckets if b <= self.max_len)
                   if prefill_buckets else default_buckets(self.max_len))
        if not buckets:
            raise ValueError("no prefill bucket fits within max_len "
                             f"{self.max_len}: {prefill_buckets!r}")
        self.sched = SlotScheduler(num_slots, buckets)

        self._pool = init_cache(cfg, num_slots, self.max_len)
        # Per-slot device-step operands, mirrored host-side as numpy so
        # admission/eviction are plain array writes. Idle rows keep
        # harmless values (pos 0, temperature 0): they decode garbage
        # into their own slot row, which the next prefill overwrites.
        self._pos = np.zeros(num_slots, np.int32)
        self._tok = np.zeros(num_slots, np.int32)
        self._temp = np.zeros(num_slots, np.float32)
        self._topk = np.zeros(num_slots, np.int32)
        self._topp = np.ones(num_slots, np.float32)
        self._seed = np.zeros(num_slots, np.int32)

        self._active: Dict[int, _Active] = {}        # slot -> state
        self._pending_results: List[Result] = []     # max_new_tokens == 0
        self._rid = itertools.count()
        self.steps = 0
        self.admitted = 0
        self.completed = 0
        # Trace-time side-effect counters: each retrace of a step
        # function bumps these, so a shape leak (e.g. a Python scalar
        # specializing a trace) shows up as a failing compile-budget
        # assert instead of a silent 10x serving slowdown.
        self.trace_counts = {"prefill": 0, "decode": 0}

        # CPU jit ignores donation (and warns); only donate the pool on
        # accelerators, where reusing the KV buffers in place matters.
        donate = (1,) if jax.default_backend() != "cpu" else ()
        self._prefill = jax.jit(self._prefill_fn, donate_argnums=donate)
        self._decode = jax.jit(self._decode_fn, donate_argnums=donate)

    # ------------------------------------------------------------------
    # compiled step functions
    # ------------------------------------------------------------------
    def _prefill_fn(self, params, pool, prompt, true_len, slot,
                    temp, top_k, top_p, seed):
        """Prompt (1, L_bucket) -> (new pool, first sampled token (1,)).

        Runs the ordinary scalar-cache prefill on a batch-1 temp cache of
        the bucket length, then writes those columns into the slot's pool
        row. Positions >= true_len hold garbage K/V — decode overwrites
        each position before attending to it and the per-row mask hides
        the rest, so padding never leaks into any output (the greedy
        parity test pins this)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        from nanosandbox_tpu.models.gpt import init_cache
        from nanosandbox_tpu.sample import _sample_token

        self.trace_counts["prefill"] += 1
        L = prompt.shape[1]
        cache = init_cache(self.cfg, 1, L)
        logits, cache = self.model.apply({"params": params}, prompt,
                                         deterministic=True, cache=cache,
                                         cache_index=0)
        new_pool = []
        for (pk, pv), (ck, cv) in zip(pool, cache):
            pk = lax.dynamic_update_slice(pk, ck, (slot, 0, 0, 0))
            pv = lax.dynamic_update_slice(pv, cv, (slot, 0, 0, 0))
            new_pool.append((pk, pv))
        last = logits[0, true_len - 1, :]
        # Token destined for position true_len: fold_in(seed, true_len) —
        # the same stream the decode step continues at true_len + 1.
        key = jax.random.fold_in(jax.random.key(seed), true_len)
        tok, _ = _sample_token(last[None, :], key[None],
                               temperature=temp, top_k=top_k, top_p=top_p)
        return new_pool, tok[0]

    def _decode_fn(self, params, pool, tokens, pos, temps, top_ks, top_ps,
                   seeds):
        """One batched token step over ALL slots at per-row frontiers."""
        import jax

        from nanosandbox_tpu.sample import _sample_token

        self.trace_counts["decode"] += 1
        logits, pool = self.model.apply({"params": params}, tokens[:, None],
                                        deterministic=True, cache=pool,
                                        cache_index=pos)
        keys = jax.vmap(
            lambda s, q: jax.random.fold_in(jax.random.key(s), q)
        )(seeds, pos + 1)
        nxt, _ = _sample_token(logits[:, 0, :], keys, temperature=temps,
                               top_k=top_ks, top_p=top_ps)
        return pool, nxt

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def submit(self, prompt: Sequence[int], max_new_tokens: int, *,
               temperature: float = 0.0, top_k: int = 0, top_p: float = 1.0,
               seed: int = 0, eos_id: Optional[int] = None) -> int:
        """Queue one request; returns its id. Fixed-shape admission rules
        are enforced here so a bad request fails at submit, not as a
        mid-flight surprise."""
        prompt = tuple(int(t) for t in prompt)
        if not prompt:
            raise ValueError("empty prompt (encode at least one token)")
        if max_new_tokens < 0:
            raise ValueError(
                f"max_new_tokens must be >= 0, got {max_new_tokens}")
        if len(prompt) > self.sched.buckets[-1]:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds the largest prefill "
                f"bucket {self.sched.buckets[-1]}")
        total = len(prompt) + max_new_tokens
        if total > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens "
                f"({max_new_tokens}) = {total} exceeds the per-slot KV "
                f"length {self.max_len}; long-context decode belongs to "
                "sample.py's windowed path")
        rid = next(self._rid)
        req = Request(rid=rid, prompt=prompt, max_new_tokens=max_new_tokens,
                      temperature=float(temperature), top_k=int(top_k),
                      top_p=float(top_p), seed=int(seed), eos_id=eos_id)
        if max_new_tokens == 0:
            self._pending_results.append(
                Result(rid=rid, prompt=prompt, tokens=[],
                       finish_reason="length"))
            return rid
        self.sched.enqueue(req)
        return rid

    def has_work(self) -> bool:
        return bool(self._active or self.sched.queued
                    or self._pending_results)

    def step(self) -> List[Result]:
        """Admit as many queued requests as slots allow (prefill +
        first token), then run one batched decode step over every slot.
        Returns the requests that finished during this step."""
        import jax.numpy as jnp

        finished, self._pending_results = self._pending_results, []

        # Backfill free slots mid-flight; a request finishing on its
        # prefill token immediately frees its slot for the next in line.
        while (adm := self.sched.next_admission()) is not None:
            req, slot, bucket = adm
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :len(req.prompt)] = req.prompt
            self._pool, tok0 = self._prefill(
                self.params, self._pool, jnp.asarray(padded),
                jnp.asarray(len(req.prompt), jnp.int32),
                jnp.asarray(slot, jnp.int32),
                jnp.asarray([req.temperature], jnp.float32),
                jnp.asarray([req.top_k], jnp.int32),
                jnp.asarray([req.top_p], jnp.float32),
                jnp.asarray(req.seed, jnp.int32))
            self.admitted += 1
            state = _Active(req=req, slot=slot, tokens=[int(tok0)])
            self._pos[slot] = len(req.prompt)
            self._tok[slot] = state.tokens[-1]
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._topp[slot] = req.top_p
            self._seed[slot] = req.seed
            self._active[slot] = state
            done = self._maybe_finish(state)
            if done is not None:
                finished.append(done)

        if self._active:
            self._pool, nxt = self._decode(
                self.params, self._pool,
                jnp.asarray(self._tok), jnp.asarray(self._pos),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                jnp.asarray(self._topp), jnp.asarray(self._seed))
            self.steps += 1
            nxt = np.asarray(nxt)
            for slot, state in list(self._active.items()):
                state.tokens.append(int(nxt[slot]))
                self._pos[slot] += 1
                self._tok[slot] = int(nxt[slot])
                done = self._maybe_finish(state)
                if done is not None:
                    finished.append(done)
        return finished

    def drain(self) -> List[Result]:
        """Run step() until queue and slots are empty; all results."""
        out: List[Result] = []
        while self.has_work():
            out.extend(self.step())
        return out

    def stats(self) -> dict:
        return {
            "num_slots": self.num_slots,
            "max_len": self.max_len,
            "prefill_buckets": list(self.sched.buckets),
            "active": len(self._active),
            "queued": self.sched.queued,
            "free_slots": self.sched.free_slots,
            "admitted": self.admitted,
            "completed": self.completed,
            "decode_steps": self.steps,
            "trace_counts": dict(self.trace_counts),
        }

    # ------------------------------------------------------------------
    def _maybe_finish(self, state: _Active) -> Optional[Result]:
        req = state.req
        reason = None
        if req.eos_id is not None and state.tokens[-1] == req.eos_id:
            reason = "eos"
        elif len(state.tokens) >= req.max_new_tokens:
            reason = "length"
        if reason is None:
            return None
        del self._active[state.slot]
        self.sched.release(state.slot)
        # Park the idle row at a harmless frontier; its garbage decode
        # writes stay inside its own slot row.
        self._pos[state.slot] = 0
        self._tok[state.slot] = 0
        self._temp[state.slot] = 0.0
        self._topk[state.slot] = 0
        self._topp[state.slot] = 1.0
        self._seed[state.slot] = 0
        self.completed += 1
        return Result(rid=req.rid, prompt=req.prompt, tokens=state.tokens,
                      finish_reason=reason)
