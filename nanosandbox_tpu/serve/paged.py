"""Host-side block accounting for the paged KV pool: a free-list
allocator over fixed-size KV blocks plus a refcounted radix prefix
cache (vLLM's PagedAttention block tables, Kwon et al. 2023; SGLang's
RadixAttention prefix reuse, Zheng et al. 2024 — scoped to this
engine's fixed-shape discipline).

Division of labor: everything HERE is plain Python over block *ids* —
no jax import, testable without a device, cheap enough to run between
decode steps (the scheduler.py contract). The device side (the actual
(num_blocks, H, page, D) K/V arrays, the (num_slots, max_blocks) block
table threaded through the slot state, and the compiled gather/scatter
programs) lives in models/gpt.py + serve/engine.py.

Allocation contract — full reservation at admission:

  A request is admitted with ALL the blocks it can ever touch:
  ceil((prompt_len + max_new_tokens) / page) minus the blocks a prefix
  hit shares. Elasticity comes from reserving a request's ACTUAL need
  instead of the dense pool's worst-case (num_slots, max_len) row, and
  from shared prefix blocks being refcounted rather than copied — not
  from mid-decode growth. The decode hot loop therefore still uploads
  NOTHING from the host (the block table is written once, at admit),
  and pool exhaustion mid-decode is impossible by construction: an
  admitted request never asks for another block, so the no-deadlock
  argument is one line. Requests whose need cannot be met wait in the
  FIFO queue (counted as stall steps) instead of deadlocking; a request
  that could NEVER fit (need > the whole pool) is rejected at submit.

Prefix sharing — block-aligned, copy-on-write by refcount:

  The radix cache is a trie keyed on PAGE-sized token blocks. Only FULL
  prompt blocks are shareable, so the shared region of any request is
  block-aligned and the frontier block — the only block anything ever
  writes — is always private. "Copy-on-write" therefore degenerates to
  copy-on-extend at block granularity: a shared (refcount > 1) block is
  never written by anyone; divergence after a shared prefix lands in
  each request's own private blocks, and the partially-matching tail
  block of a prompt simply re-prefills into a private block (that
  re-prefill IS the copy). A hit is additionally capped one token short
  of the prompt so the suffix forward always has >= 1 token to compute
  the first sampled logit from (the SGLang trick).

  On release the request's full prompt blocks are DONATED to the trie
  (refcount 0, evictable) instead of freed — the next request sharing
  that prefix skips their prefill entirely. Eviction is LRU over
  refcount-zero leaves, run lazily when an allocation comes up short.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple


def blocks_for(n_positions: int, page: int) -> int:
    """ceil(n_positions / page): blocks covering n_positions tokens."""
    return -(-n_positions // page)


def _block_digest(prev: bytes, block: Sequence[int]) -> bytes:
    """Chained 64-bit fingerprint of one page-sized token block given
    its parent chain's digest — the ONE hash both prefix_digests() and
    RadixPrefixCache.digests() use, so a router matching request chains
    against replica summaries can never drift from the trie itself."""
    h = hashlib.blake2b(digest_size=8)
    h.update(prev)
    h.update(",".join(str(int(t)) for t in block).encode())
    return h.digest()


def prefix_digests(tokens: Sequence[int], page: int) -> List[str]:
    """Chained per-block fingerprints of a token sequence's FULL
    page-sized blocks (the shareable region of a prompt — exactly what
    the radix cache can ever hold). Entry i fingerprints the whole
    prefix tokens[:(i+1)*page], so two sequences share a digest iff
    they share that block-aligned prefix, and a router-side index needs
    only MEMBERSHIP (a contiguous walk down the request's own chain) to
    estimate a replica's resident hit. Digests are hex strings — stable
    across processes, JSON-safe for Result/flight/HTTP reporting (the
    ISSUE 15 fleet-router contract)."""
    out: List[str] = []
    prev = b""
    for i in range(len(tokens) // page):
        prev = _block_digest(prev, tokens[i * page:(i + 1) * page])
        out.append(prev.hex())
    return out


class _Node:
    """One cached block: a trie edge keyed by its page of token ids."""

    __slots__ = ("key", "block", "parent", "children", "refs",
                 "last_use", "locks")

    def __init__(self, key, block: int, parent):
        self.key = key                  # tuple of page token ids
        self.block = block              # pool block id holding its K/V
        self.parent = parent
        self.children: Dict[tuple, "_Node"] = {}
        self.refs = 0                   # in-flight requests sharing it
        self.last_use = 0
        self.locks = 0                  # refs>0 nodes in this subtree
        #                                 (incl. self): evictable while 0


class RadixPrefixCache:
    """Refcounted radix/trie prefix cache over page-sized token blocks.

    Pure block-id bookkeeping (the K/V bytes stay in the device pool,
    untouched — a cached block's content is immutable because nothing
    ever writes a non-private block). Single-threaded by design, like
    the engine that owns it."""

    def __init__(self, page: int):
        if page < 1:
            raise ValueError(f"page must be >= 1, got {page}")
        self.page = page
        self.root = _Node(key=None, block=-1, parent=None)
        self._nodes: List[_Node] = []   # every live node (small pools)
        self._tick = 0
        self._evictable = 0             # nodes with locks == 0

    def __len__(self) -> int:
        return len(self._nodes)

    def _keys(self, prompt: Sequence[int], n_blocks: int) -> List[tuple]:
        p = self.page
        return [tuple(prompt[i * p:(i + 1) * p]) for i in range(n_blocks)]

    def match(self, prompt: Sequence[int]) -> List[_Node]:
        """The resident chain of FULL prompt blocks, longest first-match
        walk from the root — capped one token short of the prompt so the
        suffix prefill always has a token to run (module docstring).
        Touches the chain's LRU clocks."""
        usable = (len(prompt) - 1) // self.page
        path: List[_Node] = []
        node = self.root
        for key in self._keys(prompt, usable):
            child = node.children.get(key)
            if child is None:
                break
            path.append(child)
            node = child
        self._tick += 1
        for n in path:
            n.last_use = self._tick
        return path

    def _lock_chain(self, node: _Node, d: int) -> None:
        """Propagate a refs 0<->1 transition up the ancestor chain.
        ``locks`` counts pinned (refs>0) nodes per subtree, so a node
        is evictable exactly while locks == 0 — maintained here (O(depth)
        per transition) so ``evictable()`` is an O(1) read on the
        engine's per-step preemption probe instead of an O(all-nodes)
        pinned-set walk."""
        n = node
        while n is not None:
            n.locks += d
            if n.parent is not None:    # root is not a pool block
                if d > 0 and n.locks == 1:
                    self._evictable -= 1
                elif d < 0 and n.locks == 0:
                    self._evictable += 1
            n = n.parent

    def acquire(self, node: _Node) -> None:
        node.refs += 1
        if node.refs == 1:
            self._lock_chain(node, +1)

    def release(self, node: _Node) -> None:
        if node.refs <= 0:
            raise RuntimeError("prefix-cache refcount underflow")
        node.refs -= 1
        if node.refs == 0:
            self._lock_chain(node, -1)

    def insert_chain(self, prompt: Sequence[int], blocks: Sequence[int],
                     start: int) -> List[int]:
        """Donate ``blocks[start:full]`` (a finished request's private
        full-prompt blocks; blocks[:start] are its hit chain, already in
        the trie) as cached nodes. Returns the block ids NOT absorbed —
        duplicates of chains another request donated first — which the
        caller must free (their content is identical: same tokens, same
        deterministic prefill)."""
        full = len(prompt) // self.page
        keys = self._keys(prompt, full)
        node = self.root
        for key in keys[:start]:
            node = node.children[key]   # the hit chain: must exist
        dup: List[int] = []
        self._tick += 1
        for i in range(start, full):
            child = node.children.get(keys[i])
            if child is None:
                child = _Node(keys[i], blocks[i], node)
                node.children[keys[i]] = child
                self._nodes.append(child)
                self._evictable += 1    # refs 0, no children: locks 0
            else:
                dup.append(blocks[i])
            child.last_use = self._tick
            node = child
        return dup

    def evictable(self) -> int:
        """Blocks reclaimable RIGHT NOW by repeated leaf eviction: nodes
        with refs == 0 and no pinned descendant (a refs-0 parent of a
        pinned child must stay — the child's prefix walk crosses it).
        An O(1) counter read: the engine's preemption check probes this
        every step under block pressure, so the count is maintained
        incrementally on the refs 0<->1 transitions (_lock_chain) and
        audited against the O(n) recompute in pool _audit."""
        return self._evictable

    def evict(self, want: int) -> List[int]:
        """Free up to ``want`` blocks, LRU refcount-zero leaves first
        (a parent becomes a leaf once its children are gone). Returns
        the freed block ids."""
        freed: List[int] = []
        while len(freed) < want:
            victim = None
            for n in self._nodes:
                if n.refs == 0 and not n.children and (
                        victim is None or n.last_use < victim.last_use):
                    victim = n
            if victim is None:
                break
            del victim.parent.children[victim.key]
            self._nodes.remove(victim)
            self._evictable -= 1        # victims are locks-0 by choice
            freed.append(victim.block)
        return freed

    def cached_blocks(self) -> List[int]:
        return [n.block for n in self._nodes]

    def digests(self) -> List[str]:
        """Chained fingerprints of every resident chain prefix — one
        per trie node, computed with the same _block_digest chain
        prefix_digests() applies to a prompt, so ``set(digests())``
        answers "would this prompt's block i hit here" by membership
        alone. This is the authoritative summary the fleet router's
        approximate per-replica index refreshes from
        (GET /debug/prefix_summary): anything the LRU evicted since the
        last refresh drops out of the set, which is the router index's
        staleness eviction."""
        out: List[str] = []
        stack = [(child, b"") for child in self.root.children.values()]
        while stack:
            node, prev = stack.pop()
            d = _block_digest(prev, node.key)
            out.append(d.hex())
            for c in node.children.values():
                stack.append((c, d))
        return out


@dataclass
class Allocation:
    """One admitted request's block-level state: the full table row the
    device side scatters, and the host bookkeeping release() unwinds."""
    prompt: tuple
    table: List[int]                 # hit chain + private blocks, in order
    n_hit: int                       # leading shared (trie) blocks
    nodes: List[_Node] = field(default_factory=list)   # acquired chain


class BlockPool:
    """Free-list allocator + radix prefix cache over ``num_blocks`` KV
    blocks of ``page`` positions each.

    States (the serve_kv_pool_blocks gauge): ``free`` blocks sit on the
    free list; ``cached`` blocks live in the trie with refcount 0
    (reclaimable); ``live`` blocks are referenced by an in-flight
    request — privately owned, or shared trie blocks with refs > 0.
    The three partition [0, num_blocks) at all times (pinned by the
    fuzz test's invariant checker)."""

    def __init__(self, num_blocks: int, page: int, *,
                 prefix_cache: bool = True):
        if num_blocks < 1:
            raise ValueError(f"num_blocks must be >= 1, got {num_blocks}")
        self.num_blocks = num_blocks
        self.page = page
        self._free: List[int] = list(range(num_blocks - 1, -1, -1))
        self.cache = RadixPrefixCache(page) if prefix_cache else None
        # One-entry match memo: the engine probes a request's hit twice
        # per admission attempt (suffix-bucket wave key, then admit) —
        # same prompt, same instant, no mutation between — so the second
        # trie walk is pure waste. Invalidated by anything that changes
        # match results (insertion, eviction, reset).
        self._match_memo: Optional[tuple] = None
        # Telemetry ledger (plain ints; the engine mirrors them into the
        # obs registry at collection time — zero hot-loop cost).
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.stall_steps = 0            # wave heads deferred on blocks
        self.evicted_blocks = 0
        self.requests = 0
        self.private_blocks_allocated = 0
        self.adoptions = 0              # migrated chains re-admitted here
        self.adopted_blocks = 0         # blocks filled by KV transfer

    # -- sizing -----------------------------------------------------------
    def blocks_needed(self, prompt_len: int, max_new: int) -> int:
        return blocks_for(prompt_len + max_new, self.page)

    @property
    def free_blocks(self) -> int:
        return len(self._free)

    def _match(self, prompt: Sequence[int]) -> List[_Node]:
        key = tuple(prompt)
        if self._match_memo is not None and self._match_memo[0] == key:
            return self._match_memo[1]
        chain = self.cache.match(key) if self.cache is not None else []
        self._match_memo = (key, chain)
        return chain

    def match_len(self, prompt: Sequence[int]) -> int:
        """Non-mutating-ownership probe: tokens a hit would skip (the
        engine buckets the SUFFIX with this before committing blocks)."""
        return len(self._match(prompt)) * self.page

    # -- admit / release --------------------------------------------------
    def _take(self, want: int) -> Optional[List[int]]:
        """Pop ``want`` free blocks, evicting LRU cached blocks to cover
        a shortfall; None (and nothing consumed) when even eviction
        cannot cover it."""
        short = want - len(self._free)
        if short > 0 and self.cache is not None:
            freed = self.cache.evict(short)
            if freed:
                self.evicted_blocks += len(freed)
                self._free.extend(freed)
                self._match_memo = None
        if want > len(self._free):
            return None
        return [self._free.pop() for _ in range(want)]

    def admit(self, prompt: Sequence[int],
              max_new: int) -> Optional[Allocation]:
        """Match the prompt against the prefix cache, acquire the hit
        chain, and allocate private blocks for everything else (suffix
        prefill + the whole generation budget). None when the pool
        cannot cover it — the caller leaves the request queued.

        The chain is acquired BEFORE the private allocation: _take's
        shortfall eviction reclaims refcount-zero blocks, and an
        unpinned just-matched chain is exactly that — evicting it would
        hand the same block out as both "shared prefix, never written"
        and "fresh private, about to be written" (an aliased table and
        silently corrupt K/V). Pinning first means a pool that can only
        fit the request by sacrificing its own hit DEFERS instead —
        correctness over one admission's latency."""
        nodes = self._match(prompt)
        n_hit = len(nodes)
        total = self.blocks_needed(len(prompt), max_new)
        for n in nodes:
            self.cache.acquire(n)
        fresh = self._take(total - n_hit)
        if fresh is None:
            for n in nodes:
                self.cache.release(n)
            self.stall_steps += 1
            return None
        hit = n_hit * self.page
        self.hit_tokens += hit
        self.miss_tokens += len(prompt) - hit
        self.requests += 1
        self.private_blocks_allocated += total - n_hit
        return Allocation(prompt=tuple(prompt),
                          table=[n.block for n in nodes] + fresh,
                          n_hit=n_hit, nodes=nodes)

    def adopt_chain(self, prompt: Sequence[int],
                    max_new: int) -> Optional[Tuple[Allocation, List[int]]]:
        """Admit a MIGRATED request's block chain (ISSUE 16): the
        disaggregated handoff's receiving half. The prompt's K/V
        already exists on the source pool; this side allocates the same
        footprint a local admission would (prompt chain + the full
        generation budget) and tells the caller which chain positions
        must be FILLED by a block copy before the request may decode.

        Returns ``(alloc, copy)`` where ``copy`` lists the chain
        positions (indices into ``alloc.table``) covering the prompt
        that this pool does NOT already hold as a radix hit — every
        such position's block is private and unwritten, and the caller
        copies the source pool's block at the same chain position into
        ``alloc.table[i]`` for each ``i``. A local prefix hit shrinks
        the copy exactly like it shrinks a local prefill: hit blocks
        are bit-identical to the source's by the chained-digest
        argument (same tokens, same positions, paged layout is
        position-independent), so skipping their transfer is free
        bandwidth. The partial tail block (prompt not page-aligned) IS
        copied — its K/V for [0, len(prompt)) was fully written by the
        source's prefill. Generation-region blocks beyond the prompt
        are never copied: nothing was ever written there.

        None when the pool cannot cover the footprint (the caller
        leaves the migration parked in limbo — adoption backpressure,
        counted as a stall like any deferred admission). Refcount and
        free/cached/owned partition invariants are admit()'s
        unchanged: check() holds after adoption exactly as after a
        local admission."""
        a = self.admit(prompt, max_new)
        if a is None:
            return None
        copy = list(range(a.n_hit, blocks_for(len(prompt), self.page)))
        self.adoptions += 1
        self.adopted_blocks += len(copy)
        return a, copy

    def release(self, alloc: Allocation, *,
                generated: Sequence[int] = (),
                donate: bool = True) -> int:
        """Unwind one finished request: deref its hit chain, donate its
        full prompt blocks to the trie, free the rest (generated-region
        blocks + donation duplicates). Returns the number of blocks
        newly donated (the preempt flight event's ledger).

        ``generated`` (ISSUE 13, the preemption path) extends the
        donation to the request's full prompt+generated blocks, so a
        preempted victim's resume — prompt' = prompt + tokens-so-far —
        is a prefix HIT over its own decode-written K/V instead of a
        full re-prefill. The LAST generated token's K/V is excluded: it
        was only ever sampled, never consumed as a decode input, so its
        position is unwritten (and the radix match's one-token-short
        cap means no future hit could use it anyway).

        ``donate=False`` frees everything instead — the unwind for a
        PARTIALLY-prefilled allocation (a chunked prefill interrupted
        by a crash): donating a half-written prompt chain would serve
        garbage K/V as a prefix hit."""
        for n in alloc.nodes:
            self.cache.release(n)
        if self.cache is None or not donate:
            self._free.extend(alloc.table[alloc.n_hit:])
            return 0
        tokens = tuple(alloc.prompt) + tuple(generated)
        written = len(tokens) - (1 if generated else 0)
        full = written // self.page
        dup = self.cache.insert_chain(tokens[:full * self.page],
                                      alloc.table, alloc.n_hit)
        self._free.extend(dup)
        self._free.extend(alloc.table[full:])
        self._match_memo = None
        return full - alloc.n_hit - len(dup)

    def reset_cache(self) -> None:
        """Evict every cached block back to the free list and zero the
        hit/miss ledger. Callers must ensure no live allocation holds
        cache references (the engine checks it is idle first) — with
        refs all zero, repeated leaf eviction drains the whole trie."""
        if self.cache is None:
            return
        self._free.extend(self.cache.evict(self.num_blocks))
        self._match_memo = None
        self.hit_tokens = 0
        self.miss_tokens = 0

    def reset_ledger(self) -> None:
        """Zero the telemetry counters (hit/miss tokens, stalls,
        evictions, per-request allocation means) WITHOUT touching
        allocation state — benchmarks call this between warmup and the
        timed workload so hit rates and capacity describe the measured
        traffic (the engine's reset_latency_stats contract)."""
        self.hit_tokens = 0
        self.miss_tokens = 0
        self.stall_steps = 0
        self.evicted_blocks = 0
        self.requests = 0
        self.private_blocks_allocated = 0
        self.adoptions = 0
        self.adopted_blocks = 0

    # -- introspection ----------------------------------------------------
    def stats(self) -> dict:
        free = len(self._free)
        cached = evictable = 0
        if self.cache is not None:
            cached = len(self.cache)
            evictable = self.cache.evictable()
        seen = self.hit_tokens + self.miss_tokens
        return {
            "num_blocks": self.num_blocks,
            "page": self.page,
            "free": free,
            # Gauge semantics (class docstring): cached = trie blocks at
            # refs 0 (reclaimable), live = everything a request holds.
            "cached": evictable,
            "live": self.num_blocks - free - evictable,
            "trie_blocks": cached,
            "prefix_hit_tokens": self.hit_tokens,
            "prefix_miss_tokens": self.miss_tokens,
            "prefix_hit_rate": (self.hit_tokens / seen) if seen else None,
            "block_stall_steps": self.stall_steps,
            "evicted_blocks": self.evicted_blocks,
            "adoptions": self.adoptions,
            "adopted_blocks": self.adopted_blocks,
            "mean_private_blocks_per_request": (
                self.private_blocks_allocated / self.requests
                if self.requests else None),
        }

    def debug(self, live: Sequence[tuple] = ()) -> dict:
        """The GET /debug/kvpool view: block-state partition,
        fragmentation, trie occupancy, and the per-request block story.

        ``live`` is the engine's [(rid, used_positions, Allocation)]
        snapshot — the pool tracks block IDS, only the engine knows how
        many positions each reservation has actually written, which is
        what internal fragmentation is about: full reservation at
        admission trades elasticity for positions reserved-but-unwritten
        until the request's generation catches up."""
        out = self.stats()
        reserved_pos = used_pos = 0
        per_request = []
        for rid, used, alloc in live:
            reserved = len(alloc.table) * self.page
            reserved_pos += reserved
            used_pos += min(used, reserved)
            per_request.append({
                "rid": rid, "blocks": len(alloc.table),
                "hit_blocks": alloc.n_hit,
                "reserved_positions": reserved,
                "used_positions": min(used, reserved),
            })
        out["fragmentation"] = {
            # reserved-but-unwritten fraction of live reservations (the
            # full-reservation contract's cost, shrinking as requests
            # decode into their budgets)...
            "internal": (1.0 - used_pos / reserved_pos
                         if reserved_pos else 0.0),
            "reserved_positions": reserved_pos,
            "used_positions": used_pos,
            # ...and the pool-level free fraction (paged pools never
            # fragment externally — any free block serves any request).
            "free_frac": len(self._free) / self.num_blocks,
        }
        trie: dict = {"enabled": self.cache is not None}
        if self.cache is not None:
            depths: Dict[int, int] = {}
            # list() snapshot: debug() is read from handler threads
            # while the loop thread inserts/evicts nodes, and iterating
            # the live list would crash mid-mutation (the engine debug
            # discipline — torn reads yield a stale view, never a
            # crash). Parent pointers of an evicted node stay intact,
            # so the depth walk below is safe on the snapshot.
            for n in list(self.cache._nodes):
                d = 0
                p = n.parent
                while p is not None:
                    d += 1
                    p = p.parent
                depths[d] = depths.get(d, 0) + 1
            trie.update({
                "nodes": len(self.cache),
                "cached_tokens": len(self.cache) * self.page,
                "evictable_blocks": self.cache.evictable(),
                "depth_histogram": {str(k): v
                                    for k, v in sorted(depths.items())},
                "max_depth": max(depths) if depths else 0,
            })
        out["trie"] = trie
        out["live_requests"] = per_request
        return out

    def check(self, live_allocs: Sequence[Allocation] = ()) -> None:
        """Invariant audit (tests call this after every fuzz step): the
        free list, the trie, and the live allocations' private blocks
        partition [0, num_blocks) with no overlap; refcounts equal the
        number of live allocations holding each node."""
        free = list(self._free)
        assert len(set(free)) == len(free), "free-list duplicate"
        cached = self.cache.cached_blocks() if self.cache else []
        assert len(set(cached)) == len(cached), "trie duplicate block"
        assert not set(free) & set(cached), "block both free and cached"
        owned: List[int] = []
        refs: Dict[int, int] = {}
        for a in live_allocs:
            owned.extend(a.table[a.n_hit:])
            for n in a.nodes:
                refs[id(n)] = refs.get(id(n), 0) + 1
        assert len(set(owned)) == len(owned), "block owned twice"
        assert not set(owned) & set(free), "live block on free list"
        assert not set(owned) & set(cached), "private block in trie"
        every = set(free) | set(cached) | set(owned)
        assert every == set(range(self.num_blocks)), (
            f"pool partition broken: {len(every)}/{self.num_blocks}")
        if self.cache is not None:
            for n in self.cache._nodes:
                assert n.refs == refs.get(id(n), 0), (
                    "refcount drift", n.key, n.refs, refs.get(id(n), 0))
            # The O(1) evictable counter vs the O(n) pinned-set walk it
            # replaced — any _lock_chain bookkeeping drift fails here.
            pinned: set = set()
            for n in self.cache._nodes:
                if n.refs > 0:
                    while n is not None and id(n) not in pinned:
                        pinned.add(id(n))
                        n = n.parent
            slow = sum(1 for n in self.cache._nodes
                       if id(n) not in pinned)
            assert self.cache._evictable == slow, (
                "evictable-counter drift", self.cache._evictable, slow)
