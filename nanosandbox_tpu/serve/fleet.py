"""In-process N-replica serving fleet behind the prefix-affinity
router (ISSUE 15).

``Fleet`` owns N independent Engines ("r0".."rN-1", each with its own
metrics registry and a replica-NAMESPACED flight recorder) and one
PrefixAffinityRouter, and exposes an Engine-shaped
submit()/step()/drain() surface — so tests and ``bench.py
--mode=fleet`` can measure affinity-vs-random routing, drain/failover
behavior and fleet goodput on one host with zero network in the loop.
The asyncio HTTP front tier (serve/http.py RouterFrontend) and the k8s
router Deployment drive the SAME router class over real replica pods;
this harness is the policy's test bench, not a fork of it.

Contract highlights:

  * Routing: submit() fingerprints the prompt (paged.prefix_digests),
    routes by prefix affinity with load/brownout/readiness fallback,
    and forwards every scheduling field (deadline_s, slo_class,
    priority — the PR 13 classes pass through untouched).

  * Identity: a fleet request's id is its first attempt's namespaced
    engine rid ("r0:17"). Engine ledgers merge into one exactly-once-
    analyzable JSONL (merged_flight_jsonl); the fleet's own recorder
    adds ``route`` / ``failover`` / ``replica_down`` events, never a
    terminal — terminals belong to the engines, one per namespaced rid
    even across a failover (fuzz-pinned).

  * Failure: the ``replica_down`` fault site (serve/faults.py) hard-
    kills a replica mid-traffic (Engine.abort_all — its in-flight
    requests come back as terminal 'failed' Results). The fleet
    salvages each victim's tokens and re-routes it to a surviving
    replica as prompt' = prompt + tokens-so-far with the remaining
    budget — the engine-recovery restitch argument, one level up —
    so greedy outputs are token-identical to an undisturbed run and
    every fleet request still reaches exactly one fleet Result.

  * Backoff: retry_after_s() is the MIN over ready replicas of the
    per-replica (queue-mass-weighted) estimate — the retrying client
    will be routed to the best replica, so the binding hint is the
    minimum, not whichever replica happened to shed (satellite 2);
    retry_info() adds the ready-replica-set size the 429 body names.

No compiled program and no host sync is added anywhere: the fleet is
pure host-side orchestration over engines whose compile sets stay
byte-identical to solo engines (pinned by test).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from nanosandbox_tpu.obs import FlightRecorder, MetricRegistry
from nanosandbox_tpu.serve.engine import (Engine, EngineFailedError,
                                          Result)
from nanosandbox_tpu.serve.paged import prefix_digests
from nanosandbox_tpu.serve.router import (NoReadyReplicaError,
                                          PrefixAffinityRouter)


@dataclass
class _FleetReq:
    """One client request's fleet-side journal across attempts."""
    fleet_rid: str               # first attempt's namespaced rid
    replica: str                 # current replica name
    engine_rid: int              # current engine-local rid
    prompt: tuple                # the ORIGINAL prompt
    max_new: int                 # the ORIGINAL budget
    kwargs: dict                 # sampling/SLO fields, re-sent on failover
    tokens: List[int] = field(default_factory=list)  # salvaged so far
    submit_t: float = 0.0
    deadline_s: Optional[float] = None
    attempts: int = 1


class Fleet:
    """N engine replicas + a prefix-affinity router, submit/step/drain.

    Parameters mirror Engine where they overlap; everything in
    ``engine_kw`` (num_slots, max_len, paged, kv_page_size, scan_k,
    prefill_chunk, ...) is applied to every replica identically —
    interchangeable replicas are what make greedy outputs replica-
    independent (pinned by test).

    n_replicas : engines to build ("r0".."rN-1").
    tp : per-replica tensor-parallel degree. tp > 1 gives each replica
        its OWN disjoint device slice (replica i shards over devices
        [i*tp, (i+1)*tp)) — n_replicas * tp devices required.
    affinity : False = affinity-blind routing — seeded uniform-random
        over the ready set (the bench comparison twin).
    faults : a FaultPlan consulted for the fleet-level ``replica_down``
        site once per step (engine-level plans go through engine_kw).
    failover : re-route a dead replica's in-flight requests (default);
        False turns a replica loss into client-visible 'failed'
        Results, the pre-router behavior.
    max_failovers : re-routes ONE request may consume (default 2).
        The cap is a poison-pill fence: if some request reliably kills
        whatever replica serves it (engine.submit rejects the known
        vector — out-of-vocab ids — but the class is open-ended),
        unbounded failover would walk it through the whole fleet,
        converting one bad request into a total outage. Past the cap
        the request surfaces as 'failed' and the fleet keeps serving.
    summary_interval : steps between authoritative router-index
        refreshes from each replica's prefix_summary() (staleness
        eviction); per-request digest reports flow continuously.
    metrics : registry for the ROUTER families + fleet counters
        (default: fresh). Replica engines always get their own — their
        families would collide in one registry by design (engine.py's
        one-engine-per-registry rule).
    """

    def __init__(self, model, params, *, n_replicas: int = 2,
                 tp: int = 1, affinity: bool = True, faults=None,
                 failover: bool = True, max_failovers: int = 2,
                 summary_interval: int = 8,
                 load_weight: float = 8.0, brownout_weight: float = 64.0,
                 index_cap: int = 8192, metrics: Optional[MetricRegistry]
                 = None, seed: int = 0, **engine_kw):
        if n_replicas < 1:
            raise ValueError(f"n_replicas must be >= 1, got {n_replicas}")
        self.n_replicas = int(n_replicas)
        self.failover = bool(failover)
        self.max_failovers = int(max_failovers)
        self.summary_interval = max(1, int(summary_interval))
        self.faults = faults
        if faults is not None:
            faults.arm(0)
        names = [f"r{i}" for i in range(self.n_replicas)]
        meshes: List = [None] * self.n_replicas
        if tp > 1:
            import jax

            from nanosandbox_tpu.parallel.mesh import make_mesh

            devs = jax.devices()
            if len(devs) < self.n_replicas * tp:
                raise ValueError(
                    f"{self.n_replicas} replicas at tp={tp} need "
                    f"{self.n_replicas * tp} devices, have {len(devs)}")
            meshes = [make_mesh(1, 1, tp, 1,
                                devices=devs[i * tp:(i + 1) * tp])
                      for i in range(self.n_replicas)]
        self.replicas: Dict[str, Engine] = {}
        for name, mesh in zip(names, meshes):
            kw = dict(engine_kw)
            if tp > 1:
                kw.update(tp=tp, tp_mesh=mesh)
            self.replicas[name] = Engine(
                model, params, metrics=MetricRegistry(),
                flight=FlightRecorder(namespace=name), **kw)
        eng0 = self.replicas[names[0]]
        self.paged = eng0.paged and eng0.block_pool.cache is not None
        self.page = eng0.kv_page_size if self.paged else 0
        self.metrics = metrics if metrics is not None else MetricRegistry()
        self.router = PrefixAffinityRouter(
            names, page=self.page or 16, index_cap=index_cap,
            load_weight=load_weight, brownout_weight=brownout_weight,
            affinity=affinity, metrics=self.metrics, seed=seed)
        self._c_failovers = self.metrics.counter(
            "serve_fleet_failovers_total",
            "In-flight requests re-routed off a failed replica.")
        self._c_downs = self.metrics.counter(
            "serve_fleet_replica_down_total",
            "Replicas hard-killed (the replica_down fault site).")
        # The fleet's OWN flight recorder: route/failover/replica_down
        # events over already-namespaced rids; terminals stay with the
        # engines (one per namespaced rid, even across failover).
        self.flight = FlightRecorder()
        self._requests: Dict[str, _FleetReq] = {}
        self._by_engine: Dict[Tuple[str, int], str] = {}
        self._draining: Dict[str, bool] = {n: False for n in names}
        self.steps = 0
        self.submitted = 0
        self.completed = 0
        self.failovers = 0
        self.replica_downs = 0
        self._refresh_health()

    # ------------------------------------------------------------ health
    def _replica_state(self, name: str) -> Tuple[bool, str]:
        eng = self.replicas[name]
        if eng.failed:
            return False, f"failed: {eng.quarantine_cause or 'unknown'}"
        if eng.quarantined:
            return False, f"quarantined: {eng.quarantine_cause}"
        if self._draining[name]:
            return False, "draining"
        return True, "ok"

    def _refresh_health(self) -> None:
        """One in-process health interval: every step() refreshes, so
        a drain/quarantine/failure leaves the rotation within one step
        — the 'one health interval' contract the HTTP tier honors with
        its poll period."""
        for name, eng in self.replicas.items():
            ready, reason = self._replica_state(name)
            level = eng.brownout.level if eng.brownout is not None else 0
            self.router.update_replica(
                name, ready=ready, reason=reason,
                queued=eng.sched.queued, active=len(eng._active),
                brownout=level)

    def drain_replica(self, name: str) -> None:
        """Take one replica out of rotation (the in-process twin of
        POST /drain): no new routes, in-flight work keeps stepping to
        completion. Idempotent."""
        self._draining[name] = True
        self._refresh_health()

    def undrain_replica(self, name: str) -> None:
        self._draining[name] = False
        self._refresh_health()

    # ------------------------------------------------------------ submit
    def _chain(self, prompt: Sequence[int]) -> List[str]:
        return (prefix_digests(prompt, self.page) if self.paged else [])

    def submit(self, prompt: Sequence[int], max_new_tokens: int,
               **kwargs) -> str:
        """Route + submit one request; returns its fleet id (the
        namespaced engine rid of the first attempt, "rN:M"). Raises
        NoReadyReplicaError when the whole fleet is out of rotation
        (503 upstream) and propagates the engine's admission
        ValueErrors (400)."""
        prompt = tuple(int(t) for t in prompt)
        self._refresh_health()
        chain = self._chain(prompt)
        dec = self.router.route(chain)
        eng = self.replicas[dec.replica]
        rid = eng.submit(prompt, max_new_tokens, **kwargs)
        # Optimistic index insert: the routed prompt's chain WILL be
        # resident at this replica once its prefill lands, so a
        # same-prefix follower in the same burst must route here too —
        # without this, affinity only forms after the first request
        # FINISHES, and a burst of shared-prefix traffic sprays across
        # the fleet by load. The index is approximate by contract; the
        # periodic summary refresh corrects any optimism a shed/failed
        # request left behind.
        if chain:
            self.router.observe_digests(dec.replica, chain)
        fleet_rid = f"{dec.replica}:{rid}"
        self.submitted += 1
        self.flight.record("route", rid=fleet_rid, replica=dec.replica,
                           reason=dec.reason,
                           est_hit_tokens=dec.est_hit_tokens,
                           candidates=dec.candidates)
        self._requests[fleet_rid] = _FleetReq(
            fleet_rid=fleet_rid, replica=dec.replica, engine_rid=rid,
            prompt=prompt, max_new=int(max_new_tokens),
            kwargs=dict(kwargs), submit_t=time.monotonic(),
            deadline_s=kwargs.get("deadline_s"))
        self._by_engine[(dec.replica, rid)] = fleet_rid
        return fleet_rid

    # -------------------------------------------------------------- step
    def has_work(self) -> bool:
        return any(eng.has_work() for eng in self.replicas.values())

    def step(self) -> List[Result]:
        """Step every replica once, collect finished engine Results,
        re-route failures, and return the FLEET-terminal Results
        (rid = fleet id, prompt = the original prompt, tokens stitched
        across attempts)."""
        out: List[Result] = []
        if self.faults is not None:
            f = self.faults.fire("replica_down", self.steps)
            if f is not None:
                self._kill_one(out)
        for name, eng in self.replicas.items():
            for res in eng.step():
                self._absorb(name, res, out)
        self.steps += 1
        self._refresh_health()
        if self.steps % self.summary_interval == 0:
            for name, eng in self.replicas.items():
                if not eng.failed:
                    self.router.refresh_summary(
                        name, eng.prefix_summary()["digests"])
            # The summary is the DONATED set; chains still in flight
            # (queued or decoding — their blocks are private until
            # release) are nonetheless committed to this replica, so
            # the optimistic submit-time entries are restored on top of
            # the authoritative base.
            for fr in self._requests.values():
                if not self.replicas[fr.replica].failed:
                    self.router.observe_digests(
                        fr.replica, self._chain(fr.prompt))
        return out

    def drain(self) -> List[Result]:
        out: List[Result] = []
        while self.has_work():
            out.extend(self.step())
        return out

    def _kill_one(self, out: List[Result]) -> None:
        """The replica_down site: hard-kill the busiest live replica
        (deterministic — max active requests, name as tie-break) via
        abort_all, then absorb its terminal 'failed' Results so the
        failover path re-routes them THIS step."""
        live = [(len(self.replicas[n]._active), n)
                for n in self.replicas
                if not self.replicas[n].failed]
        if not live:
            return
        _, victim = max(live, key=lambda t: (t[0], t[1]))
        self.replica_downs += 1
        self._c_downs.inc()
        self.flight.record("replica_down", replica=victim,
                           step=self.steps)
        eng = self.replicas[victim]
        results = eng.abort_all("replica_down")
        self.router.update_replica(victim, ready=False,
                                   reason="failed: replica_down")
        self.router.forget(victim)
        for res in results:
            self._absorb(victim, res, out)

    def _absorb(self, name: str, res: Result, out: List[Result]) -> None:
        """Map one engine Result back to its fleet request: terminal,
        or a failover re-route when the replica died under it."""
        fleet_rid = self._by_engine.pop((name, res.rid), None)
        if fleet_rid is None:
            return                       # warmup traffic / direct submits
        fr = self._requests[fleet_rid]
        if (res.finish_reason == "failed" and self.failover
                and self._try_failover(fr, res, out)):
            return
        del self._requests[fleet_rid]
        self.completed += 1
        out.append(Result(
            rid=fleet_rid, prompt=fr.prompt,
            tokens=fr.tokens + list(res.tokens),
            finish_reason=res.finish_reason,
            prefix_digest=res.prefix_digest))
        if res.prefix_digest:
            self.router.observe_digests(name, list(res.prefix_digest))

    def _try_failover(self, fr: _FleetReq, res: Result,
                      out: List[Result]) -> bool:
        """Re-route one dead replica's victim: salvage its tokens,
        resubmit prompt' = prompt + tokens-so-far with the remaining
        budget on a surviving replica (fold_in(seed, abs_position) row
        keys make the resumed greedy stream token-identical — the
        recovery restitch argument, one replica over). May resolve the
        request to a terminal itself (deadline expired mid-failover,
        budget already met) — those land in ``out`` directly. False =
        no failover possible (caller emits the 'failed' terminal)."""
        salvaged = fr.tokens + list(res.tokens)
        remaining = fr.max_new - len(salvaged)
        now = time.monotonic()
        if fr.attempts > self.max_failovers:
            # Poison-pill fence (constructor docstring): this request
            # has already consumed its re-routes — surface the failure
            # instead of walking it through the rest of the fleet.
            return False
        if fr.deadline_s is not None and now - fr.submit_t >= fr.deadline_s:
            # The client stopped waiting mid-failover: terminal 'shed'
            # at the FLEET level (429 upstream), no engine resubmit.
            # The dead replica's 'failed' is the rid's one terminal;
            # this event is fleet bookkeeping, not a second one.
            self.flight.record("failover_shed", rid=fr.fleet_rid,
                               step=self.steps, tokens=len(salvaged))
            del self._requests[fr.fleet_rid]
            self.completed += 1
            out.append(Result(
                rid=fr.fleet_rid, prompt=fr.prompt, tokens=salvaged,
                finish_reason="shed"))
            return True
        if remaining <= 0:
            # Budget already met by salvage: nothing to resubmit — the
            # request is DONE, just unlucky about where its last token
            # was computed.
            del self._requests[fr.fleet_rid]
            self.completed += 1
            out.append(Result(
                rid=fr.fleet_rid, prompt=fr.prompt, tokens=salvaged,
                finish_reason="length"))
            return True
        self._refresh_health()
        try:
            dec = self.router.route(
                self._chain(fr.prompt + tuple(salvaged)),
                exclude=(fr.replica,), failover=True)
        except NoReadyReplicaError:
            return False
        kwargs = dict(fr.kwargs)
        if fr.deadline_s is not None:
            kwargs["deadline_s"] = max(fr.deadline_s
                                       - (now - fr.submit_t), 0.001)
        eng = self.replicas[dec.replica]
        try:
            rid = eng.submit(fr.prompt + tuple(salvaged), remaining,
                             **kwargs)
        except (ValueError, EngineFailedError):
            return False
        self.failovers += 1
        self._c_failovers.inc()
        self.flight.record(
            "failover", rid=fr.fleet_rid, step=self.steps,
            dead=fr.replica, replica=dec.replica,
            new_rid=f"{dec.replica}:{rid}", tokens=len(salvaged),
            reason=dec.reason, est_hit_tokens=dec.est_hit_tokens)
        fr.tokens = salvaged
        fr.replica = dec.replica
        fr.engine_rid = rid
        fr.attempts += 1
        self._by_engine[(dec.replica, rid)] = fr.fleet_rid
        return True

    # ------------------------------------------------------------- views
    def retry_after_s(self, slo_class: Optional[str] = None) -> float:
        """Fleet backoff hint: the MIN over ready replicas of the
        per-replica queue-mass-weighted estimate (each replica already
        scales its own hint by the backlog at-or-above the class) —
        the retrying client gets routed to the best replica, so the
        minimum is the binding number, not whichever replica shed."""
        ready = self.router.ready_replicas()
        if not ready:
            return 1.0
        return min(self.replicas[n].retry_after_s(slo_class=slo_class)
                   for n in ready)

    def retry_info(self, slo_class: Optional[str] = None) -> dict:
        """The 429/503 body fields: the aggregate hint plus the size of
        the ready replica set it was computed over (satellite 2)."""
        ready = self.router.ready_replicas()
        return {"retry_after_s": self.retry_after_s(slo_class),
                "replica_set": len(ready)}

    def stats(self) -> dict:
        return {
            "n_replicas": self.n_replicas,
            "router": self.router.stats(),
            "steps": self.steps,
            "submitted": self.submitted,
            "completed": self.completed,
            "in_flight": len(self._requests),
            "failovers": self.failovers,
            "replica_downs": self.replica_downs,
            "retry": self.retry_info(),
            "replicas": {
                name: {
                    "ready": self._replica_state(name)[0],
                    "reason": self._replica_state(name)[1],
                    "active": len(eng._active),
                    "queued": eng.sched.queued,
                    "completed": eng.completed,
                    "tokens_generated": eng.tokens_generated,
                    "prefix_hit_tokens": (
                        eng.block_pool.hit_tokens
                        if eng.block_pool is not None else 0),
                    "prefix_miss_tokens": (
                        eng.block_pool.miss_tokens
                        if eng.block_pool is not None else 0),
                } for name, eng in self.replicas.items()
            },
        }

    def merged_flight_events(self) -> List[dict]:
        """Every replica's ledger plus the fleet's own, one stream
        ordered by wall clock — rids are replica-namespaced, so the
        merge stays exactly-once analyzable (the satellite-1 pin)."""
        events: List[dict] = []
        for eng in self.replicas.values():
            events.extend(eng.flight.events())
        events.extend(self.flight.events())
        events.sort(key=lambda e: e["wall"])
        return events

    def merged_flight_jsonl(self) -> str:
        import json

        lines = [json.dumps(e, sort_keys=True)
                 for e in self.merged_flight_events()]
        return "\n".join(lines) + ("\n" if lines else "")

    def reset_latency_stats(self) -> None:
        """Benchmark hygiene, fleet-wide (the Engine contract)."""
        for eng in self.replicas.values():
            eng.reset_latency_stats()
        self.flight.clear()

    def reset_prefix_caches(self) -> None:
        """Cold-cache baseline: flush every replica's radix cache AND
        the router's picture of them (idle replicas only, the engine
        contract)."""
        for name, eng in self.replicas.items():
            eng.reset_prefix_cache()
            self.router.forget(name)
