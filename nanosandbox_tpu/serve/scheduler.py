"""Fixed-shape admission control for the continuous-batching engine.

TPU programs are compiled per shape, so the scheduler's job is to make
an arbitrary request stream look like a SMALL, CLOSED set of shapes:

  * decode always runs the full (num_slots,) batch — idle slots ride
    along as padding rows whose outputs are ignored (one compiled
    decode step, ever);
  * prefill pads each prompt up to a bucket from a fixed ladder, so at
    most len(buckets) prefill programs exist no matter what lengths
    arrive.

Everything here is plain host-side Python (no jax import): it must be
cheap enough to run between every decode step and testable without a
device.
"""

from __future__ import annotations

from collections import deque
from typing import Deque, List, Optional, Tuple


def default_buckets(max_len: int, min_bucket: int = 16) -> List[int]:
    """Power-of-two prefill ladder capped at max_len: 16, 32, ... max_len.

    Doubling bounds padding waste at <2x while keeping the compile set
    logarithmic in max_len — the standard fixed-shape serving trade.
    max_len itself is always the last rung so every admissible prompt
    (length <= max_len) has a bucket."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    buckets: List[int] = []
    b = min(min_bucket, max_len)
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


class SlotScheduler:
    """FIFO queue + free-slot pool + bucket ladder.

    Owns no device state: the Engine asks it which request goes into
    which slot (``next_admission``) and tells it when a slot frees
    (``release``). FIFO keeps admission starvation-free — a long prompt
    at the head is never jumped by later short ones, matching the
    reference trainer's strictly-ordered batch semantics rather than a
    throughput-greedy reorder."""

    def __init__(self, num_slots: int, buckets: List[int]):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if not buckets or sorted(buckets) != list(buckets):
            raise ValueError(f"buckets must be a sorted non-empty list, "
                             f"got {buckets!r}")
        self.num_slots = num_slots
        self.buckets = list(buckets)
        self._free: List[int] = list(range(num_slots - 1, -1, -1))
        self._queue: Deque = deque()

    # -- queue side --
    def enqueue(self, item) -> None:
        self._queue.append(item)

    @property
    def queued(self) -> int:
        return len(self._queue)

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest ladder rung >= prompt_len."""
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill "
            f"bucket {self.buckets[-1]}")

    def next_admission(self) -> Optional[Tuple[object, int, int]]:
        """(queued item, slot, prefill bucket) when both a queued request
        and a free slot exist, else None. Pops both."""
        if not self._queue or not self._free:
            return None
        item = self._queue.popleft()
        slot = self._free.pop()
        return item, slot, self.bucket_for(len(item.prompt))

    def release(self, slot: int) -> None:
        if slot in self._free:
            raise ValueError(f"slot {slot} released twice")
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        self._free.append(slot)
