"""Fixed-shape admission control for the continuous-batching engine.

TPU programs are compiled per shape, so the scheduler's job is to make
an arbitrary request stream look like a SMALL, CLOSED set of shapes:

  * decode always runs the full (num_slots,) batch — idle slots ride
    along as padding rows whose outputs are ignored (one compiled
    decode step, ever);
  * prefill pads each prompt up to a bucket from a fixed ladder, so at
    most len(buckets) prefill programs exist no matter what lengths
    arrive.

Everything here is plain host-side Python (no jax import): it must be
cheap enough to run between every decode step and testable without a
device.

Scan-chunk fencing (ISSUE 12): under the engine's multi-token decode
scan, step() IS the chunk boundary — every wave this scheduler forms
is popped, staged and committed between two chunk dispatches, never
mid-chunk, and a slot freed by a chunk's retire re-enters the free
list before the next wave forms. Admission therefore fences on chunk
boundaries by construction; the one behavioral consequence is that
queue-wait accounting stays in DISPATCH units (a "step" of waiting
spans up to scan_k tokens), which the engine's queue-wait histogram
documents.
"""

from __future__ import annotations

from bisect import insort
from collections import deque
from typing import Deque, Dict, List, Optional, Tuple


def admit_ladder(num_slots: int) -> List[int]:
    """Power-of-two admission-wave sizes: 1, 2, 4, ..., num_slots.

    A batched prefill runs one (k, L_bucket) program per wave; padding the
    wave size k up this ladder bounds the prefill compile set at
    len(admit_ladder) * len(buckets) instead of num_slots * len(buckets).
    num_slots itself is always the last rung so a full-batch wave never
    pads past capacity."""
    if num_slots < 1:
        raise ValueError(f"num_slots must be >= 1, got {num_slots}")
    ladder: List[int] = []
    k = 1
    while k < num_slots:
        ladder.append(k)
        k *= 2
    ladder.append(num_slots)
    return ladder


def default_buckets(max_len: int, min_bucket: int = 16) -> List[int]:
    """Power-of-two prefill ladder capped at max_len: 16, 32, ... max_len.

    Doubling bounds padding waste at <2x while keeping the compile set
    logarithmic in max_len — the standard fixed-shape serving trade.
    max_len itself is always the last rung so every admissible prompt
    (length <= max_len) has a bucket."""
    if max_len < 1:
        raise ValueError(f"max_len must be >= 1, got {max_len}")
    buckets: List[int] = []
    b = min(min_bucket, max_len)
    while b < max_len:
        buckets.append(b)
        b *= 2
    buckets.append(max_len)
    return buckets


class SlotScheduler:
    """Priority queue + free-slot pool + bucket ladder.

    Owns no device state: the Engine asks it which request goes into
    which slot (``next_admission``) and tells it when a slot frees
    (``release``). Ordering is priority-then-FIFO (ISSUE 13): items
    with a higher ``.priority`` attribute sit ahead of lower ones, and
    WITHIN a priority class admission is strictly FIFO — a long prompt
    at the head of its class is never jumped by later short ones, so
    the PR 1 starvation-free guarantee survives per class (items
    without a ``.priority`` all share one class and the queue degrades
    to the original pure FIFO). Cross-class starvation of low-priority
    traffic under sustained high-priority load is deliberate: the
    engine's brownout ladder sheds that traffic explicitly rather than
    letting it rot in the queue."""

    def __init__(self, num_slots: int, buckets: List[int]):
        if num_slots < 1:
            raise ValueError(f"num_slots must be >= 1, got {num_slots}")
        if not buckets or sorted(buckets) != list(buckets):
            raise ValueError(f"buckets must be a sorted non-empty list, "
                             f"got {buckets!r}")
        self.num_slots = num_slots
        self.buckets = list(buckets)
        self.admit_buckets = admit_ladder(num_slots)
        self._free: List[int] = list(range(num_slots - 1, -1, -1))
        # One FIFO deque per priority class: enqueue and requeue_front
        # are O(1) however deep the backlog grows (a single sorted
        # deque would pay an O(n) positional insert per submit under
        # exactly the sustained-overload regime this scheduler
        # targets). ``_negprios`` holds the negated priorities of the
        # NON-EMPTY classes in ascending order, i.e. priorities
        # descending — classes are few (a handful of SLO tiers),
        # requests are many, so the per-class bookkeeping is noise.
        self._queues: Dict[int, Deque] = {}
        self._negprios: List[int] = []
        # Plain int depth, maintained by every mutation path: HTTP
        # handler threads read it (stats/metrics/retry hints) while the
        # loop thread mutates, and an int read is atomic where
        # iterating _queues.values() live would race class
        # creation/removal.
        self._n = 0
        # Migration limbo (ISSUE 16): requests whose prefill completed
        # but whose block chain has not yet been adopted by a decode
        # tier. Items here hold NO slot (the engine released the row at
        # export) but DO hold prefill-side blocks, so they are real
        # outstanding work: the deadline sweep must see them (the
        # drain_expired fix below) and /debug/scheduler must show them.
        # FIFO — migrations hand off in export order.
        self._limbo: Deque = deque()

    # -- queue side --
    @staticmethod
    def _prio(item) -> int:
        return getattr(item, "priority", 0)

    def _class(self, p: int) -> Deque:
        """The class deque for priority ``p``, created (and its
        priority registered) on first use."""
        q = self._queues.get(p)
        if q is None:
            q = self._queues[p] = deque()
            insort(self._negprios, -p)
        return q

    def _drop_if_empty(self, p: int) -> None:
        if not self._queues[p]:
            del self._queues[p]
            self._negprios.remove(-p)

    def enqueue(self, item) -> None:
        """Append to the TAIL of the item's priority class (higher
        ``.priority`` classes drain first, FIFO within a class) —
        O(1) regardless of queue depth."""
        self._class(self._prio(item)).append(item)
        self._n += 1

    def peek_head(self):
        """The next item admission would consider (None when empty) —
        the engine's preemption check reads its deadline/priority
        without popping. Loop-thread only (like every mutator): it
        indexes live class state with no snapshot."""
        if not self._negprios:
            return None
        return self._queues[-self._negprios[0]][0]

    def pop_head(self):
        """Pop the queue head — the chunked-prefill lane claims the
        head OUTSIDE the wave machinery (its prefill spans multiple
        engine steps, so it cannot ride a one-dispatch wave)."""
        p = -self._negprios[0]
        item = self._queues[p].popleft()
        self._n -= 1
        self._drop_if_empty(p)
        return item

    def take_slot(self) -> int:
        """Claim one free slot (the chunked-prefill twin of the slot
        pop inside next_admission_wave). Caller must have checked
        ``free_slots``."""
        return self._free.pop()

    @property
    def queued(self) -> int:
        return self._n

    @property
    def free_slots(self) -> int:
        return len(self._free)

    # -- migration limbo (ISSUE 16) --
    @property
    def limbo(self) -> int:
        return len(self._limbo)

    def park_limbo(self, item) -> None:
        """Park an exported request awaiting decode-tier adoption
        (tail — migrations hand off in export order)."""
        self._limbo.append(item)

    def park_limbo_front(self, item) -> None:
        """Re-park at the HEAD — the adoption-side backpressure path
        (decode tier had no slot/blocks this pump): the oldest export
        must stay first in line or a stalled decode tier inverts the
        handoff order and starves the head into a deadline shed."""
        self._limbo.appendleft(item)

    def pop_limbo(self):
        """Claim the oldest parked export for transfer (None when
        empty). Loop-thread only, like every mutator."""
        return self._limbo.popleft() if self._limbo else None

    def limbo_items(self) -> List:
        """Snapshot of the limbo queue, oldest first (the
        /debug/scheduler view; same C-level-copy safety argument as
        queued_items)."""
        return list(self._limbo)

    def bucket_for(self, prompt_len: int) -> int:
        """Smallest prefill-length rung >= prompt_len."""
        for b in self.buckets:
            if prompt_len <= b:
                return b
        raise ValueError(
            f"prompt length {prompt_len} exceeds the largest prefill "
            f"bucket {self.buckets[-1]}")

    def rung_for(self, wave_size: int) -> int:
        """Smallest admission-wave rung >= wave_size — bucket_for's twin
        on the other ladder, kept here so BOTH fixed-shape admission
        policies live in one file."""
        for k in self.admit_buckets:
            if wave_size <= k:
                return k
        raise ValueError(
            f"wave size {wave_size} exceeds num_slots {self.num_slots}")

    def next_admission(self) -> Optional[Tuple[object, int, int]]:
        """(queued item, slot, prefill bucket) when both a queued request
        and a free slot exist, else None. Pops both. A wave of one — the
        single-admission convenience view over next_admission_wave, so
        there is exactly ONE admission code path to keep correct."""
        wave = self.next_admission_wave(max_items=1)
        if wave is None:
            return None
        (item,), (slot,), bucket = wave
        return item, slot, bucket

    def next_admission_wave(self, max_items: Optional[int] = None, *,
                            bucket_of=None, admit=None,
                            ) -> Optional[Tuple[List, List[int], int]]:
        """(items, slots, bucket): the maximal FIFO *prefix* of the queue
        whose prompts share the head's prefill bucket, capped at the free
        slots (and optionally at ``max_items``). One batched
        (len(items), bucket) prefill admits the whole wave.

        Strictly a prefix — a queued request with a different bucket ends
        the wave rather than being jumped over, so admission order stays
        FIFO (the starvation-free guarantee above) even though same-bucket
        runs now land together.

        ``bucket_of(item) -> int`` overrides the default
        bucket_for(len(item.prompt)) wave key — the paged engine buckets
        the prefix-cache-adjusted SUFFIX, so two requests sharing a
        resident system prompt land in one small-suffix wave.

        ``admit(item) -> bool`` is the paged engine's block-availability
        gate, called BEFORE the pop and expected to commit resources on
        True: a False return fences the wave with the item still queued
        (FIFO again — nothing behind a block-starved head jumps it, which
        with full-reservation allocation is what makes pool exhaustion a
        wait instead of a deadlock)."""
        if not self._negprios or not self._free:
            return None
        key = bucket_of if bucket_of is not None else (
            lambda item: self.bucket_for(len(item.prompt)))
        bucket = key(self.peek_head())
        items: List = []
        slots: List[int] = []
        while (self._negprios and self._free
               and (max_items is None or len(items) < max_items)):
            head = self.peek_head()
            if key(head) != bucket:
                break
            if admit is not None and not admit(head):
                break
            items.append(self.pop_head())
            slots.append(self._free.pop())
        if not items:
            return None
        return items, slots, bucket

    def drain_expired(self, expired) -> List:
        """Remove and return every queued OR limbo-parked item for which
        ``expired(item)`` is true, preserving FIFO order of the
        survivors — the engine's deadline shed: a request whose deadline
        passed while it waited is dropped (with a terminal ``shed``
        outcome) instead of burning slots on an answer its client
        stopped waiting for. The migration limbo is swept with the SAME
        predicate (ISSUE 16 fix — previously only the admission queue
        was): a request parked mid-migration holds prefill-side blocks
        and an unserved deadline exactly like a queued one, and a
        stalled decode tier must not turn limbo into a leak. The caller
        distinguishes queue items from limbo records by type and
        releases a limbo victim's blocks WITHOUT donation. Cheap when
        nothing expired: the scan is attribute checks only and each
        queue is rebuilt only on a hit."""
        shed: List = []
        if any(expired(item)
               for q in self._queues.values() for item in q):
            for np in list(self._negprios):
                p = -np
                kept: Deque = deque()
                for item in self._queues[p]:
                    (shed if expired(item) else kept).append(item)
                self._queues[p] = kept
                self._drop_if_empty(p)
            self._n -= len(shed)
        if self._limbo and any(expired(item) for item in self._limbo):
            kept_l: Deque = deque()
            for item in self._limbo:
                (shed if expired(item) else kept_l).append(item)
            self._limbo = kept_l
        return shed

    def requeue_front(self, items: List) -> None:
        """Push recovered in-flight requests back at the HEAD of their
        priority class, preserving the given (original-admission) order
        among themselves — the crash-recovery and preemption
        re-admission path: victims must not queue behind same-class
        traffic that arrived after them (that would invert FIFO and
        starve a deadline-carrying victim into a shed), but they must
        not jump HIGHER-priority traffic either — the queue stays
        sorted by priority, which the engine's preemption check relies
        on (``peek_head`` must be the most urgent queued request; a
        recovered batch victim parked at the absolute head would
        head-of-line-block an interactive request without being
        preemptible). O(1) per item."""
        for item in reversed(list(items)):
            self._class(self._prio(item)).appendleft(item)
            self._n += 1

    def queue_mass(self, priority: int) -> Tuple[int, int]:
        """(at_or_above, strictly_above) queued counts relative to
        ``priority`` — the backlog a request of that class waits behind
        (retry-after hints, fleet-router load weighting). Safe from
        HTTP handler threads: per-class len() reads on a snapshot of
        the class list, never a live deque iteration."""
        ahead = jumps = 0
        for np in list(self._negprios):
            q = self._queues.get(-np)
            if q is None:
                continue
            n = len(q)
            if -np >= priority:
                ahead += n
            if -np > priority:
                jumps += n
        return ahead, jumps

    def queued_items(self) -> List:
        """Snapshot of the queue, head first (the /debug/scheduler
        view; callers must not mutate the items). Safe from HTTP
        handler threads while the loop mutates: the class list and
        each class deque are copied at C level under the GIL (the
        ``list(deque)`` idiom the single-queue version relied on),
        never iterated live, and a class deleted mid-snapshot is
        simply skipped."""
        out: List = []
        for np in list(self._negprios):
            q = self._queues.get(-np)
            if q is not None:
                out.extend(list(q))
        return out

    def release(self, slot: int) -> None:
        if slot in self._free:
            raise ValueError(f"slot {slot} released twice")
        if not 0 <= slot < self.num_slots:
            raise ValueError(f"slot {slot} out of range")
        self._free.append(slot)
