"""Trainer.run() end-to-end on a real TPU (round-2 VERDICT missing #1).

The CPU suite proves the loop's logic; this tier proves the PRODUCT on
the hardware that matters: a short but complete `Trainer.run()` with
on-chip eval, Orbax checkpoint save/restore, auto-resume (the k8s
restart-with-identity path), TensorBoard/JSONL metrics, and a
jax.profiler trace window — the same capabilities the reference
exercises on its device in
/root/reference/notebooks/colab_nanoGPT_companion.ipynb:96-116.

Run manually on a TPU host: python -m pytest tests_tpu/ -q
"""

import glob
import json
import os

import pytest

from nanosandbox_tpu.config import TrainConfig
from nanosandbox_tpu.data.prepare import prepare_english_prose_dataset
from nanosandbox_tpu.train import Trainer


@pytest.fixture(scope="module")
def real_data(tmp_path_factory):
    root = tmp_path_factory.mktemp("data")
    prepare_english_prose_dataset(str(root / "english_prose_char"))
    return str(root)


def _cfg(data_dir: str, out_dir: str, **kw) -> TrainConfig:
    base = dict(
        data_dir=data_dir, dataset="english_prose_char", out_dir=out_dir,
        n_layer=4, n_head=4, n_embd=256, block_size=256, batch_size=16,
        dropout=0.0, max_iters=30, lr_decay_iters=30, warmup_iters=5,
        eval_interval=10, eval_iters=2, log_interval=5,
        learning_rate=1e-3, compute_dtype="bfloat16",
        attention_impl="auto", always_save_checkpoint=True)
    base.update(kw)
    return TrainConfig(**base)


def test_trainer_run_full_loop_on_tpu(real_data, tmp_path):
    out = str(tmp_path / "out")
    cfg = _cfg(real_data, out, profile_steps="12:14")
    result = Trainer(cfg).run()

    assert result["iter_num"] == 30
    # Real data, real chip: the loss must actually move.
    assert result["final_val_loss"] < 3.0
    # Orbax checkpoints exist (periodic + final).
    steps = sorted(int(os.path.basename(p))
                   for p in glob.glob(os.path.join(out, "ckpt", "*")))
    assert 30 in steps and len(steps) >= 2
    # Metrics: JSONL curve + TensorBoard events.
    (jsonl,) = glob.glob(os.path.join(out, "runs", "*", "metrics.jsonl"))
    rows = [json.loads(l) for l in open(jsonl)]
    assert any("eval/val_loss" in r for r in rows)
    assert glob.glob(os.path.join(out, "runs", "*", "events.out.tfevents*"))
    # Profiler trace window was captured on-device (start_trace creates
    # the directory unconditionally — only the xplane proto proves the
    # traced window contained work).
    assert glob.glob(os.path.join(out, "runs", "profile", "**",
                                  "*.xplane.pb"), recursive=True)


def test_trainer_auto_resume_on_tpu(real_data, tmp_path):
    """Kill-and-resume: a second run with init_from=auto continues from
    the latest Orbax checkpoint instead of restarting (the StatefulSet
    crash-restart contract, SURVEY.md §5)."""
    out = str(tmp_path / "out")
    r1 = Trainer(_cfg(real_data, out, max_iters=20,
                      lr_decay_iters=40)).run()
    assert r1["iter_num"] == 20

    r2 = Trainer(_cfg(real_data, out, max_iters=40, lr_decay_iters=40,
                      init_from="auto")).run()
    assert r2["iter_num"] == 40
    steps = sorted(int(os.path.basename(p))
                   for p in glob.glob(os.path.join(out, "ckpt", "*")))
    assert 20 in steps and 40 in steps


def test_memory_report_sane_on_tpu(real_data, tmp_path):
    """--memory_report on hardware (round-5 VERDICT next #6): XLA's
    compile-time analysis must return nonzero, mutually-consistent byte
    totals on the real backend — the preflight the 760M/1.5B configs
    gate on was CPU-only proven before."""
    cfg = _cfg(real_data, str(tmp_path / "out"))
    trainer = Trainer(cfg)
    mem = trainer.memory_report()
    assert mem, "TPU backend returned no memory analysis"
    assert mem["params_bytes"] > 0
    assert mem["state_bytes"] > mem["params_bytes"]  # params + Adam + batch
    assert mem["temp_bytes"] > 0
    assert mem["total_bytes"] > mem["temp_bytes"]
    # Order of magnitude: a 4L/256d model's step must fit comfortably
    # under a v5e's 16 GB yet cost at least a few MB.
    assert 1 << 20 < mem["total_bytes"] < 8 << 30


def test_train_step_with_dropout_rbg_on_tpu(real_data, tmp_path):
    """One compiled train step of the production regularized path
    (in-kernel flash dropout + rng_impl=rbg) on hardware, asserting
    finite loss and per-call determinism of the jitted step (two
    identically-initialized states + the same rng must produce the same
    loss; the step donates its state, so determinism is checked across
    two independent init_state() copies — same seed, same values)."""
    cfg = _cfg(real_data, str(tmp_path / "out"), dropout=0.1,
               rng_impl="rbg", max_iters=2, eval_interval=0)
    trainer = Trainer(cfg)
    state_a = trainer.init_state()
    state_b = trainer.init_state()
    step, _ = trainer.compiled_steps()
    loader = trainer.make_loader("train", prefetch=False)
    xb, yb = next(loader)
    loader.close()
    x, y = trainer.to_global(xb), trainer.to_global(yb)
    rng = trainer.train_rng(0)
    _, m1 = step(state_a, x, y, rng)
    _, m2 = step(state_b, x, y, rng)
    loss = float(m1["loss"])
    assert loss == loss and 0 < loss < 20
    assert loss == float(m2["loss"]), "rbg dropout step not deterministic"
