"""Real-TPU test tier (run manually on a chip; NOT part of the CPU suite).

The CPU suite (tests/) can only exercise Pallas kernels in interpret mode,
which skips Mosaic layout checks — exactly how round 1 shipped a kernel
that failed lowering on hardware with a green suite (VERDICT.md weak #5).
This tier compiles the real kernels. Usage, on a machine with a TPU:

    python -m pytest tests_tpu/ -q

Skips everything (collection-time) when no TPU backend is available, so
accidentally running it on CI is a no-op, not a failure.
"""

import jax
import pytest


def pytest_collection_modifyitems(config, items):
    try:
        on_tpu = jax.default_backend() == "tpu"
    except Exception:
        on_tpu = False
    if not on_tpu:
        skip = pytest.mark.skip(reason="requires a real TPU backend")
        for item in items:
            item.add_marker(skip)
