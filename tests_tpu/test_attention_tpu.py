"""Compiled (Mosaic) Pallas flash attention vs XLA on a real TPU.

These are the hardware analogues of tests/test_attention.py's
interpret-mode checks: they force real compilation, so BlockSpec/layout
regressions that interpret mode cannot see fail here (VERDICT.md weak #5;
the reference's accelerator path worked as shipped,
/root/reference/notebooks/colab_nanoGPT_companion.ipynb:96-116 — ours
must prove the same on its own hardware).

Tolerances: the TPU MXU runs f32 matmuls as bf16 passes at default
precision, so two correct implementations differ at the ~1e-3 level; the
gradient comparisons are much tighter because both backwards accumulate
in f32 over identical block structures.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from nanosandbox_tpu.ops.attention import (causal_attention, flash_attention,
                                           pallas_compile_probe,
                                           xla_attention)


def rand_qkv(rng, B=2, H=4, T=1024, D=64, dtype=jnp.bfloat16):
    q, k, v = (jnp.asarray(rng.normal(size=(B, H, T, D)) * 0.5, dtype)
               for _ in range(3))
    return q, k, v


def test_probe_compiles():
    assert pallas_compile_probe(), (
        "custom Pallas flash kernel must lower on TPU")


@pytest.mark.parametrize("T,D,dtype", [
    (1024, 64, jnp.bfloat16),     # GPT-2 124M shape
    (1024, 64, jnp.float32),
    (96, 32, jnp.float32),        # T-padding path
    (8192, 64, jnp.bfloat16),     # long context
])
def test_flash_fwd_matches_xla_compiled(T, D, dtype):
    rng = np.random.default_rng(0)
    q, k, v = rand_qkv(rng, T=T, D=D, dtype=dtype)
    out = jax.jit(lambda q, k, v: flash_attention(
        q, k, v, True, None, False))(q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=1e-2)


@pytest.mark.parametrize("dtype", [jnp.bfloat16, jnp.float32])
def test_flash_grads_match_xla_compiled(dtype):
    rng = np.random.default_rng(1)
    q, k, v = rand_qkv(rng, dtype=dtype)

    def loss_flash(q, k, v):
        return flash_attention(q, k, v, True, None, False).astype(
            jnp.float32).mean()

    def loss_ref(q, k, v):
        return xla_attention(q, k, v, causal=True).astype(jnp.float32).mean()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, gr):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(b32).max(), 1e-8)
        assert np.abs(a32 - b32).max() / scale < 1e-2


def test_auto_dispatch_selects_pallas_on_tpu():
    rng = np.random.default_rng(2)
    q, k, v = rand_qkv(rng, B=1, H=2, T=256, D=64)
    out = causal_attention(q, k, v, impl="auto")
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=5e-2)


def test_pallas_jax_impl_any_T():
    """The library kernel path must accept non-128-aligned T (the
    Trainer's init dummy batch uses T=8; round-1 weak #6)."""
    rng = np.random.default_rng(3)
    q, k, v = rand_qkv(rng, B=1, H=2, T=8, D=64, dtype=jnp.float32)
    out = causal_attention(q, k, v, impl="pallas_jax")
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=1e-2)


# -- round 3: flash_attention_lse + ring flash blocks + remat policy ------

def test_flash_lse_compiles_and_matches(T=1024):
    """The ring's block primitive must lower on real Mosaic (interpret
    mode cannot see BlockSpec/layout regressions)."""
    from nanosandbox_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(4)
    q, k, v = rand_qkv(rng, B=1, H=2, T=T, D=64)
    out, lse = jax.jit(lambda q, k, v: flash_attention_lse(
        q, k, v, True, None, False))(q, k, v)
    s = (np.asarray(q, np.float32) * (64 ** -0.5)) @ np.asarray(
        k, np.float32).transpose(0, 1, 3, 2)
    s = np.where(np.tril(np.ones((T, T), bool))[None, None], s, -1e30)
    ref_lse = np.log(np.exp(s - s.max(-1, keepdims=True)).sum(-1)) + \
        s.max(-1)
    np.testing.assert_allclose(np.asarray(lse), ref_lse, atol=2e-2)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=1e-2)


def test_ring_flash_single_device_degenerate():
    """sp=1 ring with pallas blocks on the chip: one diag flash call,
    output must match plain flash. (Multi-device rings are covered on the
    8-virtual-device CPU mesh; 1 chip is all this host has.)"""
    from nanosandbox_tpu.ops.ring_attention import ring_attention_sharded
    from nanosandbox_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(mesh_dp=1, devices=jax.devices()[:1])
    rng = np.random.default_rng(5)
    q, k, v = rand_qkv(rng, B=1, H=2, T=1024, D=64)
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, block_impl="pallas"))(q, k, v)
    ref = xla_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=1e-2)


def test_remat_save_attention_compiles_on_tpu():
    """remat + save_attention policy + real Mosaic kernel: the tagged
    residual save path must compile and differentiate on hardware."""
    import jax.numpy as jnp

    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT

    cfg = GPTConfig(n_layer=2, n_head=2, n_embd=128, block_size=512,
                    vocab_size=256, dropout=0.0, attention_impl="pallas",
                    remat=True, remat_policy="save_attention")
    model = GPT(cfg)
    x = jnp.zeros((2, 512), jnp.int32)
    params = model.init(jax.random.key(0), x)["params"]

    def loss(p):
        return (model.apply({"params": p}, x).astype(jnp.float32) ** 2).mean()

    g = jax.jit(jax.grad(loss))(params)
    assert all(bool(jnp.isfinite(l).all()) for l in jax.tree.leaves(g))


def test_flash_lse_gradients_compile_with_dlse_on_tpu():
    """The has_dlse backward is its own Mosaic program (W=2*LANES stacked
    stats operand, lane-offset column reads) — compile and check it on
    real hardware, not just interpret mode. A loss consuming BOTH outputs
    forces a nonzero dlse cotangent through the kernels."""
    from nanosandbox_tpu.ops.attention import flash_attention_lse

    rng = np.random.default_rng(6)
    q, k, v = rand_qkv(rng, B=1, H=2, T=1024, D=64, dtype=jnp.float32)
    w = jnp.asarray(rng.normal(size=(1, 2, 1024)), jnp.float32)

    def loss_flash(q, k, v):
        out, lse = flash_attention_lse(q, k, v, True, None, False)
        return (out.astype(jnp.float32) ** 2).sum() + (lse * w).sum()

    def loss_ref(q, k, v):
        sm = 64 ** -0.5
        s = jnp.einsum("bhqd,bhkd->bhqk", q * sm, k)
        T = q.shape[2]
        s = jnp.where(jnp.tril(jnp.ones((T, T), bool))[None, None], s, -1e30)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        out = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        return (out ** 2).sum() + (lse * w).sum()

    gf = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(gf, gr):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(b32).max(), 1e-8)
        assert np.abs(a32 - b32).max() / scale < 1e-2


def test_compact_stat_layout_bitwise_on_hardware():
    """--attention_stat_layout=compact must be a PURE layout change on the
    real chip: the HIGHEST-precision selection matmul in _expand_stat_tile
    makes the expanded lse bit-identical to the replicated operand, so
    gradients match bitwise (not just within tolerance). Catches any
    Mosaic lowering drift in the expansion path that interpret mode
    cannot see."""
    rng = np.random.default_rng(31)
    q, k, v = rand_qkv(rng)

    def grads(layout):
        def loss(q, k, v):
            return (flash_attention(q, k, v, True, None, False, layout)
                    .astype(jnp.float32) ** 2).sum()
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    for a, b in zip(grads("replicated"), grads("compact")):
        assert bool(jnp.array_equal(a, b)), "compact layout changed gradients"


def test_kv_cached_decode_matches_full_forward_on_hardware():
    """Per-position logits parity of the cached decode path under real
    Mosaic/XLA compilation (the CPU tier pins the same contract in
    interpret-free f32; this exercises the bf16 compiled path)."""
    from nanosandbox_tpu.config import GPTConfig
    from nanosandbox_tpu.models.gpt import GPT, init_cache

    cfg = GPTConfig(n_layer=2, n_head=4, n_embd=256, block_size=256,
                    vocab_size=512, dropout=0.0, compute_dtype="bfloat16",
                    attention_impl="auto")
    model = GPT(cfg)
    params = model.init(jax.random.key(0),
                        jnp.zeros((1, 8), jnp.int32))["params"]
    idx = jax.random.randint(jax.random.key(1), (2, 48), 0, 512, jnp.int32)

    ref = jax.jit(lambda p, x: model.apply({"params": p}, x,
                                           deterministic=True))(params, idx)

    @jax.jit
    def cached(params, idx):
        cache = init_cache(cfg, 2, 48)
        logits, cache = model.apply({"params": params}, idx[:, :16],
                                    deterministic=True, cache=cache,
                                    cache_index=0)
        chunks = [logits]
        for i in range(16, 48):
            logits, cache = model.apply({"params": params}, idx[:, i:i + 1],
                                        deterministic=True, cache=cache,
                                        cache_index=i)
            chunks.append(logits)
        return jnp.concatenate(chunks, axis=1)

    got = cached(params, idx)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               atol=0.15, rtol=0.05)
    # Greedy agreement: random-weight logits at vocab 512 are nearly
    # uniform, so bf16 rounding between the two compiled programs can flip
    # argmax at genuine near-ties — require broad agreement, not equality
    # (the CPU tier pins exact greedy parity where both paths share one
    # numeric regime; trained checkpoints have real margins).
    agree = jnp.mean((jnp.argmax(got, -1) == jnp.argmax(ref, -1))
                     .astype(jnp.float32))
    assert float(agree) > 0.9, f"greedy agreement only {float(agree):.2%}"


# -- round-5 additions: on-chip coverage for what was CPU-only proven ------


def test_dropout_kernel_matches_masked_dense_reference_on_hardware():
    """The in-kernel dropout mask, COMPILED: flash_attention_dropout's
    output must equal a dense softmax masked with hash_dropout_keep_mask
    (the same hash the kernel inlines), proving the Mosaic-lowered mask
    derivation matches the jnp derivation bit-for-bit on hardware."""
    from nanosandbox_tpu.ops.attention import (flash_attention_dropout,
                                               hash_dropout_keep_mask)

    rng = np.random.default_rng(41)
    B, H, T, D = 2, 4, 512, 64
    q, k, v = rand_qkv(rng, B=B, H=H, T=T, D=D)
    seed = jnp.asarray([991], jnp.uint32)
    rate = 0.2

    out = jax.jit(lambda q, k, v: flash_attention_dropout(
        q, k, v, seed, True, None, rate, False))(q, k, v)

    sm = D ** -0.5
    s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm,
                   k.astype(jnp.float32))
    mask = jnp.tril(jnp.ones((T, T), dtype=bool))
    s = jnp.where(mask[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    keep = hash_dropout_keep_mask(seed, B, H, T, T, hash_seq_len=T,
                                  rate=rate)
    p = jnp.where(keep, p / (1.0 - rate), 0.0)
    ref = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=2e-2)


def test_dropout_mask_determinism_fwd_vs_bwd_on_hardware():
    """The backward kernels RECOMPUTE the keep-mask rather than saving it;
    on hardware, fwd+bwd with the same seed must be exactly reproducible
    call-to-call, and the gradients must match jax.grad of the dense
    masked reference (same mask => same math => same grads within bf16)."""
    from nanosandbox_tpu.ops.attention import (flash_attention_dropout,
                                               hash_dropout_keep_mask)

    rng = np.random.default_rng(42)
    B, H, T, D = 2, 4, 512, 64
    q, k, v = rand_qkv(rng, B=B, H=H, T=T, D=D)
    seed = jnp.asarray([4242], jnp.uint32)
    rate = 0.15

    def loss(q, k, v):
        return (flash_attention_dropout(q, k, v, seed, True, None, rate,
                                        False).astype(jnp.float32) ** 2).sum()

    g1 = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g1, g2):
        assert bool(jnp.array_equal(a, b)), "dropout grads not deterministic"

    sm = D ** -0.5
    keep = hash_dropout_keep_mask(seed, B, H, T, T, hash_seq_len=T,
                                  rate=rate)

    def ref_loss(q, k, v):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32) * sm,
                       k.astype(jnp.float32))
        mask = jnp.tril(jnp.ones((T, T), dtype=bool))
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        p = jnp.where(keep, p / (1.0 - rate), 0.0)
        o = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))
        return (o.astype(q.dtype).astype(jnp.float32) ** 2).sum()

    gr = jax.jit(jax.grad(ref_loss, argnums=(0, 1, 2)))(q, k, v)
    # 4e-2: the dense reference rounds to bf16 at different points than
    # the blockwise kernel (measured ~2.3% max-rel on v5e). A mask
    # DISAGREEMENT — the failure this test exists to catch — shows up as
    # O(1) relative error (an element kept on one side, dropped on the
    # other), far beyond this bound.
    for a, b in zip(g1, gr):
        a32, b32 = np.asarray(a, np.float32), np.asarray(b, np.float32)
        scale = max(np.abs(b32).max(), 1e-8)
        assert np.abs(a32 - b32).max() / scale < 4e-2


def test_dropout_rbg_seed_path_on_hardware():
    """The production dropout configs run rng_impl=rbg: deriving the
    kernel seed via jax.random.bits from an rbg key must compile and be
    deterministic per key on the hardware RNG path."""
    from nanosandbox_tpu.ops.attention import flash_attention_dropout

    rng = np.random.default_rng(43)
    q, k, v = rand_qkv(rng, T=512)

    @jax.jit
    def run(key, q, k, v):
        seed = jax.random.bits(key, (1,), jnp.uint32)
        return flash_attention_dropout(q, k, v, seed, True, None, 0.1,
                                       False)

    k1 = jax.random.key(7, impl="rbg")
    o1 = run(k1, q, k, v)
    o2 = run(k1, q, k, v)
    o3 = run(jax.random.key(8, impl="rbg"), q, k, v)
    assert bool(jnp.array_equal(o1, o2)), "rbg seed path not deterministic"
    assert not bool(jnp.array_equal(o1, o3)), "different rbg keys, same mask"


def test_lse_dropout_ring_block_on_hardware():
    """flash_attention_lse_dropout (the regularized ring block) compiles
    and matches flash_attention_dropout's output; its lse equals the
    UNMASKED flash_attention_lse's (dropout must not perturb the
    normalizer the ring merge relies on)."""
    from nanosandbox_tpu.ops.attention import (flash_attention_dropout,
                                               flash_attention_lse,
                                               flash_attention_lse_dropout)

    rng = np.random.default_rng(44)
    q, k, v = rand_qkv(rng, T=512)
    seed = jnp.asarray([17], jnp.uint32)

    out_d, lse_d = jax.jit(lambda q, k, v: flash_attention_lse_dropout(
        q, k, v, seed, True, None, 0.2, False))(q, k, v)
    out_ref = jax.jit(lambda q, k, v: flash_attention_dropout(
        q, k, v, seed, True, None, 0.2, False))(q, k, v)
    _, lse_ref = jax.jit(lambda q, k, v: flash_attention_lse(
        q, k, v, True, None, False))(q, k, v)
    assert bool(jnp.array_equal(out_d, out_ref))
    np.testing.assert_allclose(np.asarray(lse_d), np.asarray(lse_ref),
                               atol=1e-5)


def test_compact_stat_layout_grads_long_context_on_hardware():
    """The compact expansion at T=8192 (the long-context shape, where the
    stat tile is (64, 128) per q-block slice): grads must stay bitwise
    equal to the replicated layout under real Mosaic lowering."""
    rng = np.random.default_rng(45)
    q, k, v = rand_qkv(rng, B=1, H=2, T=8192, D=64)

    def grads(layout):
        def loss(q, k, v):
            return (flash_attention(q, k, v, True, None, False, layout)
                    .astype(jnp.float32) ** 2).sum()
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)

    for a, b in zip(grads("replicated"), grads("compact")):
        assert bool(jnp.array_equal(a, b)), (
            "compact layout changed gradients at T=8192")


def test_ring_dropout_single_device_degenerate_on_hardware():
    """Ring attention + dropout at sp=1 on the real chip: the degenerate
    ring (one local Mosaic flash-dropout block) must match the non-ring
    kernel exactly — proving the regularized ring path lowers on
    hardware. (Multi-device sp parity is CPU-tier; one chip here.)"""
    from nanosandbox_tpu.ops.attention import flash_attention_dropout
    from nanosandbox_tpu.ops.ring_attention import ring_attention_sharded
    from nanosandbox_tpu.parallel.mesh import make_mesh

    rng = np.random.default_rng(46)
    q, k, v = rand_qkv(rng, T=512)
    seed = jnp.asarray([5], jnp.uint32)
    mesh = make_mesh(mesh_dp=1, devices=jax.devices()[:1])
    out = jax.jit(lambda q, k, v: ring_attention_sharded(
        q, k, v, mesh=mesh, dropout_rate=0.2, dropout_seed=seed))(q, k, v)
    ref = jax.jit(lambda q, k, v: flash_attention_dropout(
        q, k, v, seed, True, None, 0.2, False))(q, k, v)
    np.testing.assert_allclose(np.asarray(out, np.float32),
                               np.asarray(ref, np.float32), atol=2e-2)


@pytest.mark.parametrize("T", [16384, 32768])
def test_flash_bwd_compiles_at_long_context(T):
    """The single-shard long-context envelope (round-5): the backward
    kernels stream full-T q/do/o blocks, so VMEM footprint scales with T
    — at Mosaic's default budget the backward stopped COMPILING between
    8k and 16k. The raised vmem_limit_bytes in _tpu_params extends the
    envelope through 32k; this pins it (AOT compile only, cheap)."""
    x = jax.ShapeDtypeStruct((1, 12, T, 64), jnp.bfloat16)

    def loss(q):
        return flash_attention(q, q, q, True, None, False,
                               "compact").astype(jnp.float32).sum()

    jax.jit(jax.grad(loss)).lower(x).compile()
