// Native batch gather for the memmap data loader.
//
// The reference's data path (nanoGPT's get_batch, exercised via
// /root/reference/notebooks/colab_nanoGPT_companion.ipynb:56) samples
// random-offset (block_size+1)-token windows from a uint16 memmap on the
// host CPU every step. On TPU VMs the host side must keep up with the chip,
// so this gather is implemented natively: OpenMP-parallel strided copies
// from the memmap into a contiguous pinned staging buffer, plus a
// xorshift128+ offset sampler so offset generation does not round-trip
// through Python either.
//
// Exposed via ctypes (no pybind11 in the image); see
// nanosandbox_tpu/utils/native.py for the loader and pure-numpy fallback.

#include <cstdint>
#include <cstring>

extern "C" {

// Copy B windows of (T+1) uint16 tokens starting at offsets[b] into out
// (shape [B, T+1], contiguous).
void gather_windows_u16(const uint16_t* data, int64_t n_tokens,
                        const int64_t* offsets, int64_t batch, int64_t width,
                        uint16_t* out) {
#pragma omp parallel for schedule(static)
  for (int64_t b = 0; b < batch; ++b) {
    int64_t off = offsets[b];
    if (off < 0) off = 0;
    if (off + width > n_tokens) off = n_tokens - width;
    std::memcpy(out + b * width, data + off,
                static_cast<size_t>(width) * sizeof(uint16_t));
  }
}

// xorshift128+ offset sampler: fills offsets[0..batch) with values in
// [0, n_tokens - width]. Deterministic in (seed, stream).
void sample_offsets(uint64_t seed, uint64_t stream, int64_t n_tokens,
                    int64_t width, int64_t batch, int64_t* offsets) {
  // splitmix64 to seed the xorshift state from (seed, stream).
  uint64_t z = seed + 0x9E3779B97F4A7C15ULL * (stream + 1);
  auto splitmix = [&z]() {
    z += 0x9E3779B97F4A7C15ULL;
    uint64_t x = z;
    x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ULL;
    x = (x ^ (x >> 27)) * 0x94D049BB133111EBULL;
    return x ^ (x >> 31);
  };
  uint64_t s0 = splitmix(), s1 = splitmix();
  const uint64_t range = static_cast<uint64_t>(n_tokens - width + 1);
  for (int64_t b = 0; b < batch; ++b) {
    uint64_t x = s0, y = s1;
    s0 = y;
    x ^= x << 23;
    s1 = x ^ y ^ (x >> 17) ^ (y >> 26);
    offsets[b] = static_cast<int64_t>((s1 + y) % range);
  }
}

}  // extern "C"
