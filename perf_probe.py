import os, time, tempfile, sys
import numpy as np
import jax

from nanosandbox_tpu.config import TrainConfig
from nanosandbox_tpu.train import Trainer
from nanosandbox_tpu.data.prepare import prepare_char_dataset

impl = sys.argv[1] if len(sys.argv) > 1 else "pallas"
bs = int(sys.argv[2]) if len(sys.argv) > 2 else 32
remat = len(sys.argv) > 3 and sys.argv[3] == "remat"

tmp = tempfile.mkdtemp()
data = os.path.join(tmp, "data")
prepare_char_dataset(os.path.join(data, "shakespeare_char"),
                     allow_synthetic=True, url="http://x.localhost/no")

cfg = TrainConfig(out_dir=os.path.join(tmp, "o"), data_dir=data,
                  dataset="shakespeare_char", vocab_size=50304,
                  n_layer=12, n_head=12, n_embd=768, block_size=1024,
                  batch_size=bs, max_iters=0, eval_interval=0,
                  dropout=0.0, compute_dtype="bfloat16",
                  attention_impl=impl, remat=remat, tensorboard=False)
t = Trainer(cfg)
state = t.init_state()
step, _ = t.compiled_steps()
xb, yb = t.dataset.sample_batch("train", 0, cfg.sequences_per_iter,
                                cfg.block_size, seed=0)
xg, yg = t.to_global(xb), t.to_global(yb)
rng = jax.random.key(0)

for _ in range(3):
    state, m = step(state, xg, yg, rng)
print("warm loss", float(m["loss"]))

N = 20
t0 = time.perf_counter()
for _ in range(N):
    state, m = step(state, xg, yg, rng)
_ = float(m["loss"])  # single sync at end
dt = (time.perf_counter() - t0) / N
toks = cfg.tokens_per_iter / dt
mfu = t.flops_per_iter() / dt / t.peak_flops()
print(f"impl={impl} bs={bs} remat={remat}: {dt*1000:.1f} ms/step, "
      f"{toks:,.0f} tok/s, mfu {mfu*100:.1f}%")
