#!/usr/bin/env bash
# Container entrypoint: derive the multi-host JAX process identity from the
# Kubernetes StatefulSet pod identity, then exec the trainer.
#
# This is the TPU-native successor of the reference's container/entrypoint.sh
# (described at /root/reference/README.md:21,102: "Sets NODE_RANK from
# StatefulSet ordinal for multi-Pod DDP"). The mechanism survives with new
# names: instead of exporting NODE_RANK/MASTER_ADDR/MASTER_PORT for torchrun,
# we export PROCESS_ID / NUM_PROCESSES / COORDINATOR_ADDRESS for
# jax.distributed.initialize (nanosandbox_tpu/parallel/distributed.py).
# Every pod runs the SAME program (SPMD) — there is no launcher forking
# worker processes the way torchrun did.
#
#   PROCESS_ID           <- trailing ordinal of the pod hostname
#                           (train-multipod-2 -> 2); 0 if no ordinal.
#   NUM_PROCESSES        <- $NUM_PROCESSES (set by the StatefulSet manifest
#                           to spec.replicas); defaults to 1 (single-pod).
#   COORDINATOR_ADDRESS  <- pod-0 of the StatefulSet via the headless
#                           Service DNS (reference README.md:120 used the
#                           same DNS name as MASTER_ADDR).
#
# DRY_RUN=1 prints the derived environment instead of exec'ing — used by
# tests/test_deploy.py to pin the rank-derivation contract.
set -euo pipefail

STATEFULSET_NAME="${STATEFULSET_NAME:-train-multipod}"
HEADLESS_SERVICE="${HEADLESS_SERVICE:-train-mp-headless}"
COORDINATOR_PORT="${COORDINATOR_PORT:-12355}"
NUM_PROCESSES="${NUM_PROCESSES:-1}"

hostname_value="${HOSTNAME:-$(hostname)}"

# Trailing "-<digits>" of the hostname is the StatefulSet ordinal.
if [[ "${PROCESS_ID:-}" == "" ]]; then
  if [[ "$hostname_value" =~ -([0-9]+)$ ]]; then
    PROCESS_ID="${BASH_REMATCH[1]}"
  else
    PROCESS_ID=0
  fi
fi

# Rendezvous point: pod 0's stable DNS name under the headless Service.
# Within-namespace short form resolves via cluster DNS search domains.
if [[ "${COORDINATOR_ADDRESS:-}" == "" ]]; then
  if (( NUM_PROCESSES > 1 )); then
    COORDINATOR_ADDRESS="${STATEFULSET_NAME}-0.${HEADLESS_SERVICE}:${COORDINATOR_PORT}"
  else
    COORDINATOR_ADDRESS=""
  fi
fi

export PROCESS_ID NUM_PROCESSES COORDINATOR_ADDRESS

if [[ "${DRY_RUN:-0}" == "1" ]]; then
  echo "PROCESS_ID=${PROCESS_ID}"
  echo "NUM_PROCESSES=${NUM_PROCESSES}"
  echo "COORDINATOR_ADDRESS=${COORDINATOR_ADDRESS}"
  exit 0
fi

if (( $# == 0 )); then
  set -- python -m nanosandbox_tpu.train
fi

echo "[entrypoint] host=${hostname_value} process_id=${PROCESS_ID}" \
     "num_processes=${NUM_PROCESSES} coordinator=${COORDINATOR_ADDRESS:-<none>}"
exec "$@"
